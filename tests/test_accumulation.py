"""Gradient accumulation + bucketed/hierarchical boundary reduction.

The Horovod-parity accumulation contract, trainer-native: with
``DistributedOptimizer(backward_passes_per_step=K)`` the Trainer runs K
microbatch forward/backward passes inside ONE compiled step — local grads
accumulate in f32 on device — with exactly one cross-worker reduction and
one optimizer apply per K passes. The boundary reduction is bucket-fused
(Horovod tensor-fusion semantics, `collectives.flatten_buckets`) and, on a
multi-slice mesh, hierarchical: ICI sub-axis in full precision, DCN
sub-axis in the compression dtype (`collectives.hierarchical_psum`,
EQuARX-style DCN-only quantization).

Proof obligations (the PR's acceptance criteria):
* K-microbatch loss trajectory ≡ one K·B-batch run (rel 1e-4).
* Exactly one gradient reduction per OPTIMIZER step in the compiled step's
  collectives, independent of K.
* Bucketed reduction issues ≤ ceil(total_bytes/bucket_bytes) + n_dtypes
  collectives; round-trips arbitrary pytrees exactly.
* Hierarchical == flat psum on a fake 2-slice topology.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.analysis import hlo_audit
from horovod_tpu.analysis.step_probe import lowered_step_text
from horovod_tpu.parallel import collectives, mesh as mesh_lib
from horovod_tpu.training.optimizer import accumulation_spec


class MnistConvNet(nn.Module):
    """The MNIST config's 2-conv CNN (tensorflow2_keras_mnist.py:43-52)
    minus dropout: the trajectory-equivalence bound is rel 1e-4, and
    dropout masks are drawn per microbatch on the accumulating path vs per
    global batch on the SPMD path — real (intended) sampling divergence
    that would drown the numeric property under test."""

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(jnp.float32)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(10)(x)


class Probe(nn.Module):
    """Tiny deterministic classifier for the cheap structural tests."""

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        return nn.Dense(10)(nn.relu(nn.Dense(32)(x)))


def _mnist_data(n=256, seed=0):
    from horovod_tpu.data.datasets import _synth_mnist_split

    x, y = _synth_mnist_split(n, seed=seed)
    return (x[..., None] / 255.0).astype(np.float32), y.astype(np.int32)


def _probe_data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 8, 8, 1).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.int32)
    return x, y


def _trainer(module, k=1, compression="none", bucket_bytes=None, seed=3,
             **opt_kw):
    tx = hvt.DistributedOptimizer(
        optax.adam(1e-3), backward_passes_per_step=k,
        compression=compression, **opt_kw,
    )
    return hvt.Trainer(module, tx, seed=seed, bucket_bytes=bucket_bytes)


# The lowered-step plumbing and the gradient-traffic discrimination are
# `analysis.step_probe.lowered_step_text` + `analysis.hlo_audit` since
# PR 9 — one implementation, shared with bench.py and `hvt-audit`.


class TestTrajectoryEquivalence:
    def test_k4_microbatches_match_single_kb_batch(self):
        """The acceptance bound: K=4 microbatches of per-chip batch B,
        averaged (average_aggregated_gradients=True), must trace the SAME
        loss trajectory as one K·B-batch run within rel 1e-4 on the MNIST
        config — same data order (shuffle_buffer=1), same seed, same
        optimizer."""
        x, y = _mnist_data()
        acc = _trainer(
            MnistConvNet(), k=4, average_aggregated_gradients=True
        )
        h_acc = acc.fit(
            x=x, y=y, batch_size=1, epochs=2, steps_per_epoch=8,
            shuffle_buffer=1, verbose=0,
        )
        plain = _trainer(MnistConvNet(), k=1)
        h_plain = plain.fit(
            x=x, y=y, batch_size=4, epochs=2, steps_per_epoch=8,
            shuffle_buffer=1, verbose=0,
        )
        for a, b in zip(h_acc, h_plain):
            assert a["loss"] == pytest.approx(b["loss"], rel=1e-4)
        # Secondary sanity on the weights themselves: Adam divides by
        # sqrt(v), amplifying f32 grad-sum noise on near-zero params, so
        # the bound here is looser than the loss-trajectory acceptance.
        for pa, pb in zip(
            jax.tree.leaves(jax.device_get(acc.state.params)),
            jax.tree.leaves(jax.device_get(plain.state.params)),
        ):
            np.testing.assert_allclose(pa, pb, rtol=2e-3, atol=5e-4)

    def test_sum_semantics_is_horovod_default(self):
        """Without average_aggregated_gradients the K grads SUM: one SGD
        accumulation cycle moves the weights exactly K times as far as the
        averaged cycle."""
        x, y = _probe_data(64)

        def one_cycle(**kw):
            t = hvt.Trainer(
                Probe(),
                hvt.DistributedOptimizer(
                    optax.sgd(0.1), backward_passes_per_step=4, **kw
                ),
                seed=3,
            )
            t.fit(x=x, y=y, batch_size=1, epochs=1, steps_per_epoch=1,
                  shuffle_buffer=1, verbose=0)
            return jax.device_get(jax.tree.leaves(t.state.params)[0])

        init = hvt.Trainer(
            Probe(), hvt.DistributedOptimizer(optax.sgd(0.1)), seed=3
        )
        init.build(x[:8])
        w0 = jax.device_get(jax.tree.leaves(init.state.params)[0])
        w_sum = one_cycle()
        w_mean = one_cycle(average_aggregated_gradients=True)
        np.testing.assert_allclose(
            w_sum - w0, 4.0 * (w_mean - w0), rtol=1e-5, atol=1e-7
        )

    def test_device_cached_path_accumulates(self):
        """fit(cache='device') with K: each scanned optimizer step consumes
        K·B examples per shard and the run still learns."""
        x, y = _probe_data(512)
        t = _trainer(Probe(), k=4, average_aggregated_gradients=True)
        hist = t.fit(
            x=x, y=y, batch_size=2, epochs=4, cache="device", verbose=0
        )
        assert hist[-1]["loss"] < hist[0]["loss"]

    def test_steps_per_execution_composes(self):
        """spe > 1 (scan-fused executions) stacks [spe, K, ...] and must
        match the unfused accumulating run parameter-for-parameter."""
        x, y = _probe_data()
        a = _trainer(Probe(), k=2, average_aggregated_gradients=True)
        a.fit(x=x, y=y, batch_size=2, epochs=2, steps_per_epoch=6,
              shuffle_buffer=1, verbose=0)
        b = hvt.Trainer(
            Probe(),
            hvt.DistributedOptimizer(
                optax.adam(1e-3), backward_passes_per_step=2,
                average_aggregated_gradients=True,
            ),
            seed=3, steps_per_execution=3,
        )
        b.fit(x=x, y=y, batch_size=2, epochs=2, steps_per_epoch=6,
              shuffle_buffer=1, verbose=0)
        for pa, pb in zip(
            jax.tree.leaves(jax.device_get(a.state.params)),
            jax.tree.leaves(jax.device_get(b.state.params)),
        ):
            np.testing.assert_allclose(pa, pb, rtol=1e-6, atol=1e-7)


class TestOneReductionPerStep:
    def test_single_gradient_reduction_independent_of_k(self):
        """THE acceptance assertion: the compiled optimizer step carries
        exactly ONE gradient-shaped collective — the bucketed boundary
        reduction — no matter how many microbatch passes scan inside it
        (default bucket bytes hold the whole Probe gradient)."""
        x, y = _probe_data()
        for k in (2, 4):
            tr = _trainer(Probe(), k=k)
            hlo_audit.assert_program(
                lowered_step_text(tr, x, y, k), "one-reduction"
            )

    def test_implicit_spmd_path_untouched(self):
        """Control: the default K=1, no-compression step still has NO
        explicit collective (XLA places the reduction at partitioning) —
        accumulation machinery must not leak into the default path."""
        x, y = _probe_data()
        tr = _trainer(Probe(), k=1)
        hlo_audit.assert_program(
            lowered_step_text(tr, x, y, 1), "no-collectives"
        )

    def test_compression_composes_on_boundary_only(self):
        """compression='bf16' + K=4: every gradient-shaped reduction is
        bf16 (the single boundary reduction compressed), none f32 — the
        16-bit cost is paid once per K passes, not per microbatch."""
        x, y = _probe_data()
        tr = _trainer(Probe(), k=4, compression="bf16")
        hlo_audit.assert_program(
            lowered_step_text(tr, x, y, 4), "one-reduction,wire=bf16"
        )

    def test_bucket_count_tracks_bucket_bytes(self):
        """With bucket_bytes forcing multiple buckets, the reduction count
        equals the bucket count and respects the ceil(total/bytes) +
        n_dtypes bound."""
        x, y = _probe_data()
        # Probe grads (f32): 64·32 + 32 + 32·10 + 10 = 2410 params.
        total = (64 * 32 + 32 + 32 * 10 + 10) * 4
        bucket_bytes = 4096
        tr = _trainer(Probe(), k=2, bucket_bytes=bucket_bytes)
        expected = -(-total // bucket_bytes)  # ceil; one dtype → 3
        assert expected == 3
        hlo_audit.assert_program(
            lowered_step_text(tr, x, y, 2), f"reductions={expected}"
        )


class TestBucketRoundTrip:
    @pytest.mark.parametrize("bucket_bytes", [1, 64, 4096, 1 << 26])
    def test_arbitrary_pytree_round_trips(self, bucket_bytes):
        """Property: flatten→unflatten is the identity for mixed-dtype
        pytrees with 0-d leaves, any bucket size."""
        rng = np.random.RandomState(0)
        tree = {
            "conv": {"kernel": rng.randn(3, 3, 4, 8).astype(np.float32),
                     "bias": rng.randn(8).astype(np.float32)},
            "scale": np.float32(rng.randn()),          # 0-d leaf
            "table": rng.randn(16, 5).astype(np.float16),
            "counts": rng.randint(0, 9, (7,)).astype(np.int32),
            "step": np.int32(42),                      # 0-d int leaf
            "list": [rng.randn(2, 2).astype(np.float32),
                     rng.randn(5).astype(np.float16)],
        }
        buckets, spec = collectives.flatten_buckets(tree, bucket_bytes)
        out = collectives.unflatten_buckets(buckets, spec)
        jax.tree.map(
            lambda a, b: (
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                # dtype and shape restored exactly
                self_check(a, b),
            ),
            tree, out,
        )

    def test_bucket_count_bound(self):
        rng = np.random.RandomState(1)
        tree = {
            "a": rng.randn(1000).astype(np.float32),   # 4000 B
            "b": rng.randn(300).astype(np.float32),    # 1200 B
            "c": rng.randn(100).astype(np.float16),    # 200 B
        }
        bucket_bytes = 1024
        buckets, _ = collectives.flatten_buckets(tree, bucket_bytes)
        total = 4000 + 1200 + 200
        n_dtypes = 2
        assert len(buckets) <= -(-total // bucket_bytes) + n_dtypes - 1 + 1
        # exact: ceil(5200/1024)=6 f32 buckets + 1 f16 bucket
        assert len(buckets) == 7

    def test_dtype_homogeneous(self):
        tree = {"f": np.ones(4, np.float32), "h": np.ones(4, np.float16),
                "i": np.ones(4, np.int32)}
        buckets, _ = collectives.flatten_buckets(tree, 1 << 20)
        assert sorted(str(b.dtype) for b in buckets) == [
            "float16", "float32", "int32"
        ]

    def test_empty_tree(self):
        buckets, spec = collectives.flatten_buckets({}, 1024)
        assert buckets == []
        assert collectives.unflatten_buckets(buckets, spec) == {}

    def test_bad_bucket_bytes(self):
        with pytest.raises(ValueError, match="positive"):
            collectives.flatten_buckets({"a": np.ones(2)}, 0)

    def test_mismatched_spec_is_loud(self):
        buckets, spec = collectives.flatten_buckets(
            {"a": np.ones(4, np.float32)}, 1 << 20
        )
        with pytest.raises(ValueError, match="do not match"):
            collectives.unflatten_buckets(buckets + [jnp.ones(2)], spec)


def self_check(a, b):
    assert np.asarray(a).shape == np.asarray(b).shape
    assert np.asarray(a).dtype == np.asarray(b).dtype


class TestHierarchicalReduction:
    """hierarchical_psum / reduce_gradients on a fake multi-slice topology:
    the 8-device test mesh's data axis factored (dcn outer, ici inner)."""

    def _run(self, fn, x):
        from horovod_tpu import compat

        mesh = mesh_lib.data_parallel_mesh()
        P = jax.sharding.PartitionSpec
        return jax.jit(
            compat.shard_map(
                fn, mesh=mesh,
                in_specs=(P(("data", "fsdp")),),
                out_specs=P(("data", "fsdp")),
                check_vma=False,
            )
        )(x)

    @pytest.mark.parametrize("dcn", [2, 4, 8])
    def test_matches_flat_psum_in_f32(self, dcn):
        """Acceptance: the two-hop reduction == the flat psum on a fake
        multi-slice factoring. Sum associativity makes the two exact in
        real arithmetic; in f32 only the ADDITION ORDER differs (partials
        within a slice first), so the bound is float-rounding-tight, far
        under any wire-compression effect."""
        hvt.init()
        x = jnp.asarray(
            np.random.RandomState(0).randn(8, 16).astype(np.float32)
        )

        def hier(v):
            return collectives.hierarchical_psum(
                v, "data", dcn, extra_axes=("fsdp",)
            )

        def flat(v):
            return jax.lax.psum(v, ("data", "fsdp"))

        np.testing.assert_allclose(
            np.asarray(self._run(hier, x)), np.asarray(self._run(flat, x)),
            rtol=1e-6, atol=1e-6,
        )

    def test_wire_dtype_compresses_dcn_hop_only(self):
        """bf16 wire: the result tracks the flat f32 sum to bf16 tolerance
        (only the already-ICI-reduced partials cross the cast), and the
        lowered text shows exactly one bf16 all_reduce (the DCN hop) and
        one non-bf16 (the ICI hop)."""
        hvt.init()
        from horovod_tpu import compat

        mesh = mesh_lib.data_parallel_mesh()
        P = jax.sharding.PartitionSpec

        def hier(v):
            return collectives.hierarchical_psum(
                v, "data", 2, extra_axes=("fsdp",),
                wire_dtype=jnp.bfloat16,
            )

        f = jax.jit(compat.shard_map(
            hier, mesh=mesh, in_specs=(P(("data", "fsdp")),),
            out_specs=P(("data", "fsdp")), check_vma=False,
        ))
        x = jnp.asarray(
            np.random.RandomState(1).rand(8, 64).astype(np.float32)
        )
        got = np.asarray(f(x))
        want = np.broadcast_to(
            np.asarray(x).sum(0, keepdims=True), x.shape
        )
        np.testing.assert_allclose(got, want, rtol=2e-2)
        reduces = [
            op for op in hlo_audit.collective_ops(f.lower(x).as_text())
            if op.kind == "all-reduce"
        ]
        bf16 = [op for op in reduces if op.dtype == "bf16"]
        assert len(bf16) == 1, [op.describe() for op in reduces]
        assert len(reduces) >= 2  # the full-precision ICI hop is separate

    def test_bad_dcn_factor_is_loud(self):
        hvt.init()
        x = jnp.ones((8, 4), jnp.float32)

        def hier(v):
            return collectives.hierarchical_psum(v, "data", 3)

        with pytest.raises(ValueError, match="does not divide"):
            self._run(hier, x)

    def test_trainer_hierarchical_trajectory_matches_flat(self, monkeypatch):
        """End to end: HVT_DCN_FACTOR=2 (the fake 2-slice topology knob)
        routes the accumulating trainer's boundary reduction through the
        two-hop path; with an f32 wire the trajectory is identical to the
        single-slice run."""
        x, y = _probe_data()
        flat = _trainer(Probe(), k=2, average_aggregated_gradients=True)
        flat.fit(x=x, y=y, batch_size=2, epochs=1, steps_per_epoch=6,
                 shuffle_buffer=1, verbose=0)
        monkeypatch.setenv("HVT_DCN_FACTOR", "2")
        hier = _trainer(Probe(), k=2, average_aggregated_gradients=True)
        assert hier._dcn == 2
        hier.fit(x=x, y=y, batch_size=2, epochs=1, steps_per_epoch=6,
                 shuffle_buffer=1, verbose=0)
        for pa, pb in zip(
            jax.tree.leaves(jax.device_get(flat.state.params)),
            jax.tree.leaves(jax.device_get(hier.state.params)),
        ):
            np.testing.assert_allclose(pa, pb, rtol=1e-6, atol=1e-7)


class TestDcnFactor:
    def _fake_mesh(self, slice_ids):
        """Duck-typed mesh: an 8-long data axis whose device slice_index
        layout is given (dcn_factor only touches shape/axis_names/
        devices)."""
        import types

        devs = np.array(
            [types.SimpleNamespace(slice_index=s) for s in slice_ids]
        ).reshape(8, 1, 1, 1, 1, 1)
        return types.SimpleNamespace(
            shape={"data": 8}, axis_names=mesh_lib.AXES, devices=devs
        )

    def test_hybrid_outer_blocks_detected(self):
        m = self._fake_mesh([0, 0, 0, 0, 1, 1, 1, 1])
        assert mesh_lib.dcn_factor(m) == 2
        m4 = self._fake_mesh([0, 0, 1, 1, 2, 2, 3, 3])
        assert mesh_lib.dcn_factor(m4) == 4

    def test_non_hybrid_layouts_fall_back_flat(self):
        # interleaved (not outer blocks) and repeating ids: hierarchy
        # would be WRONG, so the factor must be 1
        assert mesh_lib.dcn_factor(
            self._fake_mesh([0, 1, 0, 1, 0, 1, 0, 1])
        ) == 1
        assert mesh_lib.dcn_factor(
            self._fake_mesh([0, 0, 0, 1, 1, 1, 0, 0])
        ) == 1

    def test_single_slice_is_one(self):
        hvt.init()
        assert mesh_lib.dcn_factor(mesh_lib.data_parallel_mesh()) == 1

    def test_env_override_validated(self, monkeypatch):
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        monkeypatch.setenv("HVT_DCN_FACTOR", "2")
        assert mesh_lib.dcn_factor(mesh) == 2
        monkeypatch.setenv("HVT_DCN_FACTOR", "3")
        with pytest.raises(ValueError, match="divide"):
            mesh_lib.dcn_factor(mesh)


class TestCompositionGuards:
    def test_shard_update_composes(self):
        """The PR 4 fail-fast is LIFTED: shard_update (ZeRO-1) now
        composes with backward_passes_per_step — the boundary reduction
        lowers into the sharded update's layout
        (reduce_gradients(scatter=dp); full matrix in
        tests/test_zero1_compose.py)."""
        tr = hvt.Trainer(
            Probe(),
            hvt.DistributedOptimizer(
                optax.adam(1e-3), backward_passes_per_step=2
            ),
            shard_update=True,
        )
        assert tr._scatter == tr.mesh.shape["data"]

    def test_param_specs_rejected(self):
        with pytest.raises(ValueError, match="replicated"):
            hvt.Trainer(
                Probe(),
                hvt.DistributedOptimizer(
                    optax.adam(1e-3), backward_passes_per_step=2
                ),
                param_specs={},
            )

    def test_batch_specs_rejected(self):
        P = jax.sharding.PartitionSpec
        with pytest.raises(ValueError, match="batch_specs"):
            hvt.Trainer(
                Probe(),
                hvt.DistributedOptimizer(
                    optax.adam(1e-3), backward_passes_per_step=2
                ),
                batch_specs=(P("data"), P("data")),
            )

    def test_trainer_swaps_multisteps_for_inner(self):
        """The Trainer path must NOT carry MultiSteps state (a params-sized
        accumulator in opt_state); standalone use keeps it."""
        tx = hvt.DistributedOptimizer(
            optax.adam(1e-3), backward_passes_per_step=4
        )
        spec = accumulation_spec(tx)
        assert spec is not None and spec.k == 4 and spec.average is False
        tr = hvt.Trainer(Probe(), tx)
        assert tr.tx is spec.inner
        x, _ = _probe_data(16)
        tr.build(x[:8])
        # MultiSteps state exposes mini_step/gradient_step; the trainer's
        # opt_state must be the bare inner optimizer's.
        names = {type(s).__name__ for s in jax.tree.leaves(
            tr.state.opt_state, is_leaf=lambda s: hasattr(s, "mini_step")
        )}
        assert not any("MultiSteps" in n for n in names)

    def test_axis_name_mode_keeps_multisteps_semantics(self):
        """Outside the Trainer (explicit axis_name), the transformation
        stays a MultiSteps wrap: K-1 zero updates, then the aggregate."""
        tx = hvt.DistributedOptimizer(
            optax.sgd(1.0), axis_name=None, backward_passes_per_step=2
        )
        params = {"w": jnp.ones(3)}
        state = tx.init(params)
        g = {"w": jnp.ones(3)}
        up1, state = tx.update(g, state, params)
        assert float(jnp.abs(up1["w"]).sum()) == 0.0  # pass 1: accumulate
        up2, state = tx.update(g, state, params)
        assert float(jnp.abs(up2["w"]).sum()) > 0.0  # pass 2: apply

    def test_steps_per_epoch_counts_optimizer_steps(self):
        """Default steps_per_epoch divides by K: 64 examples / (global
        batch 16 × K 2) = 2 optimizer steps per epoch."""
        x, y = _probe_data(64)
        t = _trainer(Probe(), k=2)
        hist = t.fit(x=x, y=y, batch_size=2, epochs=1, shuffle_buffer=1,
                     verbose=0)
        assert len(hist) == 1
        assert int(jax.device_get(t.state.step)) == 2
