"""Generation serving bundles (serving.py + launch/serve.py /v1/generate):
export the compiled decode loop, reload it, and serve it over real HTTP —
generations must match `make_generate_fn` locally, tokenizer round-trip
included. The reference's serving contract (mnist_keras.py:126-140's
export-so-it-can-be-served) applied to the flagship LM."""

import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.data.tokenizer import ByteBPETokenizer
from horovod_tpu.launch.serve import make_server
from horovod_tpu.models.decoding import make_generate_fn
from horovod_tpu.models.transformer import TransformerLM

# Compile-heavy end-to-end tier (suite diet: default run stays fast).
pytestmark = pytest.mark.slow

BATCH, T0, NEW = 2, 8, 6
CORPUS = [
    "the ring rotates the keys",
    "the keys rotate the ring",
    "rings and keys and rings",
] * 4


@pytest.fixture(scope="module")
def tok():
    return ByteBPETokenizer.train(CORPUS, vocab_size=280)


@pytest.fixture(scope="module")
def lm(tok):
    model = TransformerLM(
        vocab_size=tok.vocab_size, d_model=32, n_heads=4, n_layers=2,
        dropout=0.0,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((BATCH, T0), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def bundle_dir(tmp_path_factory, lm, tok):
    model, params = lm
    return serving.export_generate(
        str(tmp_path_factory.mktemp("genexport")),
        model,
        params,
        batch_size=BATCH,
        prompt_len=T0,
        max_new_tokens=NEW,
        tokenizer=tok,
        timestamp="19700101-000000",
    )


@pytest.fixture(scope="module")
def server(bundle_dir):
    srv = make_server(bundle_dir, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


def _post(server, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.server_address[1]}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


def _post_raw(server, path, payload):
    try:
        return _post(server, path, payload)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _local_ragged(model, params, prompts):
    """make_generate_fn ground truth for a list of prompt rows."""
    fn = make_generate_fn(model, max_new_tokens=NEW, include_prompt=False)
    padded = np.zeros((len(prompts), T0), np.int32)
    lens = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        padded[i, : len(p)] = p
        lens[i] = len(p)
    return np.asarray(
        fn(params, jnp.asarray(padded), jax.random.PRNGKey(0),
           jnp.asarray(lens))
    )


class TestBundle:
    def test_export_reload_matches_local(self, bundle_dir, lm):
        model, params = lm
        b = serving.load_generate(bundle_dir)
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
        got = b.generate_tokens(prompts, seed=0)
        want = _local_ragged(model, params, prompts)
        for i in range(len(prompts)):
            np.testing.assert_array_equal(got[i], want[i], err_msg=f"row {i}")

    def test_request_larger_than_compiled_batch_splits(self, bundle_dir, lm):
        model, params = lm
        b = serving.load_generate(bundle_dir)
        prompts = [[i + 1, i + 2, i + 3] for i in range(2 * BATCH + 1)]
        got = b.generate_tokens(prompts)
        want = _local_ragged(model, params, prompts)
        assert len(got) == len(prompts)
        for i in range(len(prompts)):
            np.testing.assert_array_equal(got[i], want[i], err_msg=f"row {i}")

    def test_prompt_too_long_guided_error(self, bundle_dir):
        b = serving.load_generate(bundle_dir)
        with pytest.raises(ValueError, match="1..8"):
            b.generate_tokens([[1] * (T0 + 1)])

    def test_text_roundtrip(self, bundle_dir, lm, tok):
        model, params = lm
        b = serving.load_generate(bundle_dir)
        texts = ["the ring", "keys"]
        out = b.generate_text(texts, seed=0)
        want = _local_ragged(
            model, params, [tok.encode(t) for t in texts]
        )
        assert out == [tok.decode([int(t) for t in row]) for row in want]


class TestHTTP:
    def test_healthz_reports_generate_kind(self, server):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.server_address[1]}/healthz"
        ) as r:
            body = json.loads(r.read())
        assert body["kind"] == "generate"
        assert body["signature"]["meta"]["max_new_tokens"] == NEW

    def test_generate_tokens_match_local(self, server, lm):
        model, params = lm
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [7, 7]]
        status, body = _post(server, "/v1/generate", {"prompt": prompts})
        assert status == 200
        want = _local_ragged(model, params, prompts)
        for i in range(len(prompts)):
            np.testing.assert_array_equal(
                body["tokens"][i], want[i], err_msg=f"row {i}"
            )

    def test_generate_text_roundtrip(self, server, lm, tok):
        model, params = lm
        texts = ["the keys", "rings and"]
        status, body = _post(server, "/v1/generate", {"text": texts})
        assert status == 200
        want = _local_ragged(model, params, [tok.encode(t) for t in texts])
        assert body["text"] == [
            tok.decode([int(t) for t in row]) for row in want
        ]
        for i, row in enumerate(want):
            np.testing.assert_array_equal(body["tokens"][i], row)

    def test_predict_route_rejected_with_hint(self, server):
        status, body = _post_raw(
            server, "/v1/predict", {"input": [[1, 2, 3]]}
        )
        assert status == 404
        assert "generate" in body["error"]

    def test_bad_prompt_is_400_json(self, server):
        status, body = _post_raw(
            server, "/v1/generate", {"prompt": [[1] * (T0 + 5)]}
        )
        assert status == 400
        assert "1..8" in body["error"]

    def test_text_and_prompt_together_rejected(self, server):
        status, body = _post_raw(
            server, "/v1/generate", {"text": ["a"], "prompt": [[1]]}
        )
        assert status == 400


class TestSampledBundle:
    def test_sampled_deterministic_per_seed_and_matches_local(
        self, tmp_path, lm, tok
    ):
        model, params = lm
        out = serving.export_generate(
            str(tmp_path), model, params,
            batch_size=2, prompt_len=T0, max_new_tokens=NEW,
            temperature=0.8, top_k=8, tokenizer=tok,
        )
        b = serving.load_generate(out)
        prompts = [[3, 1, 4], [9, 2, 6, 5]]
        one = b.generate_tokens(prompts, seed=7)
        two = b.generate_tokens(prompts, seed=7)
        assert one == two
        fn = make_generate_fn(
            model, max_new_tokens=NEW, temperature=0.8, top_k=8,
            include_prompt=False,
        )
        padded = np.zeros((2, T0), np.int32)
        padded[0, :3] = prompts[0]
        padded[1, :4] = prompts[1]
        want = np.asarray(
            fn(params, jnp.asarray(padded), jax.random.PRNGKey(7),
               jnp.array([3, 4], jnp.int32))
        )
        for i in range(2):
            np.testing.assert_array_equal(one[i], want[i])


class TestChunkSeeds:
    def test_sampled_chunks_do_not_repeat(self, tmp_path, lm, tok):
        # 4 identical prompts through a batch_size-2 sampled bundle: the
        # two chunks must draw DIFFERENT samples (chunk index folded into
        # the key), not repeat chunk 0's continuations verbatim.
        model, params = lm
        out = serving.export_generate(
            str(tmp_path), model, params,
            batch_size=2, prompt_len=T0, max_new_tokens=NEW,
            temperature=1.2, top_k=0,
        )
        b = serving.load_generate(out)
        prompts = [[3, 1, 4]] * 4
        got = b.generate_tokens(prompts, seed=7)
        # Key reuse would make chunk 1 bit-repeat chunk 0 (identical padded
        # inputs): rows 2/3 would equal rows 0/1 exactly.
        assert (got[2], got[3]) != (got[0], got[1]), (
            "second chunk repeated the first chunk's samples"
        )


class TestEosTrim:
    def test_generations_trim_at_eos(self, tmp_path, lm, tok):
        model, params = lm
        # Use a token the tiny random model actually emits: generate once
        # without eos, pick the first generated token as the "eos" id, and
        # check the eos-configured bundle trims at it.
        plain = serving.export_generate(
            str(tmp_path / "plain"), model, params,
            batch_size=1, prompt_len=4, max_new_tokens=NEW,
        )
        first = serving.load_generate(plain).generate_tokens([[5, 3, 2]])[0]
        eos = int(first[1])  # appears mid-generation
        out = serving.export_generate(
            str(tmp_path / "eos"), model, params,
            batch_size=1, prompt_len=4, max_new_tokens=NEW, eos_id=eos,
        )
        got = serving.load_generate(out).generate_tokens([[5, 3, 2]])[0]
        assert eos not in got
        # Greedy decode is identical up to the eos point; trim cuts there.
        assert got == first[: first.index(eos)]


class TestBundleIntegrity:
    def test_missing_advertised_tokenizer_fails_fast(self, bundle_dir, tmp_path):
        import shutil

        broken = tmp_path / "broken"
        shutil.copytree(bundle_dir, broken)
        (broken / "tokenizer.json").unlink()
        with pytest.raises(FileNotFoundError, match="incomplete"):
            serving.load_generate(str(broken))


class TestExportFromShardedParams:
    def test_generate_bundle_from_tp_sharded_params(self, tmp_path):
        # A TP/FSDP-trained model must export its decode bundle without
        # manual resharding (single-host layout: device_get assembles).
        from horovod_tpu.models.transformer import param_specs
        from horovod_tpu.parallel import mesh as mesh_lib

        model = TransformerLM(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, dropout=0.0
        )
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, T0), jnp.int32)
        )["params"]
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        sharded = jax.device_put(
            params,
            jax.tree.map(
                lambda s: jax.sharding.NamedSharding(mesh, s),
                param_specs(params, mesh),
                is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec),
            ),
        )
        out = serving.export_generate(
            str(tmp_path), model, sharded,
            batch_size=2, prompt_len=T0, max_new_tokens=NEW,
        )
        b = serving.load_generate(out)
        prompts = [[3, 1, 4, 1], [9, 2]]
        got = b.generate_tokens(prompts)
        fn = make_generate_fn(model, max_new_tokens=NEW, include_prompt=False)
        padded = np.zeros((2, T0), np.int32)
        padded[0, :4] = prompts[0]
        padded[1, :2] = prompts[1]
        want = np.asarray(
            fn(params, jnp.asarray(padded), jax.random.PRNGKey(0),
               jnp.array([4, 2], jnp.int32))
        )
        for i in range(2):
            np.testing.assert_array_equal(got[i], want[i], err_msg=f"row {i}")


class TestGenerateCoalescing:
    def test_concurrent_greedy_requests_coalesce(self, bundle_dir, lm):
        import threading as th
        import time

        model, params = lm
        srv = make_server(bundle_dir, port=0)
        t = th.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            app = srv.app
            real = app.bundle._run

            def slow_run(*a, **kw):  # hold the device; queue builds
                time.sleep(0.15)
                return real(*a, **kw)

            app.bundle._run = slow_run
            prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [7, 7], [5], [2, 4], [8]]
            results = [None] * len(prompts)
            errors = []

            def client(i):
                try:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{srv.server_address[1]}/v1/generate",
                        data=json.dumps({"prompt": [prompts[i]]}).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req) as r:
                        results[i] = json.loads(r.read())["tokens"][0]
                except Exception as e:
                    errors.append(e)

            threads = [
                th.Thread(target=client, args=(i,))
                for i in range(len(prompts))
            ]
            for c in threads:
                c.start()
            for c in threads:
                c.join(timeout=60)
            assert not errors, errors
            want = _local_ragged(model, params, prompts)
            for i in range(len(prompts)):
                np.testing.assert_array_equal(
                    results[i], want[i], err_msg=f"row {i}"
                )
            assert app.stats["rows"] == len(prompts)
            assert app.stats["device_calls"] < len(prompts), app.stats
        finally:
            srv.shutdown()


class TestQuantizedBundle:
    def test_export_with_int8_knobs_serves(self, tmp_path, lm):
        # A bundle exported with the int8 serving levers (MXU prefill +
        # int8 KV cache) generates exactly what the local configured
        # generator does.
        model, params = lm
        out = serving.export_generate(
            str(tmp_path), model, params,
            batch_size=2, prompt_len=T0, max_new_tokens=NEW,
            int8_compute=True, quantized_cache=True,
        )
        b = serving.load_generate(out)
        assert b.meta["quantized_cache"] and b.meta["int8_compute"]
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
        got = b.generate_tokens(prompts, seed=0)
        fn = make_generate_fn(
            model, max_new_tokens=NEW, include_prompt=False,
            int8_compute=True, quantized_cache=True,
        )
        padded = np.zeros((2, T0), np.int32)
        padded[0, :5] = prompts[0]
        padded[1, :3] = prompts[1]
        want = np.asarray(
            fn(params, jnp.asarray(padded), jax.random.PRNGKey(0),
               jnp.array([5, 3], jnp.int32))
        )
        for i in range(2):
            np.testing.assert_array_equal(got[i], want[i], err_msg=f"row {i}")


class TestSpeculativeBundle:
    def test_export_serve_matches_plain_greedy(self, tmp_path, lm, tok):
        # The speculative bundle's program IS the speculative decoder;
        # greedy exactness makes its HTTP generations bit-equal to the
        # plain greedy bundle's — only the speed differs.
        model, params = lm
        out = serving.export_generate(
            str(tmp_path), model, params,
            batch_size=2, prompt_len=T0, max_new_tokens=NEW,
            speculative_gamma=4, tokenizer=tok,
        )
        b = serving.load_generate(out)
        assert b.meta["speculative_gamma"] == 4
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
        got = b.generate_tokens(prompts)
        want = _local_ragged(model, params, prompts)  # plain greedy
        for i in range(len(prompts)):
            np.testing.assert_array_equal(got[i], want[i], err_msg=f"row {i}")

    def test_http_route_serves_speculative_bundle(self, tmp_path, lm, tok):
        import threading as th

        model, params = lm
        out = serving.export_generate(
            str(tmp_path), model, params,
            batch_size=2, prompt_len=T0, max_new_tokens=NEW,
            speculative_gamma=3, tokenizer=tok,
        )
        srv = make_server(out, port=0)
        t = th.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            status, body = _post(
                srv, "/v1/generate", {"text": ["the ring"]}
            )
            assert status == 200
            want = _local_ragged(model, params, [tok.encode("the ring")])
            np.testing.assert_array_equal(body["tokens"][0], want[0])
        finally:
            srv.shutdown()

    def test_sampled_speculative_bundle_rejected(self, tmp_path, lm):
        model, params = lm
        with pytest.raises(ValueError, match="greedy-only"):
            serving.export_generate(
                str(tmp_path), model, params,
                batch_size=1, prompt_len=4, max_new_tokens=4,
                speculative_gamma=4, temperature=0.7,
            )
        with pytest.raises(ValueError, match="eos"):
            serving.export_generate(
                str(tmp_path), model, params,
                batch_size=1, prompt_len=4, max_new_tokens=4,
                speculative_gamma=4, eos_id=3,
            )

    def test_quantized_cache_speculative_bundle_matches(self, tmp_path, lm):
        # The stacked config: speculative loop over the int8 KV cache.
        # Exactness contract: equals the quantized-cache GREEDY generator
        # (both consult the same quantized values at every position).
        model, params = lm
        out = serving.export_generate(
            str(tmp_path), model, params,
            batch_size=2, prompt_len=T0, max_new_tokens=NEW,
            speculative_gamma=4, quantized_cache=True,
        )
        b = serving.load_generate(out)
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
        got = b.generate_tokens(prompts)
        fn = make_generate_fn(
            model, max_new_tokens=NEW, include_prompt=False,
            quantized_cache=True,
        )
        padded = np.zeros((2, T0), np.int32)
        padded[0, :5] = prompts[0]
        padded[1, :3] = prompts[1]
        want = np.asarray(
            fn(params, jnp.asarray(padded), jax.random.PRNGKey(0),
               jnp.array([5, 3], jnp.int32))
        )
        for i in range(2):
            np.testing.assert_array_equal(got[i], want[i], err_msg=f"row {i}")

    def test_rejected_export_leaves_no_empty_dir(self, tmp_path, lm):
        model, params = lm
        with pytest.raises(ValueError):
            serving.export_generate(
                str(tmp_path), model, params,
                batch_size=1, prompt_len=4, max_new_tokens=4,
                speculative_gamma=4, temperature=0.7,
                timestamp="19990101-000000",
            )
        assert not (tmp_path / "19990101-000000").exists()


class TestStreamingBundle:
    @pytest.fixture(scope="class")
    def stream_bundle(self, tmp_path_factory, lm, tok):
        model, params = lm
        return serving.export_generate(
            str(tmp_path_factory.mktemp("streamexport")), model, params,
            batch_size=2, prompt_len=T0, max_new_tokens=6,
            streaming_chunk=2, tokenizer=tok,
        )

    def test_chunks_concatenate_to_one_shot(self, stream_bundle, lm):
        model, params = lm
        b = serving.load_generate(stream_bundle)
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
        chunks = list(b.stream_chunks(prompts, seed=0))
        assert len(chunks) == 3 and all(
            len(c[0]) == 2 for c in chunks
        )
        got = [sum((c[i] for c in chunks), []) for i in range(2)]
        want = _local_ragged(model, params, prompts)[:, :6]
        for i in range(2):
            np.testing.assert_array_equal(got[i], want[i], err_msg=f"row {i}")

    def test_one_shot_api_works_on_streaming_bundle(self, stream_bundle, lm):
        model, params = lm
        b = serving.load_generate(stream_bundle)
        got = b.generate_tokens([[7, 7, 2]], seed=0)
        want = _local_ragged(model, params, [[7, 7, 2]])[:, :6]
        np.testing.assert_array_equal(got[0], want[0])

    def test_http_ndjson_stream(self, stream_bundle, lm, tok):
        import threading as th

        model, params = lm
        srv = make_server(stream_bundle, port=0)
        t = th.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.server_address[1]}/v1/generate",
                data=json.dumps(
                    {"text": ["the ring"], "stream": True}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as r:
                assert r.headers["Content-Type"] == "application/x-ndjson"
                lines = [json.loads(l) for l in r.read().splitlines()]
            assert lines[-1]["done"] is True
            streamed = sum((l["tokens"][0] for l in lines[:-1]), [])
            want = _local_ragged(
                model, params, [tok.encode("the ring")]
            )[0, :6]
            np.testing.assert_array_equal(streamed, want)
            assert lines[-1]["text"] == [tok.decode(list(map(int, want)))]
        finally:
            srv.shutdown()

    def test_stream_on_non_streaming_bundle_is_400(self, server):
        status, body = _post_raw(
            server, "/v1/generate", {"prompt": [[1, 2]], "stream": True}
        )
        assert status == 400
        assert "streaming" in body["error"]

    def test_eos_stops_stream_early(self, tmp_path, lm):
        model, params = lm
        probe_dir = serving.export_generate(
            str(tmp_path / "probe"), model, params,
            batch_size=1, prompt_len=4, max_new_tokens=6,
        )
        first = serving.load_generate(probe_dir).generate_tokens([[5, 3, 2]])[0]
        eos = int(first[1])  # emitted at the second position
        out = serving.export_generate(
            str(tmp_path / "eos"), model, params,
            batch_size=1, prompt_len=4, max_new_tokens=6,
            streaming_chunk=2, eos_id=eos,
        )
        b = serving.load_generate(out)
        chunks = list(b.stream_chunks([[5, 3, 2]]))
        # eos lands in chunk 1 -> later chunks are not dispatched.
        assert len(chunks) < 3, chunks

    def test_mid_stream_error_is_ndjson_line(self, stream_bundle):
        import threading as th

        srv = make_server(stream_bundle, port=0)
        t = th.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            calls = {"n": 0}
            app = srv.app
            real = app.bundle._cont

            def dying_cont(*a):
                calls["n"] += 1
                raise RuntimeError("device fell over mid-stream")

            app.bundle._cont = dying_cont
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.server_address[1]}/v1/generate",
                data=json.dumps(
                    {"prompt": [[3, 1, 4]], "stream": True}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as r:
                assert r.status == 200  # headers were already out
                lines = [json.loads(l) for l in r.read().splitlines()]
            app.bundle._cont = real
            # First chunk streamed, then the error line; no 'done' line.
            assert "tokens" in lines[0]
            assert "device fell over" in lines[-1]["error"]
            assert not any(l.get("done") for l in lines)
        finally:
            srv.shutdown()

    def test_concurrent_nonstream_not_blocked_by_slow_stream_reader(
        self, stream_bundle, lm
    ):
        # Per-dispatch locking: while a stream's client drains slowly,
        # other requests' device calls interleave.
        import threading as th
        import time as time_lib

        model, params = lm
        srv = make_server(stream_bundle, port=0)
        t = th.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/v1/generate"
            stream_req = urllib.request.Request(
                url,
                data=json.dumps(
                    {"prompt": [[3, 1, 4]], "stream": True}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = urllib.request.urlopen(stream_req)
            resp.readline()  # first chunk received; stream now idle-ish
            # A one-shot request must complete while the stream is open.
            done = {}

            def oneshot():
                r = urllib.request.urlopen(
                    urllib.request.Request(
                        url,
                        data=json.dumps({"prompt": [[9, 2]]}).encode(),
                        headers={"Content-Type": "application/json"},
                    ),
                    timeout=30,
                )
                done["tokens"] = json.loads(r.read())["tokens"]

            c = th.Thread(target=oneshot)
            c.start()
            c.join(timeout=30)
            assert done.get("tokens"), "one-shot starved behind open stream"
            resp.read()  # drain the stream
        finally:
            srv.shutdown()
