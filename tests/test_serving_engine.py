"""Scheduler unit tests: paged-KV allocator, continuous-batching engine
(admit/evict ordering, exhaustion -> 429, block reuse after retire,
swap-drain invariants), the router's replica ledger, and the serve app's
head-of-line accounting fix.

The engine tests run against a FAKE streaming bundle — a pure-jnp decode
pytree honoring the exact `(cache, last_tok, rng, done)` state contract
`serving.decoder` splices — so the scheduler's logic is exercised in the
fast lane with no export/compile. Bit-exactness of the splice against the
REAL compiled programs is tests/test_serving.py's job (slow lane)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.serving.blocks import (
    BlockAllocator,
    OutOfBlocksError,
)
from horovod_tpu.serving.engine import (
    AdmissionError,
    ContinuousBatchingEngine,
)
from horovod_tpu.serving.router import NoReplicaError, ReplicaSet

BATCH, T0, NEW, CHUNK = 4, 8, 8, 2


class FakeBundle:
    """A streaming bundle whose rows deterministically count up from
    their last prompt token — per-row independent, so the engine's row
    splicing is observable: any cross-row contamination changes tokens.
    """

    def __init__(self, eos_id=None, temperature=0.0):
        self.batch_size = BATCH
        self.prompt_len = T0
        self.meta = {
            "streaming_chunk": CHUNK,
            "max_new_tokens": NEW,
            "eos_id": eos_id,
            "pad_id": 0,
            "temperature": temperature,
        }
        self.tokenizer = None
        self._params = None

    def validate_prompts(self, prompts):
        prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        for i, p in enumerate(prompts):
            if not 1 <= len(p) <= self.prompt_len:
                raise ValueError(
                    f"prompt {i} has {len(p)} tokens; this bundle serves "
                    f"prompts of 1..{self.prompt_len} tokens"
                )
        return prompts

    def _chunk_from(self, ctr):
        steps = jnp.arange(1, CHUNK + 1, dtype=jnp.int32)
        return ctr[:, None] + steps[None, :]

    def _start(self, params, padded, rng, lengths):
        idx = jnp.arange(padded.shape[0])
        ctr = jnp.asarray(padded)[idx, jnp.asarray(lengths) - 1]
        tokens = self._chunk_from(ctr)
        ctr = ctr + CHUNK
        state = ({"ctr": ctr}, tokens[:, -1], jnp.asarray(rng),
                 jnp.zeros(padded.shape[0], bool))
        return tokens, state

    def _cont(self, params, state):
        cache, last, rng, done = state
        tokens = self._chunk_from(cache["ctr"])
        return tokens, ({"ctr": cache["ctr"] + CHUNK}, tokens[:, -1],
                        rng, done)


def _engine(**kw):
    kw.setdefault("start_thread", False)
    return ContinuousBatchingEngine(FakeBundle(**kw.pop("bundle", {})), **kw)


def _expect(prompt):
    base = prompt[-1]
    return [base + i for i in range(1, NEW + 1)]


# -- paged-KV allocator -----------------------------------------------------


def test_blocks_for_math():
    a = BlockAllocator(10, 16)
    assert a.blocks_for(1) == 1
    assert a.blocks_for(16) == 1
    assert a.blocks_for(17) == 2
    assert a.blocks_for(160) == 10


def test_reserve_exhaustion_and_reuse():
    a = BlockAllocator(4, 16)
    t1 = a.reserve(32)  # 2 blocks
    t2 = a.reserve(20)  # 2 blocks
    assert a.free_blocks == 0
    with pytest.raises(OutOfBlocksError):
        a.reserve(1)
    a.free(t1)
    assert a.free_blocks == 2
    t3 = a.reserve(17)  # reuses the freed blocks
    assert a.used_blocks == 4
    a.free(t2)
    a.free(t3)
    assert a.free_blocks == 4


def test_never_fits_is_valueerror_not_wait():
    a = BlockAllocator(4, 16)
    with pytest.raises(ValueError):
        a.reserve(4 * 16 + 1)  # bigger than the WHOLE budget


def test_double_free_guard():
    a = BlockAllocator(4, 16)
    t = a.reserve(16)
    a.free(t)
    with pytest.raises(ValueError):
        a.free(t)


# -- engine: admit / step / retire -----------------------------------------


def test_tokens_match_solo_generation():
    eng = _engine()
    reqs = [eng.submit([3]), eng.submit([1, 2, 40])]
    for _ in range(NEW // CHUNK):
        eng.tick()
    assert reqs[0].result(1) == _expect([3])
    assert reqs[1].result(1) == _expect([1, 2, 40])
    s = eng.stats()
    assert s["live_seqs"] == 0 and s["retired_total"] == 2
    assert s["kv_blocks_free"] == s["kv_blocks_total"]


def test_admission_is_strict_fifo():
    eng = _engine(max_seqs=2)
    reqs = [eng.submit([10 * i + 10]) for i in range(6)]
    first = eng.tick()
    assert first == {"admitted": 2, "evicted": 0, "live": 2}
    # Slots hold the first two submissions, in order.
    assert eng._slots[0] is reqs[0] and eng._slots[1] is reqs[1]
    while any(not r._done.is_set() for r in reqs):
        eng.tick()
    # Everybody eventually ran, each exactly as if alone.
    for i, r in enumerate(reqs):
        assert r.result(1) == _expect([10 * i + 10])
    # Retirement order == admission order == submission order.
    finished = sorted(range(6), key=lambda i: reqs[i].finished)
    assert finished == list(range(6))


def test_mid_flight_admission_and_retire_same_tick():
    eng = _engine(max_seqs=4)
    a = eng.submit([5])
    eng.tick()  # a admitted, chunk 1
    b = eng.submit([7])
    out = eng.tick()  # b admitted INTO the live batch; a advances
    assert out["admitted"] == 1 and out["live"] == 2
    for _ in range(NEW // CHUNK):
        eng.tick()
    assert a.result(1) == _expect([5])
    assert b.result(1) == _expect([7])  # splicing didn't disturb either


def test_queue_full_is_429():
    eng = _engine(max_seqs=1, queue_depth=2)
    eng.submit([1])
    eng.tick()  # one live; queue now empty
    eng.submit([2])
    eng.submit([3])
    with pytest.raises(AdmissionError):
        eng.submit([4])
    assert eng.stats()["rejected_total"] == 1


def test_block_exhaustion_gates_admission_and_blocks_are_reused():
    # Budget fits exactly ONE worst-case sequence: (T0 + NEW) / 16 = 1
    # block; give the allocator 1 block so the second sequence must wait
    # for the first to retire and reuse the SAME block.
    eng = _engine(max_seqs=4, kv_blocks=1, block_tokens=T0 + NEW)
    a = eng.submit([4])
    b = eng.submit([9])
    first = eng.tick()
    assert first["admitted"] == 1  # b gated by blocks, not slots
    assert eng.stats()["queue_depth"] == 1
    while not a._done.is_set():
        eng.tick()
    # a retired -> its block freed -> b admits on a later tick.
    while not b._done.is_set():
        eng.tick()
    assert b.result(1) == _expect([9])
    assert eng.stats()["kv_blocks_free"] == 1


def test_oversized_request_is_400_not_queued():
    eng = _engine(max_seqs=2, kv_blocks=1, block_tokens=4)
    with pytest.raises(ValueError):
        eng.submit([1, 2, 3, 4, 5])  # needs more blocks than exist
    assert eng.stats()["queue_depth"] == 0
    assert eng.stats()["rejected_total"] == 0  # 400, not a 429


def test_eos_retires_early_and_frees_slot():
    # Counting rows hit eos_id=20: prompt [18] generates 19, 20 -> eos in
    # the FIRST chunk; the row must retire immediately and free capacity.
    eng = _engine(bundle={"eos_id": 20}, max_seqs=1)
    a = eng.submit([18])
    b = eng.submit([50])
    out = eng.tick()
    assert out["evicted"] == 1  # a retired the very tick it finished
    assert a.result(1) == [19]  # trimmed AT eos
    while not b._done.is_set():
        eng.tick()
    assert b.result(1) == _expect([50])  # full run, slot was reused


def test_drain_and_stop_invariants():
    eng = _engine(max_seqs=2)
    assert eng.drain(0.01) is True  # empty engine is drained
    r = eng.submit([3])
    assert eng.drain(0.01) is False  # live work: not drained
    while not r._done.is_set():
        eng.tick()
    assert eng.drain(0.01) is True
    # stop() fails out anything still queued.
    doomed = eng.submit([5])
    eng.stop()
    with pytest.raises(RuntimeError):
        doomed.result(1)
    assert eng.stats()["kv_blocks_free"] == eng.stats()["kv_blocks_total"]


def test_streaming_chunks_arrive_incrementally():
    eng = _engine()
    r = eng.submit([30], stream=True)
    eng.tick()
    got = []
    it = r.iter_chunks()
    got.extend(next(it))
    assert got == [31, 32]  # first chunk delivered after one tick
    for _ in range(NEW // CHUNK - 1):
        eng.tick()
    for piece in it:
        got.extend(piece)
    assert got == _expect([30])


def test_scheduler_thread_end_to_end():
    eng = ContinuousBatchingEngine(FakeBundle(), start_thread=True)
    try:
        reqs = [eng.submit([i + 1]) for i in range(8)]
        outs = [r.result(10) for r in reqs]
        assert outs == [_expect([i + 1]) for i in range(8)]
        assert eng.drain(5) is True
    finally:
        eng.stop()


# -- router replica ledger --------------------------------------------------


def test_acquire_prefers_least_loaded():
    rs = ReplicaSet()
    rs.add("a", "http://a")
    rs.add("b", "http://b")
    r1 = rs.acquire()
    r2 = rs.acquire()
    assert {r1.name, r2.name} == {"a", "b"}  # spread, not piled
    r3 = rs.acquire(exclude={r1.name})
    assert r3.name == r2.name
    for r in (r1, r2, r3):
        rs.release(r)
    assert all(s["inflight"] == 0 for s in rs.snapshot())


def test_draining_replica_gets_no_traffic():
    rs = ReplicaSet()
    rs.add("a", "http://a")
    rs.add("b", "http://b")
    rs.drain("a")
    for _ in range(4):
        assert rs.acquire().name == "b"
    rs.drain("b")
    with pytest.raises(NoReplicaError):
        rs.acquire()
    rs.readmit("a")
    assert rs.acquire().name == "a"


def test_wait_drained_is_the_swap_barrier():
    rs = ReplicaSet()
    rs.add("a", "http://a")
    held = rs.acquire()
    rs.drain("a")
    assert rs.wait_drained("a", 0.05) is False  # in-flight request holds it

    def _finish():
        rs.release(held)

    t = threading.Timer(0.05, _finish)
    t.start()
    try:
        assert rs.wait_drained("a", 5.0) is True
    finally:
        t.join()


# -- serve app: head-of-line accounting fix ---------------------------------


def test_invalid_request_never_reaches_accounting(monkeypatch):
    """Regression (coalescing head-of-line fix): a request that fails
    validation must be rejected BEFORE it bumps device_calls/rows or
    occupies the device lock — previously the sampled path counted the
    dispatch first and discovered the bad prompt inside the lock."""
    from horovod_tpu import serving as serving_pkg
    from horovod_tpu.launch.serve import _GenerateApp

    fake = FakeBundle(temperature=0.7)  # sampled: the legacy locked path
    monkeypatch.setattr(serving_pkg, "load_generate", lambda d: fake)
    app = _GenerateApp("fake-dir", coalesce=True)
    with pytest.raises(ValueError):
        app.generate({"prompt": [[1] * (T0 + 1)]})
    assert app.stats == {"device_calls": 0, "rows": 0}
    # The streaming path rejects at the door too (before any yield).
    with pytest.raises(ValueError):
        next(app.stream({"prompt": [[1] * (T0 + 1)], "stream": True}))
    assert app.stats == {"device_calls": 0, "rows": 0}


def test_continuous_app_sizes_engine_from_knobs(monkeypatch):
    from horovod_tpu import serving as serving_pkg
    from horovod_tpu.launch.serve import _GenerateApp

    fake = FakeBundle()
    monkeypatch.setattr(serving_pkg, "load_generate", lambda d: fake)
    monkeypatch.setenv("HVT_SERVE_MAX_SEQS", "2")
    monkeypatch.setenv("HVT_SERVE_QUEUE_DEPTH", "3")
    monkeypatch.setenv("HVT_SERVE_BLOCK_TOKENS", str(T0 + NEW))
    monkeypatch.setenv("HVT_SERVE_KV_BLOCKS", "2")
    app = _GenerateApp("fake-dir", continuous=True)
    try:
        assert app.engine.max_seqs == 2
        assert app.engine.queue_depth == 3
        assert app.engine.allocator.num_blocks == 2
        # And the engine actually serves through the app surface.
        out = app.generate({"prompt": [[6], [11]]})
        assert out["tokens"] == [_expect([6]), _expect([11])]
    finally:
        app.engine.stop()
