"""KV-cache autoregressive generation: cache decode must equal full
recomputation, prefill must equal the training forward, sampling must be
deterministic under a fixed key, and the whole loop must run TP-sharded."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.decoding import generate, make_generate_fn
from horovod_tpu.models.transformer import ShardingConfig, TransformerLM
from horovod_tpu.parallel import mesh as mesh_lib

VOCAB = 32


def _model(**kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("dropout", 0.0)
    return TransformerLM(**kw)


def _params(model, t=8, b=2):
    tokens = jnp.zeros((b, t), jnp.int32)
    return model.init(jax.random.PRNGKey(0), tokens)["params"]


def _greedy_no_cache(model, params, prompt, n):
    """Reference decoder: full forward re-run per token, no cache."""
    tokens = np.asarray(prompt)
    for _ in range(n):
        logits = model.apply({"params": params}, jnp.asarray(tokens))
        nxt = np.argmax(np.asarray(logits[:, -1], np.float32), axis=-1)
        tokens = np.concatenate([tokens, nxt[:, None].astype(tokens.dtype)], axis=1)
    return tokens


class TestGreedyParity:
    @pytest.mark.slow
    def test_cache_decode_equals_full_recompute(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], np.int32)
        want = _greedy_no_cache(model, params, prompt, 12)
        got = generate(model, params, prompt, 12)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_prefill_logits_match_training_forward(self):
        model = _model()
        params = _params(model)
        prompt = jnp.arange(12, dtype=jnp.int32).reshape(2, 6) % VOCAB
        train_logits = model.apply({"params": params}, prompt)
        dmodel = model.clone(decode=True, max_decode_len=10)
        decode_logits, _ = dmodel.apply(
            {"params": params}, prompt, mutable=["cache"]
        )
        np.testing.assert_allclose(
            np.asarray(decode_logits), np.asarray(train_logits),
            rtol=2e-5, atol=2e-5,
        )

    @pytest.mark.slow
    def test_moe_blocks_decode(self):
        # Ample capacity so routing never drops: a binding capacity is
        # enforced per call group, so the per-step decode and the
        # full-sequence recompute would legitimately drop DIFFERENT tokens
        # and diverge (models/decoding.py MoE caveat). Exact equality is the
        # contract only in the drop-free regime this test pins.
        model = _model(
            moe_every=2, n_experts=4, moe_k=2, capacity_factor=4.0
        )
        params = _params(model)
        prompt = np.array([[1, 2, 3, 4]], np.int32)
        want = _greedy_no_cache(model, params, prompt, 6)
        got = generate(model, params, prompt, 6)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_include_prompt_false(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[7, 8, 9]], np.int32)
        full = generate(model, params, prompt, 5)
        tail = generate(model, params, prompt, 5, include_prompt=False)
        assert tail.shape == (1, 5)
        np.testing.assert_array_equal(np.asarray(full)[:, 3:], np.asarray(tail))


class TestSampling:
    def test_fixed_key_deterministic(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[1, 2, 3]], np.int32)
        key = jax.random.PRNGKey(42)
        a = generate(model, params, prompt, 8, temperature=0.8, rng=key)
        b = generate(model, params, prompt, 8, temperature=0.8, rng=key)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = generate(
            model, params, prompt, 8, temperature=0.8,
            rng=jax.random.PRNGKey(43),
        )
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_tokens_in_vocab(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[0, 1], [2, 3]], np.int32)
        out = np.asarray(generate(
            model, params, prompt, 16, temperature=1.5, top_k=5,
            rng=jax.random.PRNGKey(1),
        ))
        assert out.min() >= 0 and out.max() < VOCAB

    def test_top_k_one_is_greedy(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[5, 6, 7]], np.int32)
        greedy = generate(model, params, prompt, 8)
        k1 = generate(
            model, params, prompt, 8, temperature=0.7, top_k=1,
            rng=jax.random.PRNGKey(9),
        )
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))

    def test_eos_fill(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[1, 2]], np.int32)
        base = np.asarray(generate(model, params, prompt, 12, include_prompt=False))
        eos = int(base[0, 3])  # force an id we know greedy emits at step 3
        out = np.asarray(generate(
            model, params, prompt, 12, eos_id=eos, include_prompt=False,
        ))
        stop = int(np.argmax(out[0] == eos))
        np.testing.assert_array_equal(out[0, : stop + 1], base[0, : stop + 1])
        assert (out[0, stop:] == eos).all()


class TestSharded:
    def test_tp_mesh_matches_single_device(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[3, 1, 4, 1], [2, 7, 1, 8]], np.int32)
        want = np.asarray(generate(model, params, prompt, 10))

        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, model=2), devices=jax.devices()[:4]
        )
        smodel = _model(sharding=ShardingConfig(mesh=mesh, attn="ring"))
        got = np.asarray(generate(smodel, params, prompt, 10))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow
    def test_reusable_compiled_fn(self):
        model = _model()
        params = _params(model)
        fn = make_generate_fn(model, max_new_tokens=6)
        p1 = np.array([[1, 2, 3]], np.int32)
        p2 = np.array([[4, 5, 6]], np.int32)
        a = fn(params, jnp.asarray(p1), jax.random.PRNGKey(0))
        b = fn(params, jnp.asarray(p2), jax.random.PRNGKey(0))
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(generate(model, params, p1, 6))
        )
        np.testing.assert_array_equal(
            np.asarray(b), np.asarray(generate(model, params, p2, 6))
        )

    @pytest.mark.slow
    def test_chunked_prefill_matches_single_prefill(self):
        """T>1 on a warm cache extends it (round 3): the chunk attends over
        the cached prefix plus itself causally, so feeding a prompt in two
        chunks must give the same logits and the same downstream decode
        steps as one prefill — the basis for chunked long-prompt prefill
        and speculative decoding's verify pass."""
        model = _model()
        params = _params(model)
        toks = jnp.asarray(
            np.random.RandomState(21).randint(1, VOCAB, size=(2, 12)),
            jnp.int32,
        )
        dmodel = model.clone(decode=True, max_decode_len=16)
        full_logits, v_full = dmodel.apply(
            {"params": params}, toks, mutable=["cache"]
        )
        _, v1 = dmodel.apply(
            {"params": params}, toks[:, :8], mutable=["cache"]
        )
        l2, v2 = dmodel.apply(
            {"params": params, "cache": v1["cache"]}, toks[:, 8:],
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(l2), np.asarray(full_logits[:, 8:]),
            rtol=1e-5, atol=1e-5,
        )
        assert int(v2["cache"]["index"]) == int(v_full["cache"]["index"])
        nxt = jnp.argmax(full_logits[:, -1], -1)[:, None].astype(jnp.int32)
        s_full, _ = dmodel.apply(
            {"params": params, "cache": v_full["cache"]}, nxt,
            mutable=["cache"],
        )
        s_chunk, _ = dmodel.apply(
            {"params": params, "cache": v2["cache"]}, nxt,
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(s_full), np.asarray(s_chunk), rtol=1e-5, atol=1e-5
        )

    def test_decode_rejects_train_and_remat(self):
        model = _model(remat=True)
        params = _params(model)
        dmodel = model.clone(decode=True, max_decode_len=8)
        with pytest.raises(ValueError, match="inference-only"):
            dmodel.apply(
                {"params": params}, jnp.zeros((1, 2), jnp.int32),
                mutable=["cache"],
            )


class TestTopP:
    """Nucleus sampling: the kept set is the smallest descending-prob
    prefix whose exclusive cumulative mass is < top_p (top token always
    survives)."""

    def test_support_is_the_nucleus(self):
        from horovod_tpu.models.decoding import _sample

        # probs [0.5, 0.3, 0.15, 0.05] -> top_p=0.6 keeps exactly {0, 1}
        # (exclusive cumsums 0.0, 0.5, 0.8, 0.95).
        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]], jnp.float32))
        draws = jax.vmap(
            lambda k: _sample(logits, k, 1.0, 0, 0.6)[0]
        )(jax.random.split(jax.random.PRNGKey(0), 256))
        support = set(np.asarray(draws).tolist())
        assert support == {0, 1}

    def test_tiny_top_p_is_greedy(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[5, 6, 7]], np.int32)
        greedy = generate(model, params, prompt, 8)
        p_tiny = generate(
            model, params, prompt, 8, temperature=0.9, top_p=1e-6,
            rng=jax.random.PRNGKey(3),
        )
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(p_tiny))

    def test_composes_with_top_k_in_vocab(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[0, 1], [2, 3]], np.int32)
        out = np.asarray(generate(
            model, params, prompt, 12, temperature=1.2, top_k=8, top_p=0.9,
            rng=jax.random.PRNGKey(4),
        ))
        assert out.min() >= 0 and out.max() < VOCAB


@pytest.mark.slow
class TestGQADecode:
    """GQA decode: the cache stores n_kv_heads (< n_heads) — the bytes
    streamed per token shrink by the group factor — and the grouped-einsum
    decode step must still equal a full teacher-forced recompute."""

    def test_cache_decode_equals_full_recompute(self):
        model = _model(n_kv_heads=2)
        params = _params(model)
        prompt = np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], np.int32)
        want = _greedy_no_cache(model, params, prompt, 12)
        got = generate(model, params, prompt, 12)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_cache_holds_kv_heads_only(self):
        model = _model(n_kv_heads=2)
        params = _params(model)
        prompt = jnp.zeros((2, 4), jnp.int32)
        dmodel = model.clone(decode=True, max_decode_len=8)
        _, var = dmodel.apply({"params": params}, prompt, mutable=["cache"])
        k = var["cache"]["Block_0"]["k"]
        assert k.shape == (2, 8, 2, 8)  # [B, L, H_kv, hd], not H=4

    def test_tp_mesh_matches_single_device(self):
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=1, model=2), devices=jax.devices()[:2]
        )
        plain = _model(n_kv_heads=2)
        params = _params(plain)
        prompt = np.array([[7, 8, 9, 1]], np.int32)
        want = generate(plain, params, prompt, 10)
        sharded = _model(
            n_kv_heads=2, sharding=ShardingConfig(mesh=mesh, attn="flash")
        )
        got = generate(sharded, params, prompt, 10)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
class TestSlidingWindowDecode:
    """window models decode through the cache with the same band the
    training forward used: a cached decode must equal the full recompute
    (whose attention masks the band in the training path)."""

    def test_cache_decode_equals_full_recompute(self):
        model = _model(window=6)
        params = _params(model)
        prompt = np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], np.int32)
        # 14 new tokens: generation runs well past the window so stale
        # cache rows MUST be masked away (an unmasked cache would diverge
        # from the windowed recompute).
        want = _greedy_no_cache(model, params, prompt, 14)
        got = generate(model, params, prompt, 14)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_window_changes_output(self):
        """Sanity: the window actually binds at these lengths (otherwise
        the parity test above proves nothing)."""
        full = _model()
        windowed = _model(window=3)
        params = _params(full)
        prompt = np.array([[3, 1, 4, 1, 5]], np.int32)
        a = np.asarray(generate(full, params, prompt, 14))
        b = np.asarray(generate(windowed, params, prompt, 14))
        assert not np.array_equal(a, b)

    def test_prefill_logits_match_training_forward(self):
        model = _model(window=4)
        params = _params(model)
        prompt = jnp.arange(16, dtype=jnp.int32).reshape(2, 8) % VOCAB
        train_logits = model.apply({"params": params}, prompt)
        dmodel = model.clone(decode=True, max_decode_len=12)
        decode_logits, _ = dmodel.apply(
            {"params": params}, prompt, mutable=["cache"]
        )
        np.testing.assert_allclose(
            np.asarray(decode_logits), np.asarray(train_logits),
            rtol=2e-5, atol=2e-5,
        )

    def test_chunked_prefill_matches_single_prefill(self):
        """Chunk extension (T>1 on a warm cache) must mask the band too."""
        model = _model(window=5, decode=True, max_decode_len=16)
        params = _params(_model(window=5))
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(0, VOCAB, (2, 12)), jnp.int32
        )
        single, vars1 = model.apply(
            {"params": params}, prompt, mutable=["cache"]
        )
        chunked, vars2 = model.apply(
            {"params": params}, prompt[:, :8], mutable=["cache"]
        )
        chunk2, _ = model.apply(
            {"params": params, "cache": vars2["cache"]}, prompt[:, 8:],
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(single[:, 8:]), np.asarray(chunk2),
            rtol=2e-5, atol=2e-5,
        )


class TestSlidingCache:
    """Ring-buffer KV cache (`sliding_cache=True`): O(window) memory and
    cache reads per token, bit-identical generations to the full-history
    cache for windowed models."""

    def _pair(self, **kw):
        kw = dict(vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2,
                  dropout=0.0, window=6, **kw)
        return TransformerLM(**kw), TransformerLM(**kw, sliding_cache=True)

    def test_matches_full_cache_far_past_window(self):
        full, sliding = self._pair()
        params = _params(full)
        prompt = np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], np.int32)
        a = generate(full, params, prompt, 40)
        b = generate(sliding, params, prompt, 40)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_cache_is_window_sized(self):
        import jax.numpy as jnp

        _, sliding = self._pair()
        params = _params(sliding)
        dm = sliding.clone(decode=True, max_decode_len=64)
        _, variables = dm.apply(
            {"params": params}, jnp.zeros((2, 8), jnp.int32),
            mutable=["cache"],
        )
        blk = variables["cache"]["Block_0"]
        assert blk["k"].shape[1] == 6  # window, not max_decode_len
        assert blk["pos"].shape == (2, 6)

    def test_gqa_composes(self):
        full, sliding = self._pair(n_kv_heads=2)
        params = _params(full)
        prompt = np.array([[1, 2, 3, 4]], np.int32)
        a = generate(full, params, prompt, 30)
        b = generate(sliding, params, prompt, 30)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_long_prompt_prefill_evicts_correctly(self):
        """Prompt longer than the window: only the last W rows survive the
        prefill write, and generation still matches the full cache."""
        full, sliding = self._pair()
        params = _params(full)
        prompt = np.asarray(
            np.random.RandomState(0).randint(0, VOCAB, (2, 17)), np.int32
        )
        a = generate(full, params, prompt, 12)
        b = generate(sliding, params, prompt, 12)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_chunk_extension_rejected(self):
        import jax.numpy as jnp

        _, sliding = self._pair()
        params = _params(sliding)
        dm = sliding.clone(decode=True, max_decode_len=32)
        _, variables = dm.apply(
            {"params": params}, jnp.zeros((1, 4), jnp.int32),
            mutable=["cache"],
        )
        with pytest.raises(ValueError, match="sliding_cache supports"):
            dm.apply(
                {"params": params, "cache": variables["cache"]},
                jnp.zeros((1, 3), jnp.int32), mutable=["cache"],
            )

    def test_requires_window(self):
        model = TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=1,
            dropout=0.0, sliding_cache=True,
        )
        params = _params(TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=1, dropout=0.0,
        ))
        with pytest.raises(ValueError, match="window"):
            generate(model, params, np.zeros((1, 4), np.int32), 2)

    def test_beam_search_composes(self):
        from horovod_tpu.models.beam import make_beam_search_fn

        full, sliding = self._pair()
        params = _params(full)
        prompt = np.array([[3, 1, 4, 1, 5]], np.int32)
        a, sa = make_beam_search_fn(
            full, max_new_tokens=16, beam_size=3, return_scores=True
        )(params, prompt)
        b, sb = make_beam_search_fn(
            sliding, max_new_tokens=16, beam_size=3, return_scores=True
        )(params, prompt)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(sa), np.asarray(sb), rtol=1e-6)


class TestAttentionSinks:
    """StreamingLLM sinks: the first S positions stay visible (and pinned
    in the ring) beyond the window band — the standard recipe for
    streaming a densely-trained model with bounded cache."""

    def _pair(self, **kw):
        kw = dict(vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2,
                  dropout=0.0, window=6, attention_sinks=3, **kw)
        return TransformerLM(**kw), TransformerLM(**kw, sliding_cache=True)

    def test_ring_matches_full_history_twin(self):
        """The pinned-slot ring must equal the full-history cache running
        the SAME sinks+band mask — mechanics proof, far past eviction."""
        full, ring = self._pair()
        params = _params(full)
        prompt = np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], np.int32)
        a = generate(full, params, prompt, 40)
        b = generate(ring, params, prompt, 40)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_cache_is_sinks_plus_window(self):
        import jax.numpy as jnp

        _, ring = self._pair()
        params = _params(ring)
        dm = ring.clone(decode=True, max_decode_len=64)
        _, variables = dm.apply(
            {"params": params}, jnp.zeros((2, 8), jnp.int32),
            mutable=["cache"],
        )
        assert variables["cache"]["Block_0"]["k"].shape[1] == 9  # 3 + 6

    def test_sinks_change_output(self):
        """The sinks are actually attended: with vs without differs once
        generation runs past the window."""
        base = dict(vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2,
                    dropout=0.0, window=6)
        params = _params(TransformerLM(**base))
        prompt = np.array([[3, 1, 4, 1, 5]], np.int32)
        a = generate(TransformerLM(**base), params, prompt, 20)
        b = generate(
            TransformerLM(**base, attention_sinks=3), params, prompt, 20
        )
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_long_prompt_pins_sinks_through_eviction(self):
        """Prompt much longer than the window: the ring keeps positions
        0..S-1 even though the band has moved far past them."""
        full, ring = self._pair()
        params = _params(full)
        prompt = np.asarray(
            np.random.RandomState(0).randint(0, VOCAB, (2, 23)), np.int32
        )
        a = generate(full, params, prompt, 10)
        b = generate(ring, params, prompt, 10)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_forward_matches_dense_global_local_mask(self):
        """Sinks are a first-class mask: the training/eval forward applies
        the same sinks+band visibility the decode cache does (the dense
        reference with window AND sinks)."""
        import jax
        import jax.numpy as jnp

        from horovod_tpu.ops.attention import dense_attention

        model = TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=1,
            dropout=0.0, window=6, attention_sinks=3,
        )
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, (2, 20)), jnp.int32
        )
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        got = model.apply({"params": params}, toks)
        # Window-only twin differs (the sinks matter)...
        other = model.clone(attention_sinks=0).apply({"params": params}, toks)
        assert float(jnp.abs(got - other).max()) > 1e-4
        # ...and the decode prefill agrees with the forward exactly.
        dm = model.clone(decode=True, max_decode_len=24)
        pre, _ = dm.apply({"params": params}, toks, mutable=["cache"])
        np.testing.assert_allclose(
            np.asarray(pre), np.asarray(got), rtol=2e-5, atol=2e-5
        )

    def test_chunked_prefill_consistent_with_single(self):
        """Full-history cache + sinks: the chunk-extension mask and the
        single-prefill mask agree (the review's divergence scenario)."""
        import jax.numpy as jnp

        model = TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=1,
            dropout=0.0, window=6, attention_sinks=3,
            decode=True, max_decode_len=32,
        )
        params = _params(TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=1, dropout=0.0,
            window=6,
        ))
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(0, VOCAB, (2, 20)), jnp.int32
        )
        single, _ = model.apply({"params": params}, prompt, mutable=["cache"])
        first, v1 = model.apply(
            {"params": params}, prompt[:, :10], mutable=["cache"]
        )
        second, _ = model.apply(
            {"params": params, "cache": v1["cache"]}, prompt[:, 10:],
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(single[:, 10:]), np.asarray(second),
            rtol=2e-5, atol=2e-5,
        )

    def test_sinks_reject_dense_block_ring(self):
        """Sinks compose with sequence parallelism through the flash ring
        and Ulysses (tests/test_attention.py); the ONE remaining refusal is
        the dense-block ring (attn='ring_dense'), which is sink-unaware."""
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models.transformer import ShardingConfig
        from horovod_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, seq=4), devices=jax.devices()[:8]
        )
        model = TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=1,
            dropout=0.0, window=6, attention_sinks=2,
            sharding=ShardingConfig(mesh=mesh, attn="ring_dense"),
        )
        with pytest.raises(ValueError, match="sink-unaware"):
            model.init(jax.random.PRNGKey(0), jnp.zeros((2, 16), jnp.int32))


@pytest.mark.slow
class TestRaggedPrompts:
    """fn(params, prompt, rng, lengths): mixed prompt lengths in one batch,
    each row generating exactly as if alone at its own length."""

    def test_each_row_matches_its_solo_generation(self):
        model = _model()
        params = _params(model)
        rng = np.random.RandomState(0)
        t0 = 8
        lens = np.array([3, 8, 5], np.int32)
        rows = [rng.randint(1, VOCAB, size=(L,)).astype(np.int32) for L in lens]
        padded = np.zeros((3, t0), np.int32)
        for i, r in enumerate(rows):
            padded[i, : lens[i]] = r
        fn = make_generate_fn(model, max_new_tokens=6, include_prompt=False)
        key = jax.random.PRNGKey(0)
        got = np.asarray(fn(params, jnp.asarray(padded), key, jnp.asarray(lens)))
        for i, r in enumerate(rows):
            solo = np.asarray(
                fn(params, jnp.asarray(r[None, :]), key)
            )
            np.testing.assert_array_equal(got[i], solo[0], err_msg=f"row {i}")

    def test_full_lengths_match_legacy_path(self):
        model = _model()
        params = _params(model)
        prompt = np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], np.int32)
        fn = make_generate_fn(model, max_new_tokens=7)
        key = jax.random.PRNGKey(1)
        legacy = np.asarray(fn(params, jnp.asarray(prompt), key))
        ragged = np.asarray(
            fn(params, jnp.asarray(prompt), key,
               jnp.full((2,), prompt.shape[1], jnp.int32))
        )
        np.testing.assert_array_equal(ragged, legacy)

    def test_pad_content_is_irrelevant(self):
        # Whatever garbage sits in the padding must not leak into any row's
        # generation — the core correctness claim of the ragged layout.
        model = _model()
        params = _params(model)
        lens = jnp.array([4, 6], jnp.int32)
        base = np.array(
            [[5, 3, 7, 2, 0, 0, 0, 0], [1, 9, 8, 4, 2, 6, 0, 0]], np.int32
        )
        noisy = base.copy()
        noisy[0, 4:] = [11, 13, 17, 19]
        noisy[1, 6:] = [23, 29]
        fn = make_generate_fn(model, max_new_tokens=5, include_prompt=False)
        key = jax.random.PRNGKey(2)
        a = np.asarray(fn(params, jnp.asarray(base), key, lens))
        b = np.asarray(fn(params, jnp.asarray(noisy), key, lens))
        np.testing.assert_array_equal(a, b)

    def test_sampled_ragged_stays_in_vocab(self):
        model = _model()
        params = _params(model)
        lens = jnp.array([2, 7], jnp.int32)
        prompt = np.array(
            [[5, 3, 0, 0, 0, 0, 0, 0], [1, 9, 8, 4, 2, 6, 3, 0]], np.int32
        )
        fn = make_generate_fn(
            model, max_new_tokens=8, temperature=0.8, top_k=8,
            include_prompt=False,
        )
        out = np.asarray(
            fn(params, jnp.asarray(prompt), jax.random.PRNGKey(3), lens)
        )
        assert out.shape == (2, 8)
        assert (out >= 0).all() and (out < VOCAB).all()

    def test_gqa_ragged_matches_solo(self):
        model = _model(n_heads=4, n_kv_heads=2)
        params = _params(model)
        lens = np.array([3, 6], np.int32)
        rng = np.random.RandomState(4)
        padded = np.zeros((2, 6), np.int32)
        rows = []
        for i, L in enumerate(lens):
            r = rng.randint(1, VOCAB, size=(L,)).astype(np.int32)
            rows.append(r)
            padded[i, :L] = r
        fn = make_generate_fn(model, max_new_tokens=5, include_prompt=False)
        key = jax.random.PRNGKey(0)
        got = np.asarray(fn(params, jnp.asarray(padded), key, jnp.asarray(lens)))
        for i, r in enumerate(rows):
            solo = np.asarray(fn(params, jnp.asarray(r[None, :]), key))
            np.testing.assert_array_equal(got[i], solo[0], err_msg=f"row {i}")

    def test_sliding_cache_rejects_ragged(self):
        model = _model(window=4, sliding_cache=True)
        params = _params(model)
        prompt = np.zeros((2, 6), np.int32)
        fn = make_generate_fn(model, max_new_tokens=4)
        with pytest.raises(ValueError, match="per-row"):
            fn(params, jnp.asarray(prompt), jax.random.PRNGKey(0),
               jnp.array([3, 6], jnp.int32))


class TestChunkedGeneration:
    """make_chunked_generate_fns: the streaming-serving building block —
    chunk-by-chunk emission with the cache carried between dispatches must
    reproduce make_generate_fn's token stream exactly."""

    def _stream(self, model, params, prompt, lens, *, chunk, total, **kw):
        from horovod_tpu.models.decoding import make_chunked_generate_fns

        start, cont = make_chunked_generate_fns(
            model, max_new_tokens=total, chunk=chunk, **kw
        )
        key = jax.random.PRNGKey(0)
        toks, state = start(params, jnp.asarray(prompt), key, jnp.asarray(lens))
        out = [np.asarray(toks)]
        for _ in range(total // chunk - 1):
            toks, state = cont(params, state)
            out.append(np.asarray(toks))
        return np.concatenate(out, axis=1), state

    def test_greedy_stream_matches_one_shot(self):
        model = _model()
        params = _params(model)
        lens = np.array([3, 8], np.int32)
        prompt = np.zeros((2, 8), np.int32)
        prompt[0, :3] = [3, 1, 4]
        prompt[1] = [9, 2, 6, 5, 3, 7, 1, 8]
        fn = make_generate_fn(model, max_new_tokens=12, include_prompt=False)
        want = np.asarray(
            fn(params, jnp.asarray(prompt), jax.random.PRNGKey(0),
               jnp.asarray(lens))
        )
        got, _ = self._stream(
            model, params, prompt, lens, chunk=4, total=12
        )
        np.testing.assert_array_equal(got, want)

    def test_sampled_stream_matches_one_shot(self):
        model = _model()
        params = _params(model)
        lens = np.array([5, 5], np.int32)
        prompt = np.asarray([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], np.int32)
        kw = dict(temperature=0.8, top_k=8)
        fn = make_generate_fn(
            model, max_new_tokens=10, include_prompt=False, **kw
        )
        want = np.asarray(
            fn(params, jnp.asarray(prompt), jax.random.PRNGKey(0),
               jnp.asarray(lens))
        )
        got, _ = self._stream(
            model, params, prompt, lens, chunk=5, total=10, **kw
        )
        np.testing.assert_array_equal(got, want)

    def test_eos_done_flag_and_fill(self):
        model = _model()
        params = _params(model)
        lens = np.array([4], np.int32)
        prompt = np.asarray([[5, 3, 2, 7]], np.int32)
        # Find a token the model emits, make it eos.
        probe = make_generate_fn(model, max_new_tokens=8, include_prompt=False)(
            params, jnp.asarray(prompt), jax.random.PRNGKey(0),
            jnp.asarray(lens),
        )
        eos = int(np.asarray(probe)[0, 1])
        got, state = self._stream(
            model, params, prompt, lens, chunk=4, total=8, eos_id=eos
        )
        want = np.asarray(
            make_generate_fn(
                model, max_new_tokens=8, include_prompt=False, eos_id=eos
            )(params, jnp.asarray(prompt), jax.random.PRNGKey(0),
              jnp.asarray(lens))
        )
        np.testing.assert_array_equal(got, want)
        assert bool(np.asarray(state[3])[0])  # done flag set

    def test_chunk_must_divide(self):
        from horovod_tpu.models.decoding import make_chunked_generate_fns

        with pytest.raises(ValueError, match="divide"):
            make_chunked_generate_fns(_model(), max_new_tokens=10, chunk=4)
