"""Model-zoo tests: architecture shapes, BatchNorm state threading, and the
heavier-gradients ResNet through the full distributed training path."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.models.cnn import MnistCNN
from horovod_tpu.models.resnet import ResNetCIFAR


class TestResNetArchitecture:
    def test_depth_validation(self):
        model = ResNetCIFAR(depth=21)
        with pytest.raises(ValueError, match="6n"):
            model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))

    def test_forward_shape_and_param_count(self):
        model = ResNetCIFAR(depth=20)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
        x = jnp.zeros((4, 32, 32, 3))
        logits = model.apply(variables, x)
        assert logits.shape == (4, 10)
        assert logits.dtype == jnp.float32
        n_params = sum(p.size for p in jax.tree.leaves(variables["params"]))
        # ResNet-20 CIFAR is ~0.27M params (He et al. table 6).
        assert 0.25e6 < n_params < 0.30e6, n_params

    def test_has_batch_stats(self):
        model = ResNetCIFAR(depth=8)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
        assert "batch_stats" in variables

    def test_bf16_compute_f32_logits(self):
        model = ResNetCIFAR(depth=8, compute_dtype=jnp.bfloat16)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
        logits = model.apply(variables, jnp.ones((2, 32, 32, 3)))
        assert logits.dtype == jnp.float32


@pytest.mark.slow
class TestResNetTraining:
    """The BASELINE.json config-4 path: ResNet through Trainer +
    DistributedOptimizer on the 8-device mesh."""

    def _trainer(self):
        return hvt.Trainer(
            ResNetCIFAR(depth=8),
            hvt.DistributedOptimizer(optax.adam(1e-2)),
            loss="sparse_categorical_crossentropy",
        )

    def _batch(self, n=16, seed=0):
        rng = np.random.RandomState(seed)
        return (
            rng.rand(n, 32, 32, 3).astype(np.float32),
            rng.randint(0, 10, size=n).astype(np.int64),
        )

    def test_batch_stats_update_and_loss_decreases(self):
        trainer = self._trainer()
        x, y = self._batch()
        state0 = trainer.build(x)
        assert state0.model_state is not None
        assert "batch_stats" in state0.model_state
        # Snapshot to host: the train step donates its input state, so
        # state0's device buffers are invalidated by fit().
        stats0 = jax.tree.leaves(jax.device_get(state0.model_state))

        history = trainer.fit(
            x=x, y=y, batch_size=2, epochs=3, steps_per_epoch=8, verbose=0
        )
        assert history[-1]["loss"] < history[0]["loss"]
        # Running statistics moved away from init (mean 0 / var 1).
        stats1 = jax.tree.leaves(jax.device_get(trainer.state.model_state))
        moved = any(
            float(jnp.abs(a - b).max()) > 1e-6 for a, b in zip(stats0, stats1)
        )
        assert moved

    def test_eval_uses_running_stats(self):
        trainer = self._trainer()
        x, y = self._batch(32)
        trainer.fit(x=x, y=y, batch_size=2, epochs=1, steps_per_epoch=4, verbose=0)
        result = trainer.evaluate(x, y, batch_size=2)
        assert np.isfinite(result["loss"])

    def test_checkpoint_roundtrip_covers_batch_stats(self, tmp_path):
        from horovod_tpu import checkpoint

        trainer = self._trainer()
        x, y = self._batch(8)
        trainer.fit(x=x, y=y, batch_size=1, epochs=1, steps_per_epoch=4, verbose=0)
        path = checkpoint.save(str(tmp_path / "ck.msgpack"), trainer.state)
        restored = checkpoint.restore(path, trainer.state)
        for a, b in zip(
            jax.tree.leaves(trainer.state.model_state),
            jax.tree.leaves(restored.model_state),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestMnistCNNStillParamsOnly:
    def test_no_model_state(self):
        trainer = hvt.Trainer(MnistCNN(), optax.adam(1e-3))
        x = np.zeros((8, 28, 28, 1), np.float32)
        state = trainer.build(x)
        assert state.model_state is None


@pytest.mark.slow
class TestViT:
    """The conv-free vision family: patchify + encoder blocks through the
    same Trainer/optimizer path as the CNNs."""

    def _model(self, **kw):
        from horovod_tpu.models.vit import ViT

        kw.setdefault("patch_size", 4)
        kw.setdefault("d_model", 32)
        kw.setdefault("n_heads", 4)
        kw.setdefault("n_layers", 2)
        kw.setdefault("dropout", 0.0)
        return ViT(**kw)

    def test_shapes_and_dtypes(self):
        import jax
        import jax.numpy as jnp

        model = self._model()
        x = jnp.zeros((2, 32, 32, 3), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        out = model.apply({"params": params}, x)
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32
        # uint8 input normalizes on device, same numerics path as the CNNs
        xu = jnp.zeros((2, 32, 32, 3), jnp.uint8)
        assert model.apply({"params": params}, xu).shape == (2, 10)

    def test_cls_pool_variant(self):
        import jax
        import jax.numpy as jnp

        model = self._model(pool="cls")
        x = jnp.zeros((2, 32, 32, 3), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        assert "cls" in params
        assert params["pos_embed"].shape == (1, 65, 32)  # 64 patches + cls
        assert model.apply({"params": params}, x).shape == (2, 10)

    def test_patch_divisibility_guard(self):
        import jax
        import jax.numpy as jnp
        import pytest as _pytest

        model = self._model(patch_size=5)
        with _pytest.raises(ValueError, match="divisible"):
            model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))

    def test_trains_on_synthetic_cifar(self):
        import jax
        import numpy as np
        import optax

        import horovod_tpu as hvt
        from horovod_tpu.data import datasets

        (x, y), _ = datasets.cifar10(cache_dir=None)
        x, y = x[:2048], y[:2048]
        trainer = hvt.Trainer(
            # patch 8 → T=16: each patch spans most of a grating period, so
            # the texture classes separate within a ~30 s CPU budget.
            self._model(patch_size=8),
            hvt.DistributedOptimizer(optax.adam(1e-3)),
            loss="sparse_categorical_crossentropy",
        )
        hist = trainer.fit(x=x, y=y, batch_size=64, epochs=8, verbose=0)
        assert hist[-1]["loss"] < hist[0]["loss"]
        assert hist[-1]["accuracy"] > 0.3  # 0.46 measured; noise margin
