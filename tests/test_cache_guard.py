"""`testing.cachecheck` — the poisoned-persistent-XLA-cache guard
(ISSUE 9 satellite; the twice-documented PR 5/PR 8 failure mode).

The guard has two halves, both wired into tests/conftest.py: a
session-start sweep that deletes definitionally-torn cache entries
(zero-byte / orphaned .tmp), and a failure-time matcher that appends the
actionable ``rm -rf tests/.jax_cache`` hint to any failure whose text
looks like a torn-entry deserialization — instead of letting the
operator chase a phantom numeric mismatch.
"""

import pytest

from horovod_tpu.testing import cachecheck


class TestSignatureMatching:
    CACHE = "/repo/tests/.jax_cache"

    @pytest.mark.parametrize("text", [
        "jaxlib.xla_extension.XlaRuntimeError: INTERNAL: Failed to "
        "deserialize the executable",
        "RuntimeError: error loading program from compilation cache",
        "Deserialization failed: invalid flatbuffer",
        "zlib.error: Error -3 while decompressing data",
        "DATA LOSS: truncated entry",
    ])
    def test_deserialization_shapes_match(self, text):
        advice = cachecheck.poisoned_cache_advice(text, self.CACHE)
        assert advice is not None
        assert f"rm -rf {self.CACHE}" in advice

    @pytest.mark.parametrize("text", [
        "AssertionError: arrays are not almost equal",
        "ValueError: shapes (3,) and (4,) not aligned",
        "TimeoutError: supervisor gave up",
    ])
    def test_ordinary_failures_do_not_match(self, text):
        assert cachecheck.poisoned_cache_advice(text, self.CACHE) is None

    def test_no_cache_dir_no_advice(self):
        assert cachecheck.poisoned_cache_advice(
            "Failed to deserialize the executable", None
        ) is None


class TestCacheDirFromEnv:
    def test_reads_dir(self):
        env = {"JAX_COMPILATION_CACHE_DIR": "/x/cache"}
        assert cachecheck.cache_dir_from_env(env) == "/x/cache"

    def test_disable_flag_wins(self):
        env = {
            "JAX_COMPILATION_CACHE_DIR": "/x/cache",
            "JAX_ENABLE_COMPILATION_CACHE": "0",
        }
        assert cachecheck.cache_dir_from_env(env) is None

    def test_unset_is_none(self):
        assert cachecheck.cache_dir_from_env({}) is None


class TestTornEntrySweep:
    def _populate(self, d):
        (d / "sub").mkdir()
        good = d / "sub" / "entry_ok"
        good.write_bytes(b"x" * 64)
        torn = d / "sub" / "entry_torn"
        torn.write_bytes(b"")
        tmp = d / "entry.tmp.1234"
        tmp.write_bytes(b"partial")
        return good, torn, tmp

    def test_scan_finds_only_torn(self, tmp_path):
        good, torn, tmp = self._populate(tmp_path)
        found = cachecheck.scan_cache_dir(str(tmp_path))
        assert str(torn) in found and str(tmp) in found
        assert str(good) not in found

    def test_remove_deletes_and_reports(self, tmp_path):
        good, torn, tmp = self._populate(tmp_path)
        removed = cachecheck.remove_torn_entries(str(tmp_path))
        assert sorted(removed) == sorted([str(torn), str(tmp)])
        assert good.exists() and not torn.exists() and not tmp.exists()
        # Second sweep is a no-op.
        assert cachecheck.remove_torn_entries(str(tmp_path)) == []

    def test_missing_dir_is_quiet(self, tmp_path):
        assert cachecheck.scan_cache_dir(str(tmp_path / "nope")) == []
        assert cachecheck.remove_torn_entries(None) == []
