"""Trainer end-to-end on the virtual 8-chip mesh: the full Horovod capability
set (bootstrap → sharded batch → pmean'd grads → update → callbacks) in one
jitted step (SURVEY.md §7.2 step 3's aha moment, minus real hardware)."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.models import MnistCNN


def make_data(n=256, seed=0):
    from horovod_tpu.data.datasets import _synth_mnist_split

    x, y = _synth_mnist_split(n, seed=seed)
    return (x[..., None] / 255.0).astype(np.float32), y


@pytest.fixture(scope="module")
def trained():
    hvt.init()
    x, y = make_data()
    trainer = hvt.Trainer(
        MnistCNN(),
        hvt.DistributedOptimizer(optax.adam(1e-3)),
        loss="sparse_categorical_crossentropy",
        seed=0,
    )
    history = trainer.fit(x=x, y=y, batch_size=4, epochs=5)
    return trainer, history, (x, y)


def test_loss_decreases(trained):
    _, history, _ = trained
    assert history[-1]["loss"] < history[0]["loss"]


def test_memorizes_small_set(trained):
    trainer, _, (x, y) = trained
    m = trainer.evaluate(x, y, batch_size=4)
    assert m["accuracy"] > 0.5  # 256 samples, 5 epochs: well above chance


def test_eval_handles_ragged_tail(trained):
    trainer, _, (x, y) = trained
    # 100 examples with global batch 32 -> padded tail; metrics must be exact
    full = trainer.evaluate(x[:100], y[:100], batch_size=4)
    manual_probs = trainer.predict(x[:100], batch_size=4)
    manual_acc = float((manual_probs.argmax(-1) == y[:100]).mean())
    assert full["accuracy"] == pytest.approx(manual_acc, abs=1e-6)


def test_predict_shape_and_normalization(trained):
    trainer, _, (x, _) = trained
    probs = trainer.predict(x[:33], batch_size=4)
    assert probs.shape == (33, 10)
    np.testing.assert_allclose(probs.sum(-1), np.ones(33), rtol=1e-4)


def test_onehot_loss_path():
    """mnist_keras.py:89 categorical_crossentropy + one-hot labels path."""
    hvt.init()
    x, y = make_data(64, seed=1)
    y1h = np.eye(10, dtype=np.float32)[y]
    trainer = hvt.Trainer(
        MnistCNN(),
        hvt.DistributedOptimizer(optax.adadelta(learning_rate=hvt.scale_lr(1.0))),
        loss="categorical_crossentropy",
    )
    hist = trainer.fit(x=x, y=y1h, batch_size=8, epochs=2)
    assert np.isfinite(hist[-1]["loss"])
    m = trainer.evaluate(x, y1h, batch_size=8)
    assert 0.0 <= m["accuracy"] <= 1.0


def test_dataset_idiom_with_steps_per_epoch():
    """TF2-script idiom: batched repeating dataset + steps_per_epoch=500//size
    (tensorflow2_keras_mnist.py:96)."""
    from horovod_tpu.data.loader import ArrayDataset

    hvt.init()
    x, y = make_data(128, seed=2)
    ds = ArrayDataset((x, y)).repeat().shuffle(128).batch(32)
    trainer = hvt.Trainer(MnistCNN(), hvt.DistributedOptimizer(optax.adam(1e-3)))
    steps = hvt.shard_steps(80)  # 80 // 8 = 10
    assert steps == 10
    hist = trainer.fit(ds, epochs=2, steps_per_epoch=steps)
    assert len(hist) == 2


def test_update_scale_controls_effective_lr():
    """The warmup knob: scale=0 must freeze parameters."""
    hvt.init()
    x, y = make_data(32, seed=3)
    trainer = hvt.Trainer(MnistCNN(), hvt.DistributedOptimizer(optax.adam(1e-2)))
    import jax

    trainer.build(x)
    before = jax.device_get(trainer.state.params)
    trainer.fit(x=x, y=y, batch_size=4, epochs=1, callbacks=[_FreezeScale()])
    after = jax.device_get(trainer.state.params)
    assert all(
        np.allclose(a, b)
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after))
    )


class _FreezeScale(hvt.callbacks.Callback):
    def on_epoch_begin(self, epoch, logs=None):
        self.trainer.update_scale = 0.0


class TestShardUpdate:
    """ZeRO-1 / cross-replica weight-update sharding (arXiv:2004.13336):
    replicated model, optimizer state sharded over the data axis — same
    math as pure DP, ~1/dp per-device optimizer memory."""

    def _data(self):
        from horovod_tpu.data import datasets

        (x, y), _ = datasets.mnist(cache_dir=None)
        return x[:256, ..., None], y[:256].astype(np.int32)

    def _trainer(self, **kw):
        from horovod_tpu.models.cnn import MnistCNN

        return hvt.Trainer(
            MnistCNN(),
            hvt.DistributedOptimizer(optax.adam(1e-3)),
            loss="sparse_categorical_crossentropy",
            **kw,
        )

    @pytest.mark.slow
    def test_matches_plain_dp_and_stays_sharded(self):
        import jax

        x, y = self._data()
        plain = self._trainer()
        zero1 = self._trainer(shard_update=True)
        h1 = plain.fit(x=x, y=y, batch_size=8, epochs=2, verbose=0)
        h2 = zero1.fit(x=x, y=y, batch_size=8, epochs=2, verbose=0)
        assert abs(h1[-1]["loss"] - h2[-1]["loss"]) < 1e-5
        for a, b in zip(
            jax.tree.leaves(plain.state.params),
            jax.tree.leaves(zero1.state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
            )
        # The sharding survives the donated training steps.
        specs = {
            str(l.sharding.spec)
            for l in jax.tree.leaves(zero1.state.opt_state)
            if hasattr(l, "sharding") and l.ndim > 0
        }
        assert any("data" in s for s in specs), specs

    def test_per_device_optimizer_memory_shrinks(self):
        import jax

        x, y = self._data()
        zero1 = self._trainer(shard_update=True)
        zero1.build(x[:8])
        dp = zero1.mesh.shape["data"]
        assert dp == 8

        def fleet_bytes(tree):
            # ALL shards, replicas included: replicated state costs
            # dp × global here, sharded state ≈ 1 × global — so the bound
            # below actually fails if sharding regresses.
            total = 0
            for l in jax.tree.leaves(tree):
                if isinstance(l, jax.Array):
                    total += sum(
                        int(np.prod(sh.data.shape)) * l.dtype.itemsize
                        for sh in l.addressable_shards
                    )
            return total

        global_bytes = sum(
            int(np.prod(l.shape)) * l.dtype.itemsize
            for l in jax.tree.leaves(zero1.state.opt_state)
            if isinstance(l, jax.Array)
        )
        # Sharded leaves cost one global copy across the fleet; a fully-
        # replicated state would cost dp ×. Slack covers the replicated
        # scalar/odd-shaped leaves.
        assert fleet_bytes(zero1.state.opt_state) < 0.35 * dp * global_bytes

    def test_guards(self):
        from horovod_tpu.models.transformer import param_specs

        with pytest.raises(ValueError, match="fsdp"):
            self._trainer(shard_update=True, param_specs=param_specs)
        from horovod_tpu.models.cnn import MnistCNN

        # Wire compression COMPOSES with shard_update since ISSUE 10
        # (the explicit step reduces into the sharded layout; see
        # tests/test_zero1_compose.py for the equivalence matrix).
        tr = hvt.Trainer(
            MnistCNN(),
            hvt.DistributedOptimizer(
                optax.adam(1e-3), compression="bf16"
            ),
            loss="sparse_categorical_crossentropy",
            shard_update=True,
        )
        assert tr._comm_dtype is not None and tr._scatter > 1


class TestModuleLossBuildHint:
    """Regression for the ADVICE build() fallback: with loss='module' and no
    sample_y, labels are synthesized as zeros_like(sample_x) (the LM-family
    contract); a module whose labels differ in dtype/shape fails deep inside
    init — the re-raise must name the fix (pass sample_y)."""

    def _module(self):
        import flax.linen as nn
        import jax

        class IntLabelLoss(nn.Module):
            @nn.compact
            def __call__(self, x, train=False, labels=None):
                logits = nn.Dense(4)(x)
                ll = jax.nn.log_softmax(logits)
                # take_along_axis requires integer labels — the zeros_like
                # float fallback must blow up here.
                loss = -jnp.take_along_axis(ll, labels[:, None], axis=-1)[:, 0]
                correct = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
                return loss, correct

        return IntLabelLoss()

    def test_synthesized_labels_failure_carries_hint(self):
        trainer = hvt.Trainer(
            self._module(),
            hvt.DistributedOptimizer(optax.adam(1e-3)),
            loss="module",
        )
        x = np.random.RandomState(0).rand(4, 8).astype(np.float32)
        with pytest.raises(Exception, match="pass sample_y"):
            trainer.build(x)

    def test_sample_y_builds_fine(self):
        trainer = hvt.Trainer(
            self._module(),
            hvt.DistributedOptimizer(optax.adam(1e-3)),
            loss="module",
        )
        x = np.random.RandomState(0).rand(4, 8).astype(np.float32)
        y = np.zeros(4, np.int64)
        state = trainer.build(x, y)
        assert state is trainer.state
