"""Test harness: 8 fake CPU devices — the reference's
"multi-process-without-a-cluster" test mode (SURVEY.md §4 implication (b)),
TPU-native style: pmap/pjit/shard_map collectives run unmodified on a
virtual 8-device mesh, so distributed semantics are unit-testable anywhere.
"""

import os
import sys

# Must run before jax initializes its backends (conftest imports precede
# test-module imports under pytest). Env vars alone are not enough in this
# image: a sitecustomize hook registers the TPU platform and rewrites the
# jax_platforms config at interpreter start, so override the config directly.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache, shared by every test AND every
# subprocess test (they inherit the env): the suite is compile-dominated,
# and a warm cache measured 1.8x on the heaviest file. Keyed by HLO +
# compile options, so stale-cache wrongness is not a failure mode; safe to
# delete any time. Override by exporting JAX_COMPILATION_CACHE_DIR to
# another path; export it EMPTY to disable entirely (mapped to
# JAX_ENABLE_COMPILATION_CACHE=0 below — jax itself would treat '' as a
# cwd-relative cache dir, not as off).
#
# CAVEAT — killed children: a subprocess test that SIGKILLs/os._exit()s a
# training child (resume/fault-injection e2e) can tear or race a cache
# write, and on older jax a poisoned entry later deserializes into a
# SEGFAULT or a silently WRONG executable (observed: an EMA shadow off by
# exactly the decay factor). Tests that kill children mid-run must set
# JAX_ENABLE_COMPILATION_CACHE=0 in the child env (the supervisor/fault
# tests do); if an inexplicable numeric failure appears after such runs,
# delete this cache dir first.
if os.environ.get("JAX_COMPILATION_CACHE_DIR") == "":
    del os.environ["JAX_COMPILATION_CACHE_DIR"]
    os.environ["JAX_ENABLE_COMPILATION_CACHE"] = "0"
elif os.environ.get("JAX_ENABLE_COMPILATION_CACHE") != "0":
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax: no such config option — the XLA_FLAGS fallback above
    # (xla_force_host_platform_device_count) already provides the devices.
    pass

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import pytest  # noqa: E402

from horovod_tpu.testing import cachecheck  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running end-to-end tests")
    config.addinivalue_line(
        "markers", "ci_job: full CI-gated convergence runs (several minutes)"
    )
    # Guard 1 for the twice-documented poisoned-cache failure mode (the
    # CAVEAT above): a zero-byte or orphaned-.tmp cache entry is
    # definitionally torn (its atomic rename never completed) — delete it
    # before it can deserialize into a SEGFAULT or a silently wrong
    # executable mid-suite.
    removed = cachecheck.remove_torn_entries(
        cachecheck.cache_dir_from_env()
    )
    if removed:
        print(
            f"\n[conftest] removed {len(removed)} torn persistent-XLA-"
            f"cache entr{'y' if len(removed) == 1 else 'ies'} "
            "(zero-byte/.tmp — a killed child interrupted the write):\n"
            + "\n".join(f"  {p}" for p in removed)
        )


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Guard 2: when a test fails with the torn-cache deserialization
    signature, attach the actionable `rm -rf tests/.jax_cache` hint to
    the report instead of leaving the operator to chase phantom numeric
    mismatches (the documented PR 5/PR 8 time sink)."""
    outcome = yield
    report = outcome.get_result()
    if report.when != "call" or not report.failed:
        return
    advice = cachecheck.poisoned_cache_advice(
        str(report.longrepr), cachecheck.cache_dir_from_env()
    )
    if advice:
        report.sections.append(("poisoned XLA cache?", advice))


@pytest.fixture(scope="session")
def tmp_cache(tmp_path_factory):
    d = tmp_path_factory.mktemp("hvt_cache")
    os.environ["HVT_DATA_DIR"] = str(d)
    return d
