"""Transformer LM over the full mesh: dp × seq × model composition, TP param
shardings, ring/Ulysses attention inside the training step, long-range
recall actually learned."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.data import datasets
from horovod_tpu.models.transformer import (
    ShardingConfig,
    TransformerLM,
    param_specs,
)
from horovod_tpu.parallel import mesh as mesh_lib

VOCAB = 32


def _model(mesh=None, attn="ring", **kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("d_model", 64)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("dropout", 0.0)
    return TransformerLM(sharding=ShardingConfig(mesh=mesh, attn=attn), **kw)


def _trainer(mesh, attn="ring"):
    return hvt.Trainer(
        _model(mesh=mesh, attn=attn),
        hvt.DistributedOptimizer(optax.adam(3e-3)),
        loss="sparse_categorical_crossentropy",
        mesh=mesh,
        param_specs=param_specs,
        batch_specs=(P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq")),
    )


class TestForward:
    def test_logit_shape_unsharded(self):
        model = _model()
        tokens = jnp.zeros((2, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        logits = model.apply({"params": params}, tokens)
        assert logits.shape == (2, 16, VOCAB)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not change past logits."""
        model = _model()
        rng = np.random.RandomState(0)
        toks = rng.randint(1, VOCAB, size=(1, 16)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0), jnp.asarray(toks))["params"]
        out1 = model.apply({"params": params}, jnp.asarray(toks))
        toks2 = toks.copy()
        toks2[0, 10] = (toks2[0, 10] % (VOCAB - 1)) + 1
        out2 = model.apply({"params": params}, jnp.asarray(toks2))
        np.testing.assert_allclose(
            np.asarray(out1[0, :10]), np.asarray(out2[0, :10]), atol=1e-5
        )


@pytest.mark.slow
class TestMeshComposition:
    """dp=2 × seq=2 × model=2 on the 8 virtual devices — every parallelism
    axis live in one training step."""

    def _mesh(self):
        return mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=2, model=2))

    @pytest.mark.parametrize("attn", ["ring", "ulysses"])
    def test_train_step_runs_and_learns(self, attn):
        mesh = self._mesh()
        trainer = _trainer(mesh, attn=attn)
        x, y = datasets.copy_task(512, 32, vocab_size=VOCAB, seed=0)
        history = trainer.fit(
            x=x, y=y, batch_size=8, epochs=2, steps_per_epoch=10, verbose=0
        )
        assert history[-1]["loss"] < history[0]["loss"]
        assert np.isfinite(history[-1]["loss"])

    def test_params_are_tp_sharded(self):
        mesh = self._mesh()
        trainer = _trainer(mesh)
        x, _ = datasets.copy_task(8, 32, vocab_size=VOCAB)
        state = trainer.build(x)
        flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
        tp_sharded = [
            (path, leaf) for path, leaf in flat
            if any(
                "model" in (ax if isinstance(ax, tuple) else (ax,))
                for ax in leaf.sharding.spec if ax is not None
            )
        ]
        # QKV, proj, MLP up/down per layer + LM head must carry the model axis.
        assert len(tp_sharded) >= 4 * 2 + 1, [p for p, _ in flat]
        # Optimizer mirrors inherit the layout (adam mu for a TP kernel).
        opt_flat = jax.tree_util.tree_flatten_with_path(state.opt_state)[0]
        opt_tp = [
            1 for _, leaf in opt_flat
            if hasattr(leaf, "sharding")
            and any(
                "model" in (ax if isinstance(ax, tuple) else (ax,))
                for ax in getattr(leaf.sharding, "spec", P()) if ax is not None
            )
        ]
        assert len(opt_tp) >= 2 * (4 * 2 + 1)  # mu and nu trees

    def test_pure_dp_mesh_uses_flash_in_shard_map(self):
        """seq=1 multi-device mesh: the local flash kernel must run inside a
        manual shard_map (GSPMD can't partition a Mosaic call) and train."""
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        trainer = _trainer(mesh)
        x, y = datasets.copy_task(256, 32, vocab_size=VOCAB, seed=5)
        history = trainer.fit(
            x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=6, verbose=0
        )
        assert np.isfinite(history[-1]["loss"])

    def test_dense_attn_option(self):
        """attn='dense' on an unsharded model takes the reference path."""
        model = _model(attn="dense")
        tokens = jnp.zeros((2, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        assert model.apply({"params": params}, tokens).shape == (2, 16, VOCAB)

    def test_seq_parallel_rejects_dense(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        model = _model(mesh=mesh, attn="dense")
        with pytest.raises(ValueError, match="ring"):
            model.init(jax.random.PRNGKey(0), jnp.zeros((2, 32), jnp.int32))

    def test_evaluate_per_token_loss_with_padding(self):
        """evaluate() on a sequence model: per-token [G,T] losses weighted by
        the per-example padding mask, counted in tokens."""
        mesh = self._mesh()
        trainer = _trainer(mesh)
        x, y = datasets.copy_task(20, 32, vocab_size=VOCAB)  # 20 % 16 != 0 → padding
        trainer.build(x)
        result = trainer.evaluate(x, y, batch_size=4)
        assert np.isfinite(result["loss"])
        assert 0.0 <= result["accuracy"] <= 1.0

    def test_matches_unsharded_forward(self):
        """The sharded model must compute the same function."""
        mesh = self._mesh()
        sharded = _model(mesh=mesh)
        plain = _model()
        rng = np.random.RandomState(1)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(4, 32)).astype(np.int32))
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        out_plain = plain.apply({"params": params}, toks)
        out_sharded = jax.jit(
            lambda p, t: sharded.apply({"params": p}, t)
        )(params, toks)
        np.testing.assert_allclose(
            np.asarray(out_plain), np.asarray(out_sharded), rtol=5e-4, atol=5e-4
        )


@pytest.mark.slow
class TestFSDP:
    """fsdp > 1 exercised for real: parameters and optimizer mirrors sharded
    over the fsdp axis, and the training math identical to pure DP — FSDP is
    a memory layout, not a different algorithm."""

    def test_params_and_opt_state_fsdp_sharded(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, fsdp=2, model=2))
        trainer = _trainer(mesh)
        x, _ = datasets.copy_task(8, 32, vocab_size=VOCAB)
        state = trainer.build(x)

        def fsdp_leaves(tree):
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
            return [
                path for path, leaf in flat
                if hasattr(leaf, "sharding")
                and any(
                    "fsdp" in (ax if isinstance(ax, tuple) else (ax,))
                    for ax in getattr(leaf.sharding, "spec", P())
                    if ax is not None
                )
            ]

        # Every >=2D kernel has an fsdp-shardable dim at these sizes: all
        # transformer matmul weights (2 layers x 4 + lm_head + embed).
        assert len(fsdp_leaves(state.params)) >= 4 * 2 + 1
        # Optimizer mirrors (adam mu/nu) carry the same layout.
        assert len(fsdp_leaves(state.opt_state)) >= 2 * (4 * 2 + 1)

    def test_fsdp_matches_pure_dp_math(self):
        """Same data, same seed: a data=2 x fsdp=2 x model=2 run must produce
        the same parameters as data=8 pure DP."""

        def run(mesh):
            trainer = _trainer(mesh)
            x, y = datasets.copy_task(256, 32, vocab_size=VOCAB, seed=4)
            trainer.fit(
                x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=6,
                shuffle_buffer=1, verbose=0,
            )
            leaves = jax.tree.leaves(jax.device_get(trainer.state.params))
            return float(sum(np.abs(l).sum() for l in leaves))

        d_fsdp = run(mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, fsdp=2, model=2)))
        d_dp = run(mesh_lib.build_mesh(mesh_lib.MeshSpec(data=8)))
        # Tolerance: different mesh layouts reduce in different orders, and
        # 6 adam steps amplify that float noise (measured ~2e-4 rel); a real
        # sharding bug (wrong gather/reduce) diverges by orders of magnitude.
        assert d_fsdp == pytest.approx(d_dp, rel=1e-3)

    def test_fsdp4_train_step(self):
        """The example's HVT_MESH='data=2,fsdp=4' shape trains."""
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, fsdp=4))
        trainer = _trainer(mesh)
        x, y = datasets.copy_task(128, 32, vocab_size=VOCAB, seed=6)
        history = trainer.fit(
            x=x, y=y, batch_size=2, epochs=1, steps_per_epoch=4, verbose=0
        )
        assert np.isfinite(history[-1]["loss"])


@pytest.mark.slow
class TestMemoryKnobs:
    """Long-context memory options: remat must not change the math,
    bf16 logits must keep an f32-accurate loss through the upcasting
    built into the named losses."""

    def _tokens(self, seed=0):
        rng = np.random.RandomState(seed)
        return jnp.asarray(rng.randint(0, VOCAB, (2, 16)), jnp.int32)

    def test_remat_is_numerically_invisible(self):
        toks = self._tokens()
        base = _model()
        remat = _model(remat=True)
        params = base.init(jax.random.PRNGKey(0), toks, train=False)["params"]

        def loss(m, p):
            logits = m.apply({"params": p}, toks, train=True,
                             rngs={"dropout": jax.random.PRNGKey(1)})
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, toks
            ).mean()

        l0, g0 = jax.value_and_grad(lambda p: loss(base, p))(params)
        l1, g1 = jax.value_and_grad(lambda p: loss(remat, p))(params)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    def test_remat_invisible_with_dropout(self):
        """RNG lifting through the remat boundary: the backward-pass
        recomputation must fold in the SAME dropout keys, or remat silently
        changes training math for any dropout>0 user."""
        toks = self._tokens(2)
        base = _model(dropout=0.3)
        remat = _model(dropout=0.3, remat=True)
        params = base.init(jax.random.PRNGKey(0), toks, train=False)["params"]

        def loss(m, p):
            logits = m.apply({"params": p}, toks, train=True,
                             rngs={"dropout": jax.random.PRNGKey(7)})
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, toks
            ).mean()

        l0, g0 = jax.value_and_grad(lambda p: loss(base, p))(params)
        l1, g1 = jax.value_and_grad(lambda p: loss(remat, p))(params)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    def test_bf16_logits_loss_close_to_f32(self):
        toks = self._tokens(1)
        f32 = _model()
        bf16 = _model(logits_dtype=jnp.bfloat16)
        params = f32.init(jax.random.PRNGKey(0), toks, train=False)["params"]
        from horovod_tpu.training.trainer import _resolve_loss

        loss_fn = _resolve_loss("sparse_categorical_crossentropy")

        def loss(m, p):
            logits = m.apply({"params": p}, toks, train=False)
            return float(loss_fn(logits, toks).mean())

        assert bf16.apply({"params": params}, toks, train=False).dtype == jnp.bfloat16
        # bf16 rounding of the logits themselves bounds the difference;
        # the logsumexp math runs in f32 via the loss upcast.
        assert abs(loss(f32, params) - loss(bf16, params)) < 2e-2

    def test_remat_trains_through_trainer(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, seq=2))
        trainer = hvt.Trainer(
            _model(mesh=mesh, remat=True, logits_dtype=jnp.bfloat16),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=param_specs,
            batch_specs=(P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq")),
        )
        x, y = datasets.copy_task(8, 16, vocab_size=VOCAB)
        hist = trainer.fit(x=x, y=y, batch_size=4, epochs=2)
        assert np.isfinite(hist[-1]["loss"])
        assert hist[-1]["loss"] <= hist[0]["loss"] * 1.5  # sane training


@pytest.mark.slow
class TestLongRangeRecall:
    def test_copy_task_learned_through_ring(self):
        """The functional long-context check: recall-half loss → small, which
        is impossible without cross-shard attention (the copied token sits
        T/2 positions back, on a different seq shard)."""
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        trainer = hvt.Trainer(
            _model(mesh=mesh, d_model=128, n_layers=2),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            mesh=mesh,
            param_specs=param_specs,
            batch_specs=(P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq")),
        )
        x, y = datasets.copy_task(2048, 32, vocab_size=VOCAB, seed=2)
        trainer.fit(x=x, y=y, batch_size=16, epochs=3, steps_per_epoch=16, verbose=0)

        # Per-position loss on held-out sequences.
        xt, yt = datasets.copy_task(64, 32, vocab_size=VOCAB, seed=99)
        logits = np.log(trainer.predict(xt, batch_size=8) + 1e-9)
        ll = np.take_along_axis(logits, yt[..., None], axis=-1)[..., 0]
        recall_loss = -ll[:, 16:].mean()  # second half: pure recall
        first_loss = -ll[:, :14].mean()   # first half: irreducible ~log V
        assert recall_loss < first_loss * 0.5, (recall_loss, first_loss)


@pytest.mark.slow
class TestPackedSequences:
    """Packing invariance — the semantic contract of segment_ids: a document
    packed next to others must produce EXACTLY the logits it produces alone
    (segment-masked attention + per-document RoPE restart)."""

    def test_packed_positions(self):
        from horovod_tpu.models.transformer import packed_positions

        ids = jnp.asarray([[0, 0, 0, 1, 1, 2, 2, 2]])
        np.testing.assert_array_equal(
            np.asarray(packed_positions(ids)),
            [[0, 1, 2, 0, 1, 0, 1, 2]],
        )

    def test_packing_invariance_local(self):
        model = _model()  # no mesh: local flash/dense path
        rng = np.random.RandomState(7)
        doc_a = rng.randint(1, VOCAB, size=(1, 16)).astype(np.int32)
        doc_b = rng.randint(1, VOCAB, size=(1, 16)).astype(np.int32)
        packed = jnp.asarray(np.concatenate([doc_a, doc_b], axis=1))
        seg = jnp.asarray(
            np.concatenate([np.zeros((1, 16)), np.ones((1, 16))], axis=1)
        ).astype(jnp.int32)
        params = model.init(jax.random.PRNGKey(0), packed)["params"]
        out_packed = model.apply(
            {"params": params}, packed, segment_ids=seg
        )
        out_a = model.apply({"params": params}, jnp.asarray(doc_a))
        out_b = model.apply({"params": params}, jnp.asarray(doc_b))
        np.testing.assert_allclose(
            np.asarray(out_packed[0, :16]), np.asarray(out_a[0]),
            rtol=1e-4, atol=1e-4,
        )
        np.testing.assert_allclose(
            np.asarray(out_packed[0, 16:]), np.asarray(out_b[0]),
            rtol=1e-4, atol=1e-4,
        )

    def test_packed_seq_parallel_matches_local(self):
        """The ring path on a live seq axis computes the same packed logits
        as the local path (ids riding the ring)."""
        from horovod_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        rng = np.random.RandomState(8)
        toks = rng.randint(1, VOCAB, size=(2, 32)).astype(np.int32)
        seg = np.repeat(np.arange(4), 8)[None].repeat(2, 0).astype(np.int32)
        local = _model()
        params = local.init(jax.random.PRNGKey(1), jnp.asarray(toks))["params"]
        ref = local.apply(
            {"params": params}, jnp.asarray(toks), segment_ids=jnp.asarray(seg)
        )
        ring = _model(mesh=mesh, attn="ring")
        with mesh:
            got = jax.jit(
                lambda p, t, s: ring.apply(
                    {"params": p}, t, segment_ids=s
                )
            )(params, jnp.asarray(toks), jnp.asarray(seg))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
        )


@pytest.mark.slow
class TestGQA:
    """Grouped-query attention (n_kv_heads < n_heads): K/V heads shared by
    groups of query heads. The load-bearing equivalence: a GQA model must
    compute exactly what an MHA model computes when the MHA qkv kernel is
    assembled from the GQA projections with K/V repeated per group — the
    repeat is the definition of GQA."""

    def _gqa(self, mesh=None, attn="flash", **kw):
        return _model(mesh=mesh, attn=attn, n_heads=4, n_kv_heads=2, **kw)

    def _toks(self, seed=81, shape=(2, 16)):
        return jnp.asarray(
            np.random.RandomState(seed).randint(1, VOCAB, size=shape),
            jnp.int32,
        )

    def test_param_layout(self):
        toks = self._toks()
        gqa = self._gqa()
        params = gqa.init(jax.random.PRNGKey(0), toks)["params"]
        blk = params["Block_0"]
        assert "q_proj" in blk and "kv_proj" in blk and "qkv" not in blk
        assert blk["kv_proj"]["kernel"].shape == (64, 2, 32)  # [d, H_kv, 2hd]
        # MHA default keeps the fused layout (checkpoint compatibility)
        mha = _model()
        mp = mha.init(jax.random.PRNGKey(0), toks)["params"]
        assert "qkv" in mp["Block_0"] and "q_proj" not in mp["Block_0"]

    def test_equals_mha_with_repeated_kv(self):
        toks = self._toks(82)
        gqa = self._gqa()
        params = gqa.init(jax.random.PRNGKey(0), toks)["params"]
        rep = 2  # 4 heads / 2 kv heads

        def to_mha(block):
            out = dict(block)
            qk = out.pop("q_proj")["kernel"]          # [d, H, hd]
            kvk = out.pop("kv_proj")["kernel"]        # [d, H_kv, 2hd]
            kk, vk = np.split(np.asarray(kvk), 2, axis=-1)
            kk = np.repeat(kk, rep, axis=1)
            vk = np.repeat(vk, rep, axis=1)
            out["qkv"] = {
                "kernel": jnp.asarray(
                    np.concatenate([np.asarray(qk), kk, vk], axis=-1)
                )
            }
            return out

        mha_params = {
            k: (to_mha(v) if k.startswith("Block_") else v)
            for k, v in params.items()
        }
        out_gqa = self._gqa().apply({"params": params}, toks)
        out_mha = _model(attn="flash", n_heads=4).apply(
            {"params": mha_params}, toks
        )
        np.testing.assert_allclose(
            np.asarray(out_gqa), np.asarray(out_mha), rtol=1e-5, atol=1e-5
        )

    def test_ring_matches_unsharded(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=2, model=2))
        toks = self._toks(83, (4, 32))
        plain = self._gqa()
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        out_plain = plain.apply({"params": params}, toks)
        out_sh = jax.jit(
            lambda p, t: self._gqa(mesh=mesh, attn="ring").apply(
                {"params": p}, t
            )
        )(params, toks)
        np.testing.assert_allclose(
            np.asarray(out_sh), np.asarray(out_plain), rtol=2e-4, atol=2e-4
        )

    def test_indivisible_heads_rejected(self):
        toks = self._toks(84)
        with pytest.raises(ValueError, match="n_kv_heads"):
            _model(n_heads=4, n_kv_heads=3).init(jax.random.PRNGKey(0), toks)

    def test_kv_heads_must_divide_model_axis(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, model=4))
        toks = self._toks(85)
        model = _model(mesh=mesh, attn="flash", n_heads=8, n_kv_heads=2)
        with pytest.raises(ValueError, match="n_kv_heads"):
            model.init(jax.random.PRNGKey(0), toks)

    def test_trains(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=8))
        trainer = hvt.Trainer(
            self._gqa(mesh=mesh, attn="ring"),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=param_specs,
            batch_specs=(P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq")),
        )
        x, y = datasets.copy_task(256, 16, vocab_size=VOCAB, seed=3)
        hist = trainer.fit(
            x=x, y=y, batch_size=4, epochs=2, steps_per_epoch=6, verbose=0
        )
        assert np.isfinite(hist[-1]["loss"])
        assert hist[-1]["loss"] < hist[0]["loss"]


@pytest.mark.slow
class TestSlidingWindow:
    """TransformerLM(window=W): local attention end-to-end — every
    sequence-parallel impl must agree with the dense-windowed reference,
    and a windowed model must train."""

    def _toks(self, b=2, t=32, seed=0):
        return jnp.asarray(
            np.random.RandomState(seed).randint(0, VOCAB, (b, t)), jnp.int32
        )

    def test_impls_agree_with_dense(self):
        toks = self._toks()
        dense = _model(attn="dense", window=7)
        params = dense.init(jax.random.PRNGKey(0), toks)["params"]
        want = dense.apply({"params": params}, toks)
        # local flash path (no live seq axis)
        got_local = _model(window=7).apply({"params": params}, toks)
        np.testing.assert_allclose(
            np.asarray(got_local), np.asarray(want), rtol=2e-5, atol=2e-5
        )
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        for attn in ("ring", "ulysses"):
            got = _model(mesh=mesh, attn=attn, window=7).apply(
                {"params": params}, toks
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
                err_msg=attn,
            )

    def test_window_binds(self):
        toks = self._toks(seed=1)
        full = _model()
        params = full.init(jax.random.PRNGKey(0), toks)["params"]
        a = full.apply({"params": params}, toks)
        b = _model(window=4).apply({"params": params}, toks)
        assert float(jnp.abs(a - b).max()) > 1e-3

    def test_windowed_model_trains_on_seq_mesh(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=2, model=2))
        trainer = hvt.Trainer(
            _model(mesh=mesh, attn="ring", window=8),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=param_specs,
            batch_specs=(P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq")),
        )
        x, y = datasets.copy_task(8, 32, vocab_size=VOCAB)
        state = trainer.build(x)
        zero = trainer.zero_metrics()
        losses = []
        for _ in range(4):
            state, metrics, _ = trainer._train_step(
                state, trainer._shard((x, y)), np.float32(1.0), zero
            )
            losses.append(float(metrics["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


@pytest.mark.slow
class TestGlobalLocalOnMesh:
    """window + attention_sinks through the ring on a live seq mesh: the
    global+local model must match the dense reference, and train."""

    def test_ring_sinks_match_dense(self):
        toks = jnp.asarray(
            np.random.RandomState(5).randint(0, VOCAB, (2, 32)), jnp.int32
        )
        dense = _model(attn="dense", window=7, attention_sinks=3)
        params = dense.init(jax.random.PRNGKey(0), toks)["params"]
        want = dense.apply({"params": params}, toks)
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        for attn in ("ring", "ulysses"):
            got = _model(
                mesh=mesh, attn=attn, window=7, attention_sinks=3
            ).apply({"params": params}, toks)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
                err_msg=attn,
            )

    def test_trains_on_seq_mesh(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        trainer = hvt.Trainer(
            _model(mesh=mesh, attn="ring", window=8, attention_sinks=4),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=param_specs,
            batch_specs=(P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq")),
        )
        x, y = datasets.copy_task(8, 32, vocab_size=VOCAB)
        state = trainer.build(x)
        zero = trainer.zero_metrics()
        losses = []
        for _ in range(3):
            state, metrics, _ = trainer._train_step(
                state, trainer._shard((x, y)), np.float32(1.0), zero
            )
            losses.append(float(metrics["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestLoRASpecExemption:
    """Regression for the ADVICE is_lora tightening: the TP/EP exemption is
    for LoRAModel *adapter* leaves (a 'lora' subtree with 'a'/'b' leaves) —
    a user submodule merely NAMED 'lora' must still get its kernels
    TP-sharded, or it silently trains unsharded."""

    def test_user_submodule_named_lora_still_tp_sharded(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        params = {
            # Looks like a user's submodule that happens to be called lora:
            # ordinary kernels under layer names the rule table knows.
            "lora": {"mlp_up": {"kernel": np.zeros((8, 32), np.float32)}},
            # The real LoRAModel layout: adapters keep the exemption.
            "base": {"mlp_up": {"kernel": np.zeros((8, 32), np.float32)}},
        }
        specs = param_specs(params, mesh)
        assert specs["lora"]["mlp_up"]["kernel"] == P(None, "model")
        assert specs["base"]["mlp_up"]["kernel"] == P(None, "model")

    def test_adapter_leaves_keep_exemption_under_any_wrapper(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        params = {
            "wrapper": {"lora": {"mlp_up": {
                "a": np.zeros((8, 2), np.float32),   # rank dim: unshardable
                "b": np.zeros((2, 32), np.float32),
            }}},
        }
        specs = param_specs(params, mesh)
        assert specs["wrapper"]["lora"]["mlp_up"]["a"] == P(None, None)
        assert specs["wrapper"]["lora"]["mlp_up"]["b"] == P(None, None)
