"""ZeRO-1 x accumulation x compression x overlap — the composed path
(ISSUE 10 acceptance):

* The trajectory-equivalence MATRIX: ``shard_update=True`` x K in {1, 4}
  x compression in {none, int8} x overlap on/off must equal the dense
  (replicated-update) control at rel 1e-4 on params AND optimizer state.
  The bar is reachable because the composition is arithmetic-preserving
  by construction: non-quantized wires reduce-scatter the very sums the
  control psums (reassociation only), and quantized wires keep the DENSE
  bucket layout through the two-shot wire — bitwise the control's
  reduction — and slice locally (re-cutting buckets to the zero1 layout
  would change the per-bucket scales, i.e. the numerics).
* The compiled structure: the composed step's gradient traffic is
  scatter-form ONLY — reduce-scatters (plus the quantized wire's
  payload all-to-all), never a full-payload all-reduce — and the
  overlap peel still empties the accumulation scan.
* `collectives.flatten_scatter_buckets` really inverts into the
  per-shard zero1 leaf slices `training/build.py` defines.
* `collectives.quantized_group_sum` is now the two-shot reduce-scatter +
  all-gather: equivalent to the PR 7 one-shot gather-sum within one
  re-quantization quantum, at ~2x payload receive bytes instead of
  group_size x.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu import checkpoint, compat
from horovod_tpu.analysis import hlo_audit
from horovod_tpu.analysis.step_probe import lowered_step_text
from horovod_tpu.parallel import collectives, mesh as mesh_lib
from horovod_tpu.training.optimizer import (
    ErrorFeedbackState,
    compression_error_feedback,
)


class Probe(nn.Module):
    # Dense(32) shards at dp=8 (64, 32 both divide); the Dense(10) bias
    # does NOT divide — deliberately, so the tail-bucket path (pad +
    # reduce-scatter + all-gather, replicated mirror) is always exercised.
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        return nn.Dense(10)(nn.relu(nn.Dense(32)(x)))


def _data(n=128, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 8, 8, 1).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.int32)
    return x, y


def _trainer(k=1, compression="none", zero1=False, overlap=None,
             bucket_bytes=None, seed=3, compression_ici="none"):
    tx = hvt.DistributedOptimizer(
        optax.adam(1e-3), backward_passes_per_step=k,
        average_aggregated_gradients=True, compression=compression,
        compression_ici=compression_ici,
    )
    return hvt.Trainer(
        Probe(), tx, seed=seed, shard_update=zero1,
        overlap_reduction=overlap, bucket_bytes=bucket_bytes,
    )


def _fit(tr, k, steps=3):
    x, y = _data()
    tr.fit(x=x, y=y, batch_size=max(1, 8 // k), epochs=1,
           steps_per_epoch=steps, shuffle_buffer=1, verbose=0)
    return tr


def _assert_state_close(a, b, rtol=1e-4, atol=1e-6):
    for pa, pb in zip(
        jax.tree.leaves(jax.device_get(a.state.params)),
        jax.tree.leaves(jax.device_get(b.state.params)),
    ):
        np.testing.assert_allclose(pa, pb, rtol=rtol, atol=atol)
    for oa, ob in zip(
        jax.tree.leaves(jax.device_get(a.state.opt_state)),
        jax.tree.leaves(jax.device_get(b.state.opt_state)),
    ):
        np.testing.assert_allclose(
            np.asarray(oa), np.asarray(ob), rtol=rtol, atol=atol
        )


class TestComposedTrajectoryMatrix:
    """THE acceptance matrix: every composed configuration equals its
    dense control at rel 1e-4 on params and optimizer state."""

    @pytest.mark.parametrize("k", [1, 4])
    @pytest.mark.parametrize(
        "compression,ici",
        [("none", "none"), ("int8", "none"), ("int8", "int8")],
    )
    def test_composed_equals_dense_control(self, k, compression, ici,
                                           monkeypatch):
        """The PR 10 matrix extended with the ICI-hop wire: int8+ici
        runs BOTH hops quantized under a faked 2-slice factoring
        (HVT_DCN_FACTOR=2) and must still equal the dense control at the
        same config — the scatter path keeps the dense bucket layout
        for quantized DCN wires (bitwise the replicated reduction) and
        slices locally."""
        if ici != "none":
            monkeypatch.setenv("HVT_DCN_FACTOR", "2")
        dense = _fit(_trainer(k, compression, compression_ici=ici), k)
        for overlap in (True, False):
            z = _fit(_trainer(k, compression, zero1=True,
                              overlap=overlap, compression_ici=ici), k)
            _assert_state_close(z, dense)
            # And it really trained sharded: some opt-state mirror
            # carries the data axis (dp=8 divides every Probe leaf's
            # dim 0 except the Dense(10) bias).
            specs = {
                str(l.sharding.spec)
                for l in jax.tree.leaves(z.state.opt_state)
                if hasattr(l, "sharding") and getattr(l, "ndim", 0) > 0
            }
            assert any("data" in s for s in specs), specs

    def test_quantized_ici_on_scatter_layout_tracks_exact(self,
                                                          monkeypatch):
        """compression_ici alone (no DCN wire) keeps the SCATTER layout
        — the quantized wire rides `_scatter_reduce_bucket`'s ICI hop
        with error feedback — and the trained params track the exact
        (uncompressed) zero1 run closely (EF telescopes the per-hop
        quantization error)."""
        monkeypatch.setenv("HVT_DCN_FACTOR", "2")
        exact = _fit(_trainer(4, zero1=True), 4)
        q = _fit(_trainer(4, zero1=True, compression_ici="int8"), 4)
        for a, b in zip(
            jax.tree.leaves(jax.device_get(exact.state.params)),
            jax.tree.leaves(jax.device_get(q.state.params)),
        ):
            np.testing.assert_allclose(a, b, rtol=0.05, atol=5e-3)
        # The EF residual exists and lives in opt_state.
        assert isinstance(q.state.opt_state, ErrorFeedbackState)

    def test_fail_fasts_are_lifted(self):
        """The three former composition fail-fasts construct and build:
        shard_update with accumulation, with wire compression, and with
        the overlap peel (which needs the other two)."""
        x, _ = _data(16)
        for tr in (
            _trainer(4, zero1=True),
            _trainer(1, "bf16", zero1=True),
            _trainer(2, "int8", zero1=True, overlap=True),
        ):
            tr.build(x[:8])

    def test_param_specs_still_rejected(self):
        """The TP/FSDP layout family stays out of scope: shard_update
        composes with accumulation/compression/overlap, not with
        param_specs (the documented fsdp-axis route)."""
        from horovod_tpu.models.transformer import param_specs

        with pytest.raises(ValueError, match="fsdp"):
            hvt.Trainer(
                Probe(),
                hvt.DistributedOptimizer(optax.adam(1e-3)),
                shard_update=True, param_specs=param_specs,
            )


class TestComposedCompiledStructure:
    """Scatter-form gradient traffic only — the `hvt-audit` invariants,
    asserted against the real lowered step."""

    def test_k4_step_is_scatter_only(self):
        x, y = _data()
        tr = _trainer(4, zero1=True)
        # dp=8: {k1, b1, k2} scatter pieces AND the padded b2 tail piece
        # share ONE bucket at the default fusion threshold -> exactly one
        # reduce-scatter, zero full-payload all-reduces; the tail's full
        # value comes back through a small rank-1 all-gather of just its
        # columns (outside every reduction count by design).
        text = lowered_step_text(tr, x, y, 4)
        hlo_audit.assert_program(text, "scatters=1")
        tail_gathers = [
            op for op in hlo_audit.collective_ops(text)
            if op.kind == "all-gather" and op.rank == 1
        ]
        assert len(tail_gathers) == 1, tail_gathers
        # b2 is (10,), padded to 2 columns x 8 shards = 16 elements.
        assert tail_gathers[0].shape == (16,), tail_gathers

    def test_int8_step_is_one_bucketed_scatter_group(self):
        """The canonical acceptance audit: K=4 + shard_update + int8
        compiles to exactly ONE bucketed scatter-form reduction per
        optimizer step (the dense-layout payload all-to-all), wire dtype
        i8, no full-payload all-reduce."""
        x, y = _data()
        tr = _trainer(4, "int8", zero1=True)
        hlo_audit.assert_program(
            lowered_step_text(tr, x, y, 4), "scatters=1,wire=int8"
        )

    def test_bf16_wire_rides_the_reduce_scatter(self):
        x, y = _data()
        tr = _trainer(4, "bf16", zero1=True)
        text = lowered_step_text(tr, x, y, 4)
        hlo_audit.assert_program(text, "scatter-reduction,wire=bf16")
        rs = [
            op for op in hlo_audit.collective_ops(text)
            if op.kind == "reduce-scatter"
        ]
        assert rs and all(op.dtype == "bf16" for op in rs), rs

    def test_overlap_peel_survives_composition(self):
        """Strictly fewer loop ops with the peel on — the PR 7 witness,
        now on the ZeRO-1 composed step."""
        x, y = _data()
        whiles_on = hlo_audit.while_count(lowered_step_text(
            _trainer(2, zero1=True, overlap=True), x, y, 2
        ))
        whiles_off = hlo_audit.while_count(lowered_step_text(
            _trainer(2, zero1=True, overlap=False), x, y, 2
        ))
        assert whiles_on < whiles_off

    def test_implicit_zero1_path_untouched(self):
        """K=1 + no compression + shard_update keeps the implicit SPMD
        step: no explicit collective in the lowered text (XLA places the
        reduce-scatter at partitioning time, as before this PR)."""
        x, y = _data()
        tr = _trainer(1, zero1=True)
        hlo_audit.assert_program(
            lowered_step_text(tr, x, y, 1), "no-collectives"
        )


class TestScatterBuckets:
    """`flatten_scatter_buckets` really is the zero1 layout, bucketed."""

    def _tree(self):
        rng = np.random.RandomState(0)
        return {
            "k1": rng.randn(64, 32).astype(np.float32),
            "b1": rng.randn(32).astype(np.float32),
            "k2": rng.randn(32, 10).astype(np.float32),
            "b2": rng.randn(10).astype(np.float32),
        }

    @pytest.mark.parametrize("reverse", [False, True])
    @pytest.mark.parametrize("bucket_bytes", [1 << 20, 512])
    def test_round_trips_into_per_shard_zero1_slices(
        self, reverse, bucket_bytes
    ):
        dp = 8
        tree = self._tree()
        buckets, spec = collectives.flatten_scatter_buckets(
            tree, dp, bucket_bytes, reverse=reverse
        )
        fams = collectives.bucket_families(spec)
        spans = collectives.bucket_tail_spans(spec)
        assert len(fams) == len(buckets) == len(spans)
        for s in range(dp):
            entries = []
            for b, sp in zip(buckets, spans):
                m = np.asarray(b).reshape(dp, -1)
                if sp:
                    tails = np.concatenate(
                        [m[:, c: c + w] for c, w in sp], axis=1
                    )
                    entries.append((m[s], tails.ravel()))
                else:
                    entries.append(m[s])
            got = collectives.unflatten_scatter_buckets(entries, spec)
            for name, leaf in tree.items():
                sd = collectives.zero1_shard_dim(leaf.shape, dp)
                if sd is None:
                    np.testing.assert_array_equal(
                        np.asarray(got[name]), leaf
                    )
                else:
                    blk = leaf.shape[sd] // dp
                    want = np.take(
                        leaf, range(s * blk, (s + 1) * blk), axis=sd
                    )
                    np.testing.assert_array_equal(
                        np.asarray(got[name]), want
                    )

    @pytest.mark.parametrize("bucket_bytes", [1 << 20, 512])
    def test_full_buckets_round_trip(self, bucket_bytes):
        """`unflatten_scatter_full` (the error-feedback residual path)
        is the exact inverse from un-scattered buckets."""
        tree = self._tree()
        buckets, spec = collectives.flatten_scatter_buckets(
            tree, 8, bucket_bytes
        )
        got = collectives.unflatten_scatter_full(buckets, spec)
        for name, leaf in tree.items():
            np.testing.assert_array_equal(np.asarray(got[name]), leaf)

    def test_every_bucket_is_a_world_multiple(self):
        buckets, _ = collectives.flatten_scatter_buckets(
            self._tree(), 8, 512
        )
        assert all(b.size % 8 == 0 for b in buckets)

    def test_buckets_are_leaf_aligned(self):
        """The per-bucket schedulability contract: every bucket's spec
        names exactly the leaf pieces it was assembled from (no bucket
        references the whole-tree concat), cut points at exact
        bucket_bytes column multiples."""
        dp = 8
        buckets, spec = collectives.flatten_scatter_buckets(
            self._tree(), dp, 512
        )
        per = 512 // (dp * 4)  # columns per bucket (f32)
        descs = spec[5]
        assert len(descs) == len(buckets)
        for b, pieces in zip(buckets, descs):
            assert sum(w for _i, w in pieces) == b.size // dp
            assert b.size // dp <= per
        # Every leaf's pieces, concatenated across buckets, cover it once.
        shapes = spec[1]
        covered = {i: 0 for i in range(len(shapes))}
        for pieces in descs:
            for i, w in pieces:
                covered[i] += w
        for i, shape in enumerate(shapes):
            n = int(np.prod(shape))
            assert covered[i] == -(-n // dp), (i, shape, covered[i])

    def test_families_split_by_divisibility(self):
        # At the default threshold everything packs into ONE bucket:
        # b2 (10,) cannot shard at dp=8, so the bucket is mixed.
        _, spec = collectives.flatten_scatter_buckets(self._tree(), 8)
        assert collectives.bucket_families(spec) == ["mixed"]
        assert collectives.bucket_tail_spans(spec)[0]  # b2's columns
        # ...but at dp=2 every leaf divides: pure scatter, no tail spans.
        _, spec2 = collectives.flatten_scatter_buckets(self._tree(), 2)
        assert collectives.bucket_families(spec2) == ["scatter"]
        assert collectives.bucket_tail_spans(spec2) == [()]

    def test_shared_rule_with_build(self):
        """zero1_partition_spec is the layout build_state installs —
        assert against a really-built trainer."""
        x, _ = _data(16)
        tr = _trainer(4, zero1=True)
        tr.build(x[:8])
        dp = tr.mesh.shape[mesh_lib.DATA_AXIS]
        mu = tr.state.opt_state[0].mu  # Adam's param-shaped mirror
        for leaf, p in zip(
            jax.tree.leaves(mu), jax.tree.leaves(tr.state.params)
        ):
            want = collectives.zero1_partition_spec(p.shape, dp)
            assert leaf.sharding.spec == want, (p.shape, leaf.sharding)

    @pytest.mark.parametrize("dcn", [2, 4, 8])
    def test_hierarchical_scatter_matches_flat(self, dcn):
        """The two-hop scatter (ICI psum_scatter full precision, DCN
        psum_scatter on the wire) equals the flat scatter for every
        dcn factoring of the 8-way axis — the target-inner-major
        arrangement really lands each shard its own zero1 row."""
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        dp = mesh.shape["data"]
        P = jax.sharding.PartitionSpec
        tree = self._tree()
        outspec = {
            k: (P() if collectives.zero1_shard_dim(v.shape, dp) is None
                else collectives.zero1_partition_spec(v.shape, dp))
            for k, v in tree.items()
        }

        def mk(d, wire=None):
            def red(g):
                return collectives.reduce_gradients(
                    g, data_axis="data", extra_axes=("fsdp",), dcn=d,
                    wire_dtype=wire, bucket_bytes=1 << 20, scatter=dp,
                )

            return jax.jit(compat.shard_map(
                red, mesh=mesh, in_specs=(P(),), out_specs=outspec,
                check_vma=False,
            ))

        flat = jax.device_get(mk(1)(tree))
        hier = jax.device_get(mk(dcn)(tree))
        for k in tree:
            np.testing.assert_allclose(
                np.asarray(hier[k]), np.asarray(flat[k]), rtol=1e-6
            )
        # A 16-bit wire rides the DCN hop only: per bucket, one f32
        # (ICI) and one bf16 (DCN) reduce-scatter.
        text = mk(dcn, jnp.bfloat16).lower(tree).as_text()
        rs = [
            op.dtype for op in hlo_audit.collective_ops(text)
            if op.kind == "reduce-scatter"
        ]
        if dcn < dp:  # dcn == dp has no non-trivial ICI hop
            assert sorted(set(rs)) == ["bf16", "f32"], rs
        else:
            assert set(rs) == {"bf16"}, rs

    def test_mismatched_bucket_list_is_loud(self):
        buckets, spec = collectives.flatten_scatter_buckets(
            self._tree(), 8
        )
        with pytest.raises(ValueError, match="do not match"):
            collectives.unflatten_scatter_buckets(buckets[:-1], spec)


class TestIciWire:
    """compression_ici — the ICI-hop wire of the two-hop factoring
    (ISSUE 12): quantized reduce-scatter on hop 1 of the scatter path,
    per-hop error-feedback charging, structural dtype witnesses."""

    def _tree(self):
        rng = np.random.RandomState(0)
        return {
            "k1": rng.randn(64, 32).astype(np.float32),
            "b2": rng.randn(10).astype(np.float32),
        }

    def _shard_map(self, fn, in_specs, out_specs):
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        return mesh, jax.jit(compat.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        ))

    def test_quantized_ici_scatter_matches_flat_within_quantum(self):
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        dp = mesh.shape["data"]
        P = jax.sharding.PartitionSpec
        tree = self._tree()
        outspec = {
            k: (P() if collectives.zero1_shard_dim(v.shape, dp) is None
                else collectives.zero1_partition_spec(v.shape, dp))
            for k, v in tree.items()
        }

        def mk(d, ici=None):
            def red(g):
                return collectives.reduce_gradients(
                    g, data_axis="data", extra_axes=("fsdp",), dcn=d,
                    ici_wire_dtype=ici, scatter=dp,
                )

            return jax.jit(compat.shard_map(
                red, mesh=mesh, in_specs=(P(),), out_specs=outspec,
                check_vma=False,
            ))

        flat = jax.device_get(mk(1)(tree))
        quant = jax.device_get(mk(2, jnp.int8)(tree))
        for k in tree:
            a, b = np.asarray(quant[k]), np.asarray(flat[k])
            denom = np.abs(b).max() + 1e-6
            assert np.abs(a - b).max() / denom < 0.02, k
        # Structural: hop 1 is the quantized reduce-scatter (an i8
        # all-to-all + scale gather), hop 2 a plain f32 psum_scatter —
        # and NO full-payload all-reduce anywhere.
        text = mk(2, jnp.int8).lower(tree).as_text()
        ops = hlo_audit.collective_ops(text)
        kinds = [(o.kind, o.dtype) for o in ops if not o.scalar]
        assert ("all-to-all", "i8") in kinds, kinds
        assert any(
            k == "reduce-scatter" and d == "f32" for k, d in kinds
        ), kinds
        assert not any(
            o.kind == "all-reduce" and not o.scalar for o in ops
        ), kinds

    def test_bf16_ici_wire_casts_hop_one(self):
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        dp = mesh.shape["data"]
        P = jax.sharding.PartitionSpec
        tree = {"k1": np.ones((64, 32), np.float32)}

        def red(g):
            return collectives.reduce_gradients(
                g, data_axis="data", extra_axes=("fsdp",), dcn=2,
                ici_wire_dtype=jnp.bfloat16, scatter=dp,
            )

        f = jax.jit(compat.shard_map(
            red, mesh=mesh, in_specs=(P(),),
            out_specs={"k1": collectives.zero1_partition_spec(
                (64, 32), dp
            )},
            check_vma=False,
        ))
        rs = [
            op.dtype for op in hlo_audit.collective_ops(
                f.lower(tree).as_text()
            ) if op.kind == "reduce-scatter"
        ]
        # hop 1 bf16 (ICI wire), hop 2 f32 (no DCN wire).
        assert sorted(set(rs)) == ["bf16", "f32"], rs

    def test_ici_only_error_mass_identity(self):
        """With ONLY the ICI hop quantized (residual consumed at the
        first quantized hop, hop 2 an exact psum), the global identity
        holds exactly: summed over shards, the returned errors equal
        (true sum + residual mass − delivered sum)."""
        rng = np.random.RandomState(3)
        v = jnp.asarray(rng.randn(8, 64).astype(np.float32))
        r = jnp.asarray(rng.randn(8, 64).astype(np.float32) * 0.1)
        P = jax.sharding.PartitionSpec
        sharded = P(("data", "fsdp"))

        def red(x, res):
            return collectives._hierarchical_psum_err(
                x, "data", 2, extra_axes=("fsdp",),
                ici_wire_dtype=jnp.int8, residual=res,
            )

        _, f = self._shard_map(
            red, (sharded, sharded), (sharded, sharded)
        )
        total, err = jax.device_get(f(v, r))
        true = np.asarray(v).sum(axis=0) + np.asarray(r).sum(axis=0)
        np.testing.assert_allclose(
            err.sum(axis=0), true - total[0], rtol=1e-4, atol=1e-4
        )

    def test_per_hop_error_mass_identity_both_hops(self):
        """Per-HOP charging with BOTH hops quantized. The DCN hop runs
        redundantly in each of the ``ici`` dcn-groups (every group sees
        the same hop-1 outputs once the residual is consumed at hop 1,
        so every shard agrees on the delivered gradient), and each group
        charges its own copy of the hop-2 error — so the exact global
        identity is

            Σ_s err_s = (true + residual − h) + ici · (h − delivered)

        where ``h`` is the hop-1 (ICI-quantized) partial total,
        measured by running the SAME reduction with the DCN hop exact
        (deterministic quantization → identical hop-1 outputs)."""
        rng = np.random.RandomState(3)
        v = jnp.asarray(rng.randn(8, 64).astype(np.float32))
        r = jnp.asarray(rng.randn(8, 64).astype(np.float32) * 0.1)
        P = jax.sharding.PartitionSpec
        sharded = P(("data", "fsdp"))

        def red(wire):
            def f(x, res):
                return collectives._hierarchical_psum_err(
                    x, "data", 2, extra_axes=("fsdp",),
                    wire_dtype=wire, ici_wire_dtype=jnp.int8,
                    residual=res,
                )

            return f

        _, both = self._shard_map(
            red(jnp.int8), (sharded, sharded), (sharded, sharded)
        )
        _, ici_only = self._shard_map(
            red(None), (sharded, sharded), (sharded, sharded)
        )
        total, err = jax.device_get(both(v, r))
        h = jax.device_get(ici_only(v, r))[0][0]  # exact hop-2 of hop-1
        ici = 8 // 2
        true = np.asarray(v).sum(axis=0) + np.asarray(r).sum(axis=0)
        want = (true - h) + ici * (h - total[0])
        np.testing.assert_allclose(
            err.sum(axis=0), want, rtol=1e-4, atol=1e-4
        )
        # Residual consumed at hop 1 => every shard agrees on the
        # delivered gradient (no per-dcn-group divergence).
        np.testing.assert_array_equal(total, np.broadcast_to(
            total[0], total.shape
        ))

    def test_residual_flushes_on_exact_wire(self):
        """A residual with no quantized hop anywhere is transmitted in
        full and comes back zero — mass conserved, never dropped."""
        rng = np.random.RandomState(4)
        v = jnp.asarray(rng.randn(8, 32).astype(np.float32))
        r = jnp.asarray(rng.randn(8, 32).astype(np.float32) * 0.1)
        P = jax.sharding.PartitionSpec
        sharded = P(("data", "fsdp"))

        def red(x, res):
            out, err = collectives.reduce_gradients(
                {"v": x}, data_axis="data", extra_axes=("fsdp",),
                residual={"v": res},
            )
            return out["v"], err["v"]

        _, f = self._shard_map(
            red, (sharded, sharded), (sharded, sharded)
        )
        total, err = jax.device_get(f(v, r))
        true = np.asarray(v).sum(axis=0) + np.asarray(r).sum(axis=0)
        np.testing.assert_allclose(total[0], true, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(err, 0.0, atol=0.0)

    def test_scatter_residual_requires_a_quantized_hop(self):
        with pytest.raises(ValueError, match="quantized wire"):
            collectives._reduce_gradients_scatter(
                {"k1": jnp.ones((64, 32))}, 8, data_axis="data",
                extra_axes=(), dcn=1, wire_dtype=None,
                ici_wire_dtype=jnp.bfloat16, bucket_bytes=None,
                reverse=False, residual={"k1": jnp.ones((64, 32))},
            )

    def test_optimizer_tags_and_rejections(self):
        tx = hvt.DistributedOptimizer(
            optax.adam(1e-3), compression_ici="int8"
        )
        from horovod_tpu.training.optimizer import compression_ici_dtype

        assert compression_ici_dtype(tx) == jnp.int8
        # A quantized ICI hop alone turns error feedback on.
        assert compression_error_feedback(tx)
        with pytest.raises(ValueError, match="compression_ici"):
            hvt.DistributedOptimizer(
                optax.adam(1e-3), compression_ici="int4"
            )
        with pytest.raises(ValueError, match="axis_name"):
            hvt.DistributedOptimizer(
                optax.adam(1e-3), compression_ici="int8",
                axis_name="data",
            )


class TestQuantizedTwoShot:
    """The replicated quantized wire is now a two-shot reduce-scatter +
    all-gather (ROADMAP item-2 seam)."""

    def _run(self, fn, v, *extra):
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        P = jax.sharding.PartitionSpec
        sharded = P(("data", "fsdp"))
        f = jax.jit(compat.shard_map(
            fn, mesh=mesh,
            in_specs=(sharded,) * (1 + len(extra)),
            out_specs=(sharded, sharded),
            check_vma=False,
        ))
        return jax.device_get(f(v, *extra))

    def test_equivalent_to_gather_sum_within_one_quantum(self):
        """Shot 2 re-quantizes the REDUCED chunk, so the two-shot total
        may differ from the one-shot gather-sum by that single
        re-quantization — bounded by one quantum of the reduced value's
        scale, never compounding (error feedback charges it to the
        chunk's owner)."""
        rng = np.random.RandomState(1)
        v = jnp.asarray(rng.randn(8, 256).astype(np.float32))

        def two(x):
            return collectives.quantized_group_sum(
                x, ("data", "fsdp"), jnp.int8
            )

        def one(x):
            return collectives._quantized_gather_sum(
                x, ("data", "fsdp"), jnp.int8
            )

        t2, e2 = self._run(two, v)
        t1, e1 = self._run(one, v)
        true = np.asarray(v).sum(axis=0)
        quantum = float(np.abs(true).max()) / 127.0
        np.testing.assert_array_less(np.abs(t2 - t1), quantum + 1e-5)
        # Both are honest reductions of the same sum.
        np.testing.assert_allclose(t2[0], true, atol=8 * quantum)

    def test_error_mass_identity_holds(self):
        """Summed over shards, the returned errors equal exactly
        (true sum - delivered sum) — the telescoping precondition, now
        including the shot-2 error charged to each chunk's owner."""
        rng = np.random.RandomState(2)
        v = jnp.asarray(rng.randn(8, 64).astype(np.float32))

        def two(x):
            return collectives.quantized_group_sum(
                x, ("data", "fsdp"), jnp.int8
            )

        total, err = self._run(two, v)
        true = np.asarray(v).sum(axis=0)
        np.testing.assert_allclose(
            err.sum(axis=0), true - total[0], rtol=1e-4, atol=1e-5
        )

    def test_receive_bytes_drop_from_world_to_two(self):
        """Structural: the two-shot wire's per-device payload receive
        bytes are ~2x the bucket (one all-to-all + one all-gather of
        1/world chunks), vs the one-shot's world x (a full [world, n]
        payload gather). Counted from the lowered programs."""
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        world = mesh.shape["data"]
        P = jax.sharding.PartitionSpec
        v = jnp.ones((world, 1024), jnp.float32)

        def lower(fn):
            f = jax.jit(compat.shard_map(
                lambda x: fn(x)[0], mesh=mesh,
                in_specs=(P(("data", "fsdp")),),
                out_specs=P(("data", "fsdp")), check_vma=False,
            ))
            return f.lower(v).as_text()

        def payload_bytes(text):
            return sum(
                hlo_audit.op_bytes(op)
                for op in hlo_audit.collective_ops(text)
                if op.dtype == "i8"
            )

        two = payload_bytes(lower(
            lambda x: collectives.quantized_group_sum(
                x, ("data", "fsdp"), jnp.int8
            )
        ))
        one = payload_bytes(lower(
            lambda x: collectives._quantized_gather_sum(
                x, ("data", "fsdp"), jnp.int8
            )
        ))
        n = 1024  # per-shard bucket bytes (i8)
        assert one >= world * n  # the gather-sum's full payload gather
        assert two <= 3 * n      # all-to-all (n) + chunk gather (n)
        assert two < one / 2

    def test_groups_need_explicit_position(self):
        with pytest.raises(ValueError, match="group_position"):
            collectives.quantized_group_sum(
                jnp.ones(8), "data", jnp.int8,
                axis_index_groups=[[0, 1], [2, 3]],
            )


class TestBenchZero1Gates:
    """Pure-function units for the new bench gates (the wall-clock
    overlap gate and MFU-denominator guard run in bench.py's main;
    their decision logic is unit-tested here)."""

    def _bench(self):
        import importlib.util
        import os

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench.py",
        )
        spec = importlib.util.spec_from_file_location("_bench_mod", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_flops_guard_accepts_peel_structure(self):
        bench = self._bench()
        micro = 1e9
        # K=4 overlap on: first microbatch + peeled last + scan body =
        # 3 statically counted microbatches; the compiled count sits in
        # [2.5, 3.5] x micro.
        g = bench._flops_guard(4, True, micro, 2.9e9)
        assert g["ok"] and g["counted_microbatches"] == 3
        # K=4 overlap off: first + scan body = 2.
        g2 = bench._flops_guard(4, False, micro, 2.1e9)
        assert g2["ok"] and g2["counted_microbatches"] == 2

    def test_flops_guard_catches_structure_drift(self):
        bench = self._bench()
        micro = 1e9
        # Peel silently gone: the program statically counts one less
        # microbatch than the overlap-on structure implies.
        assert not bench._flops_guard(4, True, micro, 1.9e9)["ok"]
        # Scan silently unrolled: every microbatch counted.
        assert not bench._flops_guard(4, True, micro, 4.2e9)["ok"]

    def test_flops_guard_skips_without_cost_model(self):
        bench = self._bench()
        g = bench._flops_guard(4, True, None, None)
        assert g["ok"] and g["skipped"]
        assert bench._flops_guard(1, True, 1e9, 1e9)["skipped"]

    def test_peak_flops_override_resolves_without_calibration(self,
                                                              monkeypatch):
        bench = self._bench()
        monkeypatch.setenv("HVT_PEAK_FLOPS", "1.5e12")
        peak, src = bench._resolve_peak_flops()
        assert peak == 1.5e12 and src == "override"

    def test_unparseable_peak_override_is_loud(self, monkeypatch):
        from horovod_tpu.analysis import registry

        monkeypatch.setenv("HVT_PEAK_FLOPS", "fast")
        with pytest.raises(ValueError):
            registry.get_float("HVT_PEAK_FLOPS")

    def test_peak_table_override_reaches_trace_mfu(self, monkeypatch):
        from horovod_tpu import trace

        monkeypatch.setenv("HVT_PEAK_FLOPS", "2e12")
        assert trace.device_peak_flops() == 2e12
        # mfu divides by the override: 1e12 FLOP in 1 s on 1 chip.
        assert trace.mfu(1e12, 1.0, 1) == pytest.approx(0.5)


class TestComposedStateSurfaces:
    """EF residuals and checkpoints ride the scattered layout."""

    def _trained(self):
        tr = _trainer(2, "int8", zero1=True)
        return _fit(tr, 2, steps=2)

    def test_residual_lives_sharded_in_zero1_opt_state(self):
        tr = self._trained()
        assert isinstance(tr.state.opt_state, ErrorFeedbackState)
        dp = tr.dp_size
        for leaf, p in zip(
            jax.tree.leaves(tr.state.opt_state.ef_residual),
            jax.tree.leaves(tr.state.params),
        ):
            assert leaf.shape == (dp,) + p.shape
            # dim-0 sharded over the data axes, never dense-replicated.
            assert "data" in str(leaf.sharding.spec)
        # The inner (Adam) mirrors carry the zero1 layout.
        mu = tr.state.opt_state.inner[0].mu
        assert any(
            "data" in str(l.sharding.spec) for l in jax.tree.leaves(mu)
        )

    def test_checkpoint_roundtrip(self, tmp_path):
        tr = self._trained()
        path = str(tmp_path / "state.msgpack")
        checkpoint.save(path, tr.state)
        tr2 = _trainer(2, "int8", zero1=True)
        x, y = _data(16)
        tr2.build(x[:8], y[:8])
        restored = checkpoint.restore(path, tr2.state)
        for a, b in zip(
            jax.tree.leaves(jax.device_get(tr.state.opt_state)),
            jax.tree.leaves(jax.device_get(restored.opt_state)),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_install_state_reshard_recuts_residual(self):
        """A committed snapshot from a 2-shard world installs onto the
        8-shard composed trainer: the EF residual re-cuts
        mass-conserving, the zero1 mirrors re-slice."""
        tr = self._trained()
        snap = jax.device_get(tr.state)
        old = jax.tree.map(
            lambda p: np.stack([
                np.full(p.shape, 1.0, np.float32),
                np.full(p.shape, 3.0, np.float32),
            ]),
            jax.device_get(tr.state.params),
        )
        snap = snap.replace(
            opt_state=snap.opt_state.replace(ef_residual=old)
        )
        installed = tr.install_state(snap)
        for leaf in jax.tree.leaves(
            jax.device_get(installed.opt_state.ef_residual)
        ):
            np.testing.assert_allclose(leaf.sum(axis=0), 4.0, rtol=1e-6)

    def test_device_cached_path_composes(self):
        x, y = _data(512)
        tr = _trainer(2, "int8", zero1=True)
        hist = tr.fit(x=x, y=y, batch_size=2, epochs=3, cache="device",
                      verbose=0)
        assert hist[-1]["loss"] < hist[0]["loss"]
