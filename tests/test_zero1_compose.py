"""ZeRO-1 x accumulation x compression x overlap — the composed path
(ISSUE 10 acceptance):

* The trajectory-equivalence MATRIX: ``shard_update=True`` x K in {1, 4}
  x compression in {none, int8} x overlap on/off must equal the dense
  (replicated-update) control at rel 1e-4 on params AND optimizer state.
  The bar is reachable because the composition is arithmetic-preserving
  by construction: non-quantized wires reduce-scatter the very sums the
  control psums (reassociation only), and quantized wires keep the DENSE
  bucket layout through the two-shot wire — bitwise the control's
  reduction — and slice locally (re-cutting buckets to the zero1 layout
  would change the per-bucket scales, i.e. the numerics).
* The compiled structure: the composed step's gradient traffic is
  scatter-form ONLY — reduce-scatters (plus the quantized wire's
  payload all-to-all), never a full-payload all-reduce — and the
  overlap peel still empties the accumulation scan.
* `collectives.flatten_scatter_buckets` really inverts into the
  per-shard zero1 leaf slices `training/build.py` defines.
* `collectives.quantized_group_sum` is now the two-shot reduce-scatter +
  all-gather: equivalent to the PR 7 one-shot gather-sum within one
  re-quantization quantum, at ~2x payload receive bytes instead of
  group_size x.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu import checkpoint, compat
from horovod_tpu.analysis import hlo_audit
from horovod_tpu.analysis.step_probe import lowered_step_text
from horovod_tpu.parallel import collectives, mesh as mesh_lib
from horovod_tpu.training.optimizer import ErrorFeedbackState


class Probe(nn.Module):
    # Dense(32) shards at dp=8 (64, 32 both divide); the Dense(10) bias
    # does NOT divide — deliberately, so the tail-bucket path (pad +
    # reduce-scatter + all-gather, replicated mirror) is always exercised.
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        return nn.Dense(10)(nn.relu(nn.Dense(32)(x)))


def _data(n=128, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 8, 8, 1).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.int32)
    return x, y


def _trainer(k=1, compression="none", zero1=False, overlap=None,
             bucket_bytes=None, seed=3):
    tx = hvt.DistributedOptimizer(
        optax.adam(1e-3), backward_passes_per_step=k,
        average_aggregated_gradients=True, compression=compression,
    )
    return hvt.Trainer(
        Probe(), tx, seed=seed, shard_update=zero1,
        overlap_reduction=overlap, bucket_bytes=bucket_bytes,
    )


def _fit(tr, k, steps=3):
    x, y = _data()
    tr.fit(x=x, y=y, batch_size=max(1, 8 // k), epochs=1,
           steps_per_epoch=steps, shuffle_buffer=1, verbose=0)
    return tr


def _assert_state_close(a, b, rtol=1e-4, atol=1e-6):
    for pa, pb in zip(
        jax.tree.leaves(jax.device_get(a.state.params)),
        jax.tree.leaves(jax.device_get(b.state.params)),
    ):
        np.testing.assert_allclose(pa, pb, rtol=rtol, atol=atol)
    for oa, ob in zip(
        jax.tree.leaves(jax.device_get(a.state.opt_state)),
        jax.tree.leaves(jax.device_get(b.state.opt_state)),
    ):
        np.testing.assert_allclose(
            np.asarray(oa), np.asarray(ob), rtol=rtol, atol=atol
        )


class TestComposedTrajectoryMatrix:
    """THE acceptance matrix: every composed configuration equals its
    dense control at rel 1e-4 on params and optimizer state."""

    @pytest.mark.parametrize("k", [1, 4])
    @pytest.mark.parametrize("compression", ["none", "int8"])
    def test_composed_equals_dense_control(self, k, compression):
        dense = _fit(_trainer(k, compression), k)
        for overlap in (True, False):
            z = _fit(_trainer(k, compression, zero1=True,
                              overlap=overlap), k)
            _assert_state_close(z, dense)
            # And it really trained sharded: some opt-state mirror
            # carries the data axis (dp=8 divides every Probe leaf's
            # dim 0 except the Dense(10) bias).
            specs = {
                str(l.sharding.spec)
                for l in jax.tree.leaves(z.state.opt_state)
                if hasattr(l, "sharding") and getattr(l, "ndim", 0) > 0
            }
            assert any("data" in s for s in specs), specs

    def test_fail_fasts_are_lifted(self):
        """The three former composition fail-fasts construct and build:
        shard_update with accumulation, with wire compression, and with
        the overlap peel (which needs the other two)."""
        x, _ = _data(16)
        for tr in (
            _trainer(4, zero1=True),
            _trainer(1, "bf16", zero1=True),
            _trainer(2, "int8", zero1=True, overlap=True),
        ):
            tr.build(x[:8])

    def test_param_specs_still_rejected(self):
        """The TP/FSDP layout family stays out of scope: shard_update
        composes with accumulation/compression/overlap, not with
        param_specs (the documented fsdp-axis route)."""
        from horovod_tpu.models.transformer import param_specs

        with pytest.raises(ValueError, match="fsdp"):
            hvt.Trainer(
                Probe(),
                hvt.DistributedOptimizer(optax.adam(1e-3)),
                shard_update=True, param_specs=param_specs,
            )


class TestComposedCompiledStructure:
    """Scatter-form gradient traffic only — the `hvt-audit` invariants,
    asserted against the real lowered step."""

    def test_k4_step_is_scatter_only(self):
        x, y = _data()
        tr = _trainer(4, zero1=True)
        # dp=8: {k1, b1, k2} scatter-bucket + {b2} tail-bucket -> exactly
        # two reduce-scatters, zero full-payload all-reduces.
        hlo_audit.assert_program(
            lowered_step_text(tr, x, y, 4), "scatters=2"
        )

    def test_int8_step_is_one_bucketed_scatter_group(self):
        """The canonical acceptance audit: K=4 + shard_update + int8
        compiles to exactly ONE bucketed scatter-form reduction per
        optimizer step (the dense-layout payload all-to-all), wire dtype
        i8, no full-payload all-reduce."""
        x, y = _data()
        tr = _trainer(4, "int8", zero1=True)
        hlo_audit.assert_program(
            lowered_step_text(tr, x, y, 4), "scatters=1,wire=int8"
        )

    def test_bf16_wire_rides_the_reduce_scatter(self):
        x, y = _data()
        tr = _trainer(4, "bf16", zero1=True)
        text = lowered_step_text(tr, x, y, 4)
        hlo_audit.assert_program(text, "scatter-reduction,wire=bf16")
        rs = [
            op for op in hlo_audit.collective_ops(text)
            if op.kind == "reduce-scatter"
        ]
        assert rs and all(op.dtype == "bf16" for op in rs), rs

    def test_overlap_peel_survives_composition(self):
        """Strictly fewer loop ops with the peel on — the PR 7 witness,
        now on the ZeRO-1 composed step."""
        x, y = _data()
        whiles_on = hlo_audit.while_count(lowered_step_text(
            _trainer(2, zero1=True, overlap=True), x, y, 2
        ))
        whiles_off = hlo_audit.while_count(lowered_step_text(
            _trainer(2, zero1=True, overlap=False), x, y, 2
        ))
        assert whiles_on < whiles_off

    def test_implicit_zero1_path_untouched(self):
        """K=1 + no compression + shard_update keeps the implicit SPMD
        step: no explicit collective in the lowered text (XLA places the
        reduce-scatter at partitioning time, as before this PR)."""
        x, y = _data()
        tr = _trainer(1, zero1=True)
        hlo_audit.assert_program(
            lowered_step_text(tr, x, y, 1), "no-collectives"
        )


class TestScatterBuckets:
    """`flatten_scatter_buckets` really is the zero1 layout, bucketed."""

    def _tree(self):
        rng = np.random.RandomState(0)
        return {
            "k1": rng.randn(64, 32).astype(np.float32),
            "b1": rng.randn(32).astype(np.float32),
            "k2": rng.randn(32, 10).astype(np.float32),
            "b2": rng.randn(10).astype(np.float32),
        }

    @pytest.mark.parametrize("reverse", [False, True])
    @pytest.mark.parametrize("bucket_bytes", [1 << 20, 512])
    def test_round_trips_into_per_shard_zero1_slices(
        self, reverse, bucket_bytes
    ):
        dp = 8
        tree = self._tree()
        buckets, spec = collectives.flatten_scatter_buckets(
            tree, dp, bucket_bytes, reverse=reverse
        )
        fams = collectives.bucket_families(spec)
        assert len(fams) == len(buckets)
        for s in range(dp):
            local = [
                b.reshape(dp, -1)[s] if f == "scatter" else b
                for b, f in zip(buckets, fams)
            ]
            got = collectives.unflatten_scatter_buckets(local, spec)
            for name, leaf in tree.items():
                sd = collectives.zero1_shard_dim(leaf.shape, dp)
                if sd is None:
                    np.testing.assert_array_equal(
                        np.asarray(got[name]), leaf
                    )
                else:
                    blk = leaf.shape[sd] // dp
                    want = np.take(
                        leaf, range(s * blk, (s + 1) * blk), axis=sd
                    )
                    np.testing.assert_array_equal(
                        np.asarray(got[name]), want
                    )

    def test_every_bucket_is_a_world_multiple(self):
        buckets, _ = collectives.flatten_scatter_buckets(
            self._tree(), 8, 512
        )
        assert all(b.size % 8 == 0 for b in buckets)

    def test_families_split_by_divisibility(self):
        _, spec = collectives.flatten_scatter_buckets(self._tree(), 8)
        fams = {fam for fam, _, _ in spec[5]}
        assert fams == {"scatter", "tail"}  # b2 (10,) cannot shard at 8
        # ...but at dp=2 every leaf divides: no tail family at all.
        _, spec2 = collectives.flatten_scatter_buckets(self._tree(), 2)
        assert {fam for fam, _, _ in spec2[5]} == {"scatter"}

    def test_shared_rule_with_build(self):
        """zero1_partition_spec is the layout build_state installs —
        assert against a really-built trainer."""
        x, _ = _data(16)
        tr = _trainer(4, zero1=True)
        tr.build(x[:8])
        dp = tr.mesh.shape[mesh_lib.DATA_AXIS]
        mu = tr.state.opt_state[0].mu  # Adam's param-shaped mirror
        for leaf, p in zip(
            jax.tree.leaves(mu), jax.tree.leaves(tr.state.params)
        ):
            want = collectives.zero1_partition_spec(p.shape, dp)
            assert leaf.sharding.spec == want, (p.shape, leaf.sharding)

    @pytest.mark.parametrize("dcn", [2, 4, 8])
    def test_hierarchical_scatter_matches_flat(self, dcn):
        """The two-hop scatter (ICI psum_scatter full precision, DCN
        psum_scatter on the wire) equals the flat scatter for every
        dcn factoring of the 8-way axis — the target-inner-major
        arrangement really lands each shard its own zero1 row."""
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        dp = mesh.shape["data"]
        P = jax.sharding.PartitionSpec
        tree = self._tree()
        outspec = {
            k: (P() if collectives.zero1_shard_dim(v.shape, dp) is None
                else collectives.zero1_partition_spec(v.shape, dp))
            for k, v in tree.items()
        }

        def mk(d, wire=None):
            def red(g):
                return collectives.reduce_gradients(
                    g, data_axis="data", extra_axes=("fsdp",), dcn=d,
                    wire_dtype=wire, bucket_bytes=1 << 20, scatter=dp,
                )

            return jax.jit(compat.shard_map(
                red, mesh=mesh, in_specs=(P(),), out_specs=outspec,
                check_vma=False,
            ))

        flat = jax.device_get(mk(1)(tree))
        hier = jax.device_get(mk(dcn)(tree))
        for k in tree:
            np.testing.assert_allclose(
                np.asarray(hier[k]), np.asarray(flat[k]), rtol=1e-6
            )
        # A 16-bit wire rides the DCN hop only: per bucket, one f32
        # (ICI) and one bf16 (DCN) reduce-scatter.
        text = mk(dcn, jnp.bfloat16).lower(tree).as_text()
        rs = [
            op.dtype for op in hlo_audit.collective_ops(text)
            if op.kind == "reduce-scatter"
        ]
        if dcn < dp:  # dcn == dp has no non-trivial ICI hop
            assert sorted(set(rs)) == ["bf16", "f32"], rs
        else:
            assert set(rs) == {"bf16"}, rs

    def test_mismatched_bucket_list_is_loud(self):
        buckets, spec = collectives.flatten_scatter_buckets(
            self._tree(), 8
        )
        with pytest.raises(ValueError, match="do not match"):
            collectives.unflatten_scatter_buckets(buckets[:-1], spec)


class TestQuantizedTwoShot:
    """The replicated quantized wire is now a two-shot reduce-scatter +
    all-gather (ROADMAP item-2 seam)."""

    def _run(self, fn, v, *extra):
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        P = jax.sharding.PartitionSpec
        sharded = P(("data", "fsdp"))
        f = jax.jit(compat.shard_map(
            fn, mesh=mesh,
            in_specs=(sharded,) * (1 + len(extra)),
            out_specs=(sharded, sharded),
            check_vma=False,
        ))
        return jax.device_get(f(v, *extra))

    def test_equivalent_to_gather_sum_within_one_quantum(self):
        """Shot 2 re-quantizes the REDUCED chunk, so the two-shot total
        may differ from the one-shot gather-sum by that single
        re-quantization — bounded by one quantum of the reduced value's
        scale, never compounding (error feedback charges it to the
        chunk's owner)."""
        rng = np.random.RandomState(1)
        v = jnp.asarray(rng.randn(8, 256).astype(np.float32))

        def two(x):
            return collectives.quantized_group_sum(
                x, ("data", "fsdp"), jnp.int8
            )

        def one(x):
            return collectives._quantized_gather_sum(
                x, ("data", "fsdp"), jnp.int8
            )

        t2, e2 = self._run(two, v)
        t1, e1 = self._run(one, v)
        true = np.asarray(v).sum(axis=0)
        quantum = float(np.abs(true).max()) / 127.0
        np.testing.assert_array_less(np.abs(t2 - t1), quantum + 1e-5)
        # Both are honest reductions of the same sum.
        np.testing.assert_allclose(t2[0], true, atol=8 * quantum)

    def test_error_mass_identity_holds(self):
        """Summed over shards, the returned errors equal exactly
        (true sum - delivered sum) — the telescoping precondition, now
        including the shot-2 error charged to each chunk's owner."""
        rng = np.random.RandomState(2)
        v = jnp.asarray(rng.randn(8, 64).astype(np.float32))

        def two(x):
            return collectives.quantized_group_sum(
                x, ("data", "fsdp"), jnp.int8
            )

        total, err = self._run(two, v)
        true = np.asarray(v).sum(axis=0)
        np.testing.assert_allclose(
            err.sum(axis=0), true - total[0], rtol=1e-4, atol=1e-5
        )

    def test_receive_bytes_drop_from_world_to_two(self):
        """Structural: the two-shot wire's per-device payload receive
        bytes are ~2x the bucket (one all-to-all + one all-gather of
        1/world chunks), vs the one-shot's world x (a full [world, n]
        payload gather). Counted from the lowered programs."""
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        world = mesh.shape["data"]
        P = jax.sharding.PartitionSpec
        v = jnp.ones((world, 1024), jnp.float32)

        def lower(fn):
            f = jax.jit(compat.shard_map(
                lambda x: fn(x)[0], mesh=mesh,
                in_specs=(P(("data", "fsdp")),),
                out_specs=P(("data", "fsdp")), check_vma=False,
            ))
            return f.lower(v).as_text()

        def payload_bytes(text):
            return sum(
                hlo_audit.op_bytes(op)
                for op in hlo_audit.collective_ops(text)
                if op.dtype == "i8"
            )

        two = payload_bytes(lower(
            lambda x: collectives.quantized_group_sum(
                x, ("data", "fsdp"), jnp.int8
            )
        ))
        one = payload_bytes(lower(
            lambda x: collectives._quantized_gather_sum(
                x, ("data", "fsdp"), jnp.int8
            )
        ))
        n = 1024  # per-shard bucket bytes (i8)
        assert one >= world * n  # the gather-sum's full payload gather
        assert two <= 3 * n      # all-to-all (n) + chunk gather (n)
        assert two < one / 2

    def test_groups_need_explicit_position(self):
        with pytest.raises(ValueError, match="group_position"):
            collectives.quantized_group_sum(
                jnp.ones(8), "data", jnp.int8,
                axis_index_groups=[[0, 1], [2, 3]],
            )


class TestComposedStateSurfaces:
    """EF residuals and checkpoints ride the scattered layout."""

    def _trained(self):
        tr = _trainer(2, "int8", zero1=True)
        return _fit(tr, 2, steps=2)

    def test_residual_lives_sharded_in_zero1_opt_state(self):
        tr = self._trained()
        assert isinstance(tr.state.opt_state, ErrorFeedbackState)
        dp = tr.dp_size
        for leaf, p in zip(
            jax.tree.leaves(tr.state.opt_state.ef_residual),
            jax.tree.leaves(tr.state.params),
        ):
            assert leaf.shape == (dp,) + p.shape
            # dim-0 sharded over the data axes, never dense-replicated.
            assert "data" in str(leaf.sharding.spec)
        # The inner (Adam) mirrors carry the zero1 layout.
        mu = tr.state.opt_state.inner[0].mu
        assert any(
            "data" in str(l.sharding.spec) for l in jax.tree.leaves(mu)
        )

    def test_checkpoint_roundtrip(self, tmp_path):
        tr = self._trained()
        path = str(tmp_path / "state.msgpack")
        checkpoint.save(path, tr.state)
        tr2 = _trainer(2, "int8", zero1=True)
        x, y = _data(16)
        tr2.build(x[:8], y[:8])
        restored = checkpoint.restore(path, tr2.state)
        for a, b in zip(
            jax.tree.leaves(jax.device_get(tr.state.opt_state)),
            jax.tree.leaves(jax.device_get(restored.opt_state)),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_install_state_reshard_recuts_residual(self):
        """A committed snapshot from a 2-shard world installs onto the
        8-shard composed trainer: the EF residual re-cuts
        mass-conserving, the zero1 mirrors re-slice."""
        tr = self._trained()
        snap = jax.device_get(tr.state)
        old = jax.tree.map(
            lambda p: np.stack([
                np.full(p.shape, 1.0, np.float32),
                np.full(p.shape, 3.0, np.float32),
            ]),
            jax.device_get(tr.state.params),
        )
        snap = snap.replace(
            opt_state=snap.opt_state.replace(ef_residual=old)
        )
        installed = tr.install_state(snap)
        for leaf in jax.tree.leaves(
            jax.device_get(installed.opt_state.ef_residual)
        ):
            np.testing.assert_allclose(leaf.sum(axis=0), 4.0, rtol=1e-6)

    def test_device_cached_path_composes(self):
        x, y = _data(512)
        tr = _trainer(2, "int8", zero1=True)
        hist = tr.fit(x=x, y=y, batch_size=2, epochs=3, cache="device",
                      verbose=0)
        assert hist[-1]["loss"] < hist[0]["loss"]
