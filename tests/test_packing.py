"""Sequence packing (data/packing.py): variable-length docs -> fixed rows +
segment ids, end-to-end with the segment-masked model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.data.packing import (
    next_token_pairs,
    pack_documents,
    packing_efficiency,
)


def _docs(seed=0, n=40, lo=3, hi=40, vocab=64):
    rng = np.random.RandomState(seed)
    return [
        rng.randint(1, vocab, size=rng.randint(lo, hi)).astype(np.int32)
        for _ in range(n)
    ]


class TestPackDocuments:
    def test_reconstructs_every_document(self):
        docs = _docs()
        toks, seg, doc = pack_documents(docs, seq_len=64)
        # Every document appears exactly once, contiguously, in order.
        for i, d in enumerate(docs):
            rows, cols = np.where(doc == i)
            assert len(rows) == len(d)
            assert (rows == rows[0]).all()  # one row
            assert (np.diff(cols) == 1).all()  # contiguous
            np.testing.assert_array_equal(toks[rows[0], cols], d)
            # one segment id covers it
            assert len(set(seg[rows[0], cols].tolist())) == 1

    def test_static_shapes_and_padding_segment(self):
        toks, seg, doc = pack_documents(_docs(1), seq_len=48, pad_id=0)
        assert toks.shape == seg.shape == doc.shape
        assert toks.shape[1] == 48
        pad = seg == 0
        assert (toks[pad] == 0).all()
        assert (doc[pad] == -1).all()

    def test_efficiency_beats_one_doc_per_row(self):
        docs = _docs(2)
        toks, seg, _ = pack_documents(docs, seq_len=64)
        eff = packing_efficiency(seg)
        total = sum(len(d) for d in docs)
        naive_rows = len(docs)  # one doc per 64-wide row
        assert eff > total / (naive_rows * 64)  # strictly fewer rows
        assert eff > 0.8  # first-fit-decreasing packs these tightly

    def test_overlong_split_or_dropped(self):
        long = [np.arange(1, 150, dtype=np.int32)]
        toks, seg, doc = pack_documents(long, seq_len=64)
        got = toks[doc == 0]
        assert len(got) == 149  # all chunks kept...
        # ...as isolated units: each chunk occupies one (row, segment) and
        # no two chunks share one (different rows, or different ids).
        rows_used = np.unique(np.where(doc == 0)[0])
        assert len(rows_used) == 3  # 64 + 64 + 21
        for r in rows_used:
            ids = seg[r][doc[r] == 0]
            assert len(set(ids.tolist())) == 1
        toks2, seg2, _ = pack_documents(long, seq_len=64, drop_overlong=True)
        assert (seg2 == 0).all() if seg2.size else True

    def test_max_docs_per_row(self):
        docs = [[1, 2]] * 10
        _, seg, _ = pack_documents(docs, seq_len=64, max_docs_per_row=2)
        for row in seg:
            assert len(set(row.tolist()) - {0}) <= 2

    def test_bad_seq_len(self):
        with pytest.raises(ValueError, match="seq_len"):
            pack_documents([[1]], seq_len=0)


class TestNextTokenPairs:
    def test_mask_stops_at_boundaries(self):
        toks = np.array([[5, 6, 7, 9, 9, 0]], np.int32)
        seg = np.array([[1, 1, 1, 2, 2, 0]], np.int32)
        x, y, w = next_token_pairs(toks, seg)
        np.testing.assert_array_equal(x, [[5, 6, 7, 9, 9]])
        np.testing.assert_array_equal(y, [[6, 7, 9, 9, 0]])
        # target crossing 1->2 boundary masked; crossing into padding masked
        np.testing.assert_array_equal(w, [[1, 1, 0, 1, 0]])


@pytest.mark.slow
class TestEndToEnd:
    def test_packed_rows_train_the_segment_model(self):
        """pack_documents output feeds TransformerLM(segment_ids=...) and a
        masked next-token loss runs finite on the packed batch."""
        import optax

        from horovod_tpu.models.transformer import TransformerLM

        docs = _docs(3, n=24, lo=4, hi=24, vocab=32)
        toks, seg, _ = pack_documents(docs, seq_len=32)
        x, y, w = next_token_pairs(toks, seg)
        seg_x = seg[:, :-1]
        model = TransformerLM(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, dropout=0.0
        )
        params = model.init(
            jax.random.PRNGKey(0), jnp.asarray(x)
        )["params"]

        def loss(p):
            logits = model.apply(
                {"params": p}, jnp.asarray(x),
                segment_ids=jnp.asarray(seg_x),
            )
            per_tok = optax.softmax_cross_entropy_with_integer_labels(
                logits, jnp.asarray(y)
            )
            wt = jnp.asarray(w)
            return (per_tok * wt).sum() / wt.sum()

        val, grads = jax.value_and_grad(loss)(params)
        assert np.isfinite(float(val))
        assert all(
            np.isfinite(np.asarray(g)).all() for g in jax.tree.leaves(grads)
        )
