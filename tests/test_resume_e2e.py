"""Restart-with-restore, end-to-end (SURVEY.md §5.3): a training run is
SIGKILLed mid-way, relaunched with the identical command, and must resume
from the newest checkpoint — continuing the epoch numbering and the step
counter — exactly the reference's fail-stop fault model (MPI job dies →
rerun → `BroadcastGlobalVariablesCallback` syncs the restored weights)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import optax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STEPS, EPOCHS = 3, 4


def _env(tmp_path):
    return {
        **os.environ,
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "2",
        "PS_MODEL_PATH": str(tmp_path),
        "DRIVE_STEPS": str(STEPS),
        "DRIVE_EPOCHS": str(EPOCHS),
        # This test SIGKILLs the child mid-run: it must not share the
        # suite's persistent XLA cache (a torn write poisons later runs —
        # see the conftest cache caveat).
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }


@pytest.mark.slow
def test_kill_and_resume_tf2(tmp_path):
    argv = [sys.executable, os.path.join(REPO, "examples", "tf2_style_mnist.py")]
    model_dir = tmp_path / "horovod-mnist"

    # --- run 1: kill it once the epoch-2 checkpoint lands -------------------
    proc = subprocess.Popen(
        argv, env=_env(tmp_path), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    deadline = time.time() + 300
    while time.time() < deadline:
        if (model_dir / "checkpoint-2.msgpack").exists():
            break
        if proc.poll() is not None:
            raise AssertionError(
                "run 1 exited before checkpoint-2:\n" + proc.stdout.read()
            )
        time.sleep(0.05)
    else:
        proc.kill()
        raise AssertionError("checkpoint-2 never appeared")
    proc.send_signal(signal.SIGKILL)
    proc.wait()

    killed_at = max(
        int(p.name.split("-")[1].split(".")[0])
        for p in model_dir.glob("checkpoint-*.msgpack")
    )
    assert killed_at >= 2
    if killed_at >= EPOCHS:
        # The run outpaced the kill (timing-dependent); the mid-run resume
        # assertions below would be vacuous — covered instead by
        # test_resume_is_noop_when_complete.
        pytest.skip("run 1 completed before SIGKILL landed")

    # --- run 2: identical command; must resume, not restart -----------------
    res = subprocess.run(
        argv, env=_env(tmp_path), capture_output=True, text=True, timeout=420,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert f"Resuming from checkpoint epoch {killed_at}" in res.stdout
    # It trained only the remaining epochs (epoch numbering continued)...
    assert f"Epoch {killed_at + 1}/{EPOCHS}" in res.stdout
    assert f"Epoch {EPOCHS}/{EPOCHS}" in res.stdout
    assert f"Epoch {killed_at}/{EPOCHS}" not in res.stdout
    # ...and every epoch checkpoint exists.
    for e in range(1, EPOCHS + 1):
        assert (model_dir / f"checkpoint-{e}.msgpack").exists()

    # --- step-counter continuity: the final state counts ALL steps ----------
    import jax.numpy as jnp

    import horovod_tpu as hvt
    from horovod_tpu import checkpoint
    from horovod_tpu.models.cnn import MnistCNN

    trainer = hvt.Trainer(
        MnistCNN(compute_dtype=jnp.bfloat16),
        hvt.DistributedOptimizer(optax.adam(hvt.scale_lr(0.001))),
    )
    rng = np.random.RandomState(0)
    template = trainer.build(rng.rand(1, 28, 28, 1).astype(np.float32))
    final = checkpoint.restore(
        str(model_dir / f"checkpoint-{EPOCHS}.msgpack"), template
    )
    assert int(final.step) == EPOCHS * STEPS


@pytest.mark.slow
def test_resume_is_noop_when_complete(tmp_path):
    """Relaunching a COMPLETED run trains zero further epochs."""
    argv = [sys.executable, os.path.join(REPO, "examples", "tf2_style_mnist.py")]
    env = _env(tmp_path)
    first = subprocess.run(argv, env=env, capture_output=True, text=True, timeout=420)
    assert first.returncode == 0, first.stdout + first.stderr
    again = subprocess.run(argv, env=env, capture_output=True, text=True, timeout=420)
    assert again.returncode == 0, again.stdout + again.stderr
    assert f"Resuming from checkpoint epoch {EPOCHS}" in again.stdout
    assert "Epoch " not in again.stdout  # nothing left to train