"""Per-rule `hvt-lint` units over fixture snippets (ISSUE 6 satellite).

Each rule gets positive fixtures seeded with the bug shape it encodes —
including the PR 2 one-sided-shutdown reproduction for HVT001 — plus
negatives for the shapes it must NOT flag, and the suppression paths
(``# hvt: noqa[RULE]``, committed baseline) end to end through
`lint_paths` and the CLI.
"""

import json
import os
import textwrap

import pytest

from horovod_tpu.analysis import callgraph, cli, core, registry
from horovod_tpu.analysis.rules import (
    CheckpointWriteAtomicity,
    MetricRegistryDiscipline,
    CollectiveOrderDivergence,
    CollectiveSymmetry,
    DataLayerSeededRng,
    EnvKnobRegistry,
    ExpertAllToAllDiscipline,
    ReductionComposition,
    ScheduleDivergence,
    TeardownDiscipline,
    TracingHazards,
    TunableKnobResolverOnly,
)


def findings_of(rule_cls, src, relpath="horovod_tpu/fake.py"):
    """Run ONE rule over a source snippet (no noqa/baseline filtering —
    that layer is covered through `lint_paths` below)."""
    module = core.ModuleSource(
        "/fake/" + relpath, relpath, textwrap.dedent(src)
    )
    return list(rule_cls().check(module))


def lint_tree(tmp_path, files, **kwargs):
    """Write `files` ({relpath: source}) under tmp_path and lint the tree
    with the full pipeline (noqa + baseline)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    kwargs.setdefault("baseline_path", None)
    return core.lint_paths([str(tmp_path)], root=str(tmp_path), **kwargs)


class TestHVT001CollectiveSymmetry:
    def test_rank_gated_psum_flagged(self):
        found = findings_of(CollectiveSymmetry, """
            from horovod_tpu.parallel.collectives import psum
            def step(x):
                if rank() == 0:
                    return psum(x)
                return x
        """)
        assert len(found) == 1
        assert found[0].rule == "HVT001" and "psum" in found[0].message

    def test_pr2_one_sided_shutdown_shape(self):
        """The seeded PR 2 fixture: `runtime.shutdown` is a BARRIER; a
        rank-gated call tears down one side and SIGABRTs the survivors
        (CHANGES.md PR 2) — exactly the shape HVT001 exists for."""
        found = findings_of(CollectiveSymmetry, """
            from horovod_tpu import runtime

            def leave_early(world):
                if runtime.process_rank() != 0:
                    runtime.shutdown()
        """)
        assert [f.rule for f in found] == ["HVT001"]
        assert "runtime.shutdown" in found[0].message

    def test_attribute_rank_gate_and_while(self):
        found = findings_of(CollectiveSymmetry, """
            def f(world, x):
                while world.process_index == 0:
                    barrier()
        """)
        assert len(found) == 1

    def test_boolop_short_circuit_gate(self):
        flagged = findings_of(CollectiveSymmetry, """
            def f(x):
                ok = rank() == 0 and broadcast_object(x)
        """)
        assert len(flagged) == 1
        # Operand BEFORE the rank test is unconditionally evaluated.
        clean = findings_of(CollectiveSymmetry, """
            def f(x):
                ok = broadcast_object(x) and rank() == 0
        """)
        assert clean == []

    def test_else_branch_of_rank_gate_flagged(self):
        # Either arm of a rank-conditional is rank-asymmetric.
        found = findings_of(CollectiveSymmetry, """
            def f(x):
                if is_primary():
                    pass
                else:
                    allgather_object(x)
        """)
        assert len(found) == 1

    def test_ungated_collective_clean(self):
        assert findings_of(CollectiveSymmetry, """
            def step(x):
                y = psum(x)
                if rank() == 0:
                    print(y)
                return y
        """) == []

    def test_def_under_gate_is_not_execution(self):
        # A function DEFINED under a rank gate is not thereby CALLED
        # under it (tracking call sites needs dataflow; documented limit).
        assert findings_of(CollectiveSymmetry, """
            def f(x):
                if rank() == 0:
                    def helper(y):
                        return psum(y)
                return x
        """) == []

    def test_qualified_shutdown_needs_runtime_like_owner(self):
        # `httpd.shutdown()` under a rank gate is a same-name method on an
        # unrelated object — must not be flagged.
        assert findings_of(CollectiveSymmetry, """
            def stop(httpd):
                if rank() == 0:
                    httpd.shutdown()
        """) == []

    def test_elastic_state_sync_qualified_forms(self):
        found = findings_of(CollectiveSymmetry, """
            def agree(self, x):
                if process_index() == 0:
                    self.state.sync(x)
        """)
        assert len(found) == 1
        assert findings_of(CollectiveSymmetry, """
            def f(conn):
                if rank() == 0:
                    conn.sync()
        """) == []


class TestHVT001Interprocedural:
    """The PR 9 tentpole: rank-taint propagation through the call graph.
    A collective reached only through a rank-gated HELPER — one or more
    hops deep, across modules — is the seeded PR 2 shape the lexical
    rule deliberately missed."""

    def test_two_hops_in_one_module(self):
        """The acceptance fixture: gate -> helper -> inner -> psum, two
        call hops between the gate and the collective."""
        found = findings_of(CollectiveSymmetry, """
            from horovod_tpu.parallel.collectives import psum

            def inner(x):
                return psum(x)

            def helper(x):
                return inner(x)

            def step(x):
                if rank() == 0:
                    helper(x)
        """)
        assert len(found) == 1
        assert "helper -> inner -> psum" in found[0].message
        assert "rank-conditional" in found[0].message

    def test_cross_module_helper(self, tmp_path):
        """The same shape split across files: resolution rides the
        import-alias map and the module-set call graph."""
        res = lint_tree(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/helpers.py": """
                from pkg.deep import inner
                def helper(x):
                    return inner(x)
            """,
            "pkg/deep.py": """
                def inner(x):
                    return psum(x)
            """,
            "pkg/main.py": """
                from pkg import helpers
                def step(x):
                    if rank() == 0:
                        helpers.helper(x)
            """,
        }, select=["HVT001"])
        assert [f.path for f in res.findings] == ["pkg/main.py"]
        assert "helpers.helper -> inner -> psum" in res.findings[0].message

    def test_self_method_resolution(self):
        found = findings_of(CollectiveSymmetry, """
            class Agreement:
                def _announce(self, x):
                    return broadcast_object(x)

                def maybe(self, x):
                    if self.is_primary:
                        self._announce(x)
        """)
        assert len(found) == 1
        assert "self._announce" in found[0].message

    def test_ungated_transitive_call_clean(self):
        assert findings_of(CollectiveSymmetry, """
            def helper(x):
                return psum(x)

            def step(x):
                helper(x)
                if rank() == 0:
                    print(x)
        """) == []

    def test_gated_inside_callee_does_not_taint_call_site(self):
        """A helper that gates its own collective is flagged AT the
        internal site (that finding stands on its own); calling such a
        helper under a gate adds no second finding — its effect summary
        is rank-gated, not issues-collective."""
        found = findings_of(CollectiveSymmetry, """
            def helper(x):
                if rank() == 0:
                    psum(x)

            def step(x):
                if is_primary():
                    helper(x)
        """)
        assert len(found) == 1
        assert found[0].line == 4  # the psum inside helper, not the call

    def test_unresolvable_call_never_taints(self):
        # A call the module set cannot resolve (stdlib, dynamic) must
        # not propagate taint — no guessing.
        assert findings_of(CollectiveSymmetry, """
            import os
            def step(x):
                if rank() == 0:
                    os.listdir(".")
        """) == []

    def test_redefined_function_body_still_scanned(self):
        """A fallback redefinition (the try-import shape) must not put
        the second def's body in the dark: the clash gets a synthetic
        non-addressable unit and its gated collective is still a
        finding — lexical-rule parity."""
        found = findings_of(CollectiveSymmetry, """
            def save(x):
                return x

            def save(x):
                if rank() == 0:
                    barrier()
        """)
        assert len(found) == 1
        assert "barrier" in found[0].message

    def test_noqa_suppresses_call_site(self, tmp_path):
        res = lint_tree(tmp_path, {"m.py": """
            def helper(x):
                return psum(x)

            def step(x):
                if rank() == 0:
                    helper(x)  # hvt: noqa[HVT001]
        """}, select=["HVT001"])
        assert res.findings == []

    def test_effect_classification_summary(self):
        """The callgraph's three-way classification is observable."""
        m = core.ModuleSource("/fake/m.py", "m.py", textwrap.dedent("""
            def issues(x):
                return psum(x)
            def gated(x):
                if rank() == 0:
                    barrier()
            def clean(x):
                return x + 1
            def transitive(x):
                return issues(x)
        """))
        g = callgraph.CallGraph([m])
        s = g.summary()
        assert s["m:issues"] == callgraph.ISSUES
        assert s["m:gated"] == callgraph.RANK_GATED
        assert s["m:clean"] == callgraph.CLEAN
        assert s["m:transitive"] == callgraph.ISSUES
        assert g.witness("m:transitive") == ["issues", "psum"]


class TestHVT002TeardownDiscipline:
    def test_direct_jax_distributed_shutdown_flagged(self):
        found = findings_of(TeardownDiscipline, """
            import jax
            def cleanup():
                jax.distributed.shutdown()
        """)
        assert [f.rule for f in found] == ["HVT002"]

    def test_import_alias_resolved(self):
        found = findings_of(TeardownDiscipline, """
            from jax import distributed
            def cleanup():
                distributed.shutdown()
        """)
        assert len(found) == 1

    def test_clear_backends_flagged(self):
        found = findings_of(TeardownDiscipline, """
            from horovod_tpu import compat
            def reset():
                compat.clear_backends()
        """)
        assert len(found) == 1 and "clear_backends" in found[0].message

    def test_sanctioned_modules_exempt(self):
        src = """
            import jax
            def _teardown_and_interrupt():
                jax.distributed.shutdown()
        """
        for rel in ("horovod_tpu/elastic/rescale.py",
                    "horovod_tpu/elastic/state.py",
                    "horovod_tpu/runtime.py",
                    "horovod_tpu/compat.py"):
            assert findings_of(TeardownDiscipline, src, relpath=rel) == []
        assert len(findings_of(
            TeardownDiscipline, src, relpath="horovod_tpu/training/x.py"
        )) == 1

    def test_runtime_shutdown_wrapper_clean(self):
        # The sanctioned wrapper is the REPLACEMENT, not a violation.
        assert findings_of(TeardownDiscipline, """
            from horovod_tpu import runtime
            def cleanup():
                runtime.shutdown()
        """) == []


class TestHVT003TracingHazards:
    def test_time_in_jitted_function(self):
        found = findings_of(TracingHazards, """
            import time
            import jax
            @jax.jit
            def step(x):
                t = time.time()
                return x + t
        """)
        assert [f.rule for f in found] == ["HVT003"]
        assert "trace time" in found[0].message

    def test_seed_free_numpy_random(self):
        found = findings_of(TracingHazards, """
            import numpy as np
            from jax import jit
            @jit
            def noise(x):
                return x + np.random.rand()
        """)
        assert len(found) == 1 and "numpy.random.rand" in found[0].message

    def test_jax_random_with_key_clean(self):
        assert findings_of(TracingHazards, """
            from jax import jit, random
            @jit
            def noise(x, key):
                return x + random.normal(key, x.shape)
        """) == []

    def test_environ_read_inside_shard_map(self):
        found = findings_of(TracingHazards, """
            import os
            from jax.experimental.shard_map import shard_map
            @shard_map
            def step(x):
                if os.environ.get("HVT_FAULT"):
                    return x
                return x * 2
        """)
        assert len(found) == 1 and "os.environ" in found[0].message

    def test_scan_body_lambda_and_named(self):
        found = findings_of(TracingHazards, """
            import time
            from jax import lax
            def body(c, x):
                return c, x * time.perf_counter()
            def run(xs):
                lax.scan(body, 0.0, xs)
                lax.scan(lambda c, x: (c, print(x)), 0.0, xs)
        """)
        assert len(found) == 2

    def test_host_effects_outside_trace_clean(self):
        assert findings_of(TracingHazards, """
            import time
            def host_loop(step_fn, xs):
                t0 = time.time()
                for x in xs:
                    step_fn(x)
                print(time.time() - t0)
        """) == []


class TestHVT004EnvKnobRegistry:
    def test_undeclared_literal_flagged(self):
        found = findings_of(EnvKnobRegistry, """
            KNOB = "HVT_DEFINITELY_NOT_DECLARED"
        """)
        assert [f.rule for f in found] == ["HVT004"]

    def test_inline_reads_flagged_even_for_declared_knobs(self):
        found = findings_of(EnvKnobRegistry, """
            import os
            a = os.environ.get("HVT_FAULT")
            b = os.getenv("HVT_FAULT")
            c = os.environ["HVT_FAULT"]
        """)
        assert len(found) == 3
        assert all("registry" in f.message for f in found)

    def test_registry_accessor_and_plain_literal_clean(self):
        assert findings_of(EnvKnobRegistry, """
            from horovod_tpu.analysis import registry
            a = registry.get_str("HVT_FAULT")
            DOC = "set HVT_FAULT to inject faults"  # not a bare knob literal
        """) == []

    def test_non_hvt_env_reads_out_of_scope(self):
        assert findings_of(EnvKnobRegistry, """
            import os
            p = os.environ.get("PS_MODEL_PATH", "./models")
        """) == []

    def test_every_declared_knob_passes(self):
        src = "NAMES = [" + ",".join(
            repr(name) for name in registry.KNOBS
        ) + "]"
        assert findings_of(EnvKnobRegistry, src) == []


class TestHVT005CheckpointWriteAtomicity:
    def test_truncating_open_flagged(self):
        found = findings_of(CheckpointWriteAtomicity, """
            def save(path, data):
                with open(path, "w") as f:
                    f.write(data)
        """)
        assert [f.rule for f in found] == ["HVT005"]

    def test_mode_kwarg_and_update_modes(self):
        found = findings_of(CheckpointWriteAtomicity, """
            def f(path):
                a = open(path, mode="wb")
                b = open(path, "r+b")
        """)
        assert len(found) == 2

    def test_reads_and_appends_clean(self):
        assert findings_of(CheckpointWriteAtomicity, """
            def f(path):
                a = open(path)
                b = open(path, "rb")
                c = open(path, "a")  # append streams cannot tear history
        """) == []

    def test_atomic_write_helper_sanctioned(self):
        assert findings_of(CheckpointWriteAtomicity, """
            import os
            def _atomic_write(path, data):
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
        """) == []


class TestHVT006DataLayerSeededRng:
    """HVT006: unseeded RNG inside horovod_tpu/data/ — the determinism
    invariant the durable stream cursors depend on (ISSUE 8 satellite)."""

    DATA = "horovod_tpu/data/fake.py"

    def test_global_numpy_rng_flagged(self):
        found = findings_of(DataLayerSeededRng, """
            import numpy as np
            def order(n):
                return np.random.permutation(n)
        """, relpath=self.DATA)
        assert [f.rule for f in found] == ["HVT006"]
        assert "numpy.random.permutation" in found[0].message

    def test_stdlib_global_rng_flagged(self):
        found = findings_of(DataLayerSeededRng, """
            import random
            def pick(xs):
                random.shuffle(xs)
                return random.randint(0, 9)
        """, relpath=self.DATA)
        assert len(found) == 2

    def test_seedless_generator_ctors_flagged(self):
        found = findings_of(DataLayerSeededRng, """
            import numpy as np
            rng1 = np.random.RandomState()
            rng2 = np.random.default_rng()
        """, relpath=self.DATA)
        assert len(found) == 2

    def test_seeded_generators_clean(self):
        assert findings_of(DataLayerSeededRng, """
            import numpy as np
            def order(seed, epoch, n):
                rng = np.random.RandomState(seed)
                g = np.random.default_rng(seed=epoch)
                s = np.random.SeedSequence([seed, epoch])
                return rng.permutation(n), g, s
        """, relpath=self.DATA) == []

    def test_method_calls_on_local_generators_clean(self):
        # rng.shuffle/rng.randint resolve through the LOCAL name, not
        # the numpy.random global module — never flagged.
        assert findings_of(DataLayerSeededRng, """
            import numpy as np
            def draw(seed):
                rng = np.random.RandomState(seed)
                rng.shuffle([1, 2])
                return rng.randint(3)
        """, relpath=self.DATA) == []

    def test_outside_data_layer_not_scoped(self):
        assert findings_of(DataLayerSeededRng, """
            import numpy as np
            x = np.random.permutation(8)
        """, relpath="horovod_tpu/training/fake.py") == []


class TestHVT007CollectiveOrderDivergence:
    """Sibling branches issuing different collective sequences — the
    cross-rank mismatched-submission-order deadlock class."""

    def test_direct_order_divergence_flagged(self):
        found = findings_of(CollectiveOrderDivergence, """
            def step(x, phase):
                if phase:
                    psum(x)
                    allgather(x)
                else:
                    allgather(x)
                    psum(x)
        """)
        assert [f.rule for f in found] == ["HVT007"]
        assert "['psum', 'allgather']" in found[0].message
        assert "['allgather', 'psum']" in found[0].message

    def test_divergence_through_helpers_flagged(self):
        """Callee sequences are inlined: the branches LOOK symmetric
        (one call each) but the helpers issue different collectives."""
        found = findings_of(CollectiveOrderDivergence, """
            def path_a(x):
                psum(x)

            def path_b(x):
                broadcast(x)

            def step(x, phase):
                if phase:
                    path_a(x)
                else:
                    path_b(x)
        """)
        assert len(found) == 1
        assert "['psum']" in found[0].message
        assert "['broadcast']" in found[0].message

    def test_same_sequence_both_arms_clean(self):
        assert findings_of(CollectiveOrderDivergence, """
            def step(x, phase):
                if phase:
                    y = psum(x)
                else:
                    y = psum(x * 2)
        """) == []

    def test_collective_free_branch_is_hvt001_territory(self):
        # One silent arm is only a bug under a rank-varying condition —
        # exactly what HVT001's gate detection covers; HVT007 stays out.
        assert findings_of(CollectiveOrderDivergence, """
            def step(x, phase):
                if phase:
                    psum(x)
                else:
                    log(x)
        """) == []

    def test_repeat_count_divergence_flagged(self):
        """A helper called TWICE in one arm vs once in the other submits
        a different number of collectives — the cycle guard must pop
        after inlining (recursion-only), not swallow sibling repeats."""
        found = findings_of(CollectiveOrderDivergence, """
            def helper(x):
                psum(x)

            def step(x, phase):
                if phase:
                    helper(x)
                    helper(x)
                else:
                    helper(x)
        """)
        assert len(found) == 1
        assert "['psum', 'psum']" in found[0].message

    def test_recursive_helper_terminates(self):
        found = findings_of(CollectiveOrderDivergence, """
            def loop(x, n):
                psum(x)
                return loop(x, n - 1)

            def step(x, phase):
                if phase:
                    loop(x, 3)
                else:
                    broadcast(x)
        """)
        assert len(found) == 1  # and no RecursionError

    def test_uniform_config_branch_noqa(self, tmp_path):
        res = lint_tree(tmp_path, {"m.py": """
            def reduce(x, quantized):
                if quantized:  # hvt: noqa[HVT007] config-uniform branch
                    allgather(x)
                else:
                    psum(x)
        """}, select=["HVT007"])
        assert res.findings == []


class TestHVT008ReductionComposition:
    """Per-leaf gradient reductions in the accumulation/ZeRO surface
    must route through `collectives.reduce_gradients` (ROADMAP item 3's
    pinned guardrail)."""

    def test_tree_mapped_psum_lambda_flagged(self):
        found = findings_of(ReductionComposition, """
            # wires backward_passes_per_step into the step
            import jax
            def reduce(grads):
                return jax.tree.map(lambda g: psum(g, 'data'), grads)
        """)
        assert [f.rule for f in found] == ["HVT008"]
        assert "reduce_gradients" in found[0].message

    def test_tree_mapped_named_local_fn_flagged(self):
        found = findings_of(ReductionComposition, """
            # wires backward_passes_per_step into the step
            import jax
            def _one(g):
                return hierarchical_psum(g, 'data', 2)
            def reduce(grads):
                return jax.tree.map(_one, grads)
        """)
        assert len(found) == 1

    def test_raw_psum_scatter_flagged(self):
        found = findings_of(ReductionComposition, """
            from jax import lax
            def shard_update_reduce(grads, spec):
                return lax.psum_scatter(grads, 'data')
        """)
        assert len(found) == 1
        assert "psum_scatter" in found[0].message

    def test_outside_surface_module_not_scoped(self):
        assert findings_of(ReductionComposition, """
            import jax
            def reduce(grads):
                return jax.tree.map(lambda g: psum(g, 'data'), grads)
        """) == []

    def test_metric_pmean_tree_map_clean(self):
        # Scalar-metric bookkeeping (trainer.py's sown-metrics pmean) is
        # not gradient reduction — pmean per leaf stays legal.
        assert findings_of(ReductionComposition, """
            # wires backward_passes_per_step into the step
            import jax
            def metrics(sm):
                return jax.tree.map(lambda v: jax.lax.pmean(v, 'data'), sm)
        """) == []

    def test_entry_point_module_exempt(self):
        src = """
            # wires backward_passes_per_step into the step
            import jax
            def reduce_gradients(grads):
                return jax.tree.map(lambda g: psum(g, 'data'), grads)
        """
        assert findings_of(
            ReductionComposition, src,
            relpath="horovod_tpu/parallel/collectives.py",
        ) == []
        assert len(findings_of(
            ReductionComposition, src,
            relpath="horovod_tpu/training/zero1.py",
        )) == 1

    def test_routed_through_entry_point_clean(self):
        assert findings_of(ReductionComposition, """
            # wires backward_passes_per_step into the step
            from horovod_tpu.parallel import collectives
            def boundary(grads, k):
                return collectives.reduce_gradients(grads, reverse=True)
        """) == []


class TestHVT010ScheduleDivergence:
    """Whole-program schedule verification (ISSUE 14 tentpole): every
    rank-feasible path through a unit must submit the same collective
    sequence. The matrix seeds the shapes the first two layers cannot
    see — and the rank-gated-but-agreeing shapes that must NOT fire."""

    def test_rank_gated_early_return_flagged(self):
        """The canonical HVT001/HVT007-invisible deadlock: no collective
        under the gate, no sibling arm — rank 0 just skips the psum
        every other rank blocks in."""
        found = findings_of(ScheduleDivergence, """
            def step(x):
                if rank() == 0:
                    return x
                return psum(x)
        """)
        assert [f.rule for f in found] == ["HVT010"]
        assert "DIVERGENT" in found[0].message
        assert "`psum`" in found[0].message
        assert "first mismatched submission at op 0" in found[0].message
        # Anchored at the rank fork, where the noqa belongs.
        assert found[0].line == 3

    def test_two_hop_cross_module_divergent_schedule(self, tmp_path):
        """The 2-hop cross-module case: the gate lives in the entry
        module, the collective two call hops away in another — the
        witness chain still names the fork and the mismatched op."""
        res = lint_tree(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/helpers.py": """
                from pkg.deep import inner
                def finish(x):
                    return inner(x)
            """,
            "pkg/deep.py": """
                def inner(x):
                    return psum(x)
            """,
            "pkg/main.py": """
                from pkg import helpers
                def step(x):
                    if rank() == 0:
                        return x
                    return helpers.finish(x)
            """,
        }, select=["HVT010"])
        assert [f.path for f in res.findings] == ["pkg/main.py"]
        msg = res.findings[0].message
        assert "['psum']" in msg and "[]" in msg

    def test_loop_count_divergence_flagged(self):
        """A loop whose trip count reads the rank submits a different
        NUMBER of collectives per rank — no gate for HVT001, no sibling
        arm for HVT007; the {0, 1}-iteration bound witnesses it."""
        found = findings_of(ScheduleDivergence, """
            from horovod_tpu import runtime
            def drain(x):
                for _ in range(runtime.rank()):
                    psum(x)
                return x
        """)
        assert [f.rule for f in found] == ["HVT010"]
        assert "0-iterations" in found[0].message

    def test_rank_gated_but_agreeing_arms_clean(self):
        """Both arms submit the SAME sequence (the root/non-root
        broadcast idiom): rank-feasible paths agree — no finding."""
        assert findings_of(ScheduleDivergence, """
            def pick(x):
                if rank() == 0:
                    cfg = broadcast_object(x)
                else:
                    cfg = broadcast_object(None)
                return cfg
        """) == []

    def test_uniform_config_pick_clean(self):
        """elastic/state.py's transport pick, in miniature: the branch
        reads an ALLGATHERED vote — uniform across ranks — so the two
        transports are separate configurations, never compared (the
        false positive the rank-predicate awareness exists to avoid)."""
        assert findings_of(ScheduleDivergence, """
            def sync(self, root):
                votes = allgather_object(self._vote())
                if all(v == votes[root] for v in votes):
                    return
                if votes[root][0] is not None:
                    self._c = broadcast_pytree(self._c, root=root)
                else:
                    self._c = broadcast_object(self._c, root=root)
        """) == []

    def test_hvt007_invisible_cross_function_case(self):
        """The gate travels as an ARGUMENT: `step` passes `rank() == 0`
        into a helper whose one-armed branch on that parameter issues an
        extra collective. HVT007 needs both arms of one `if` to carry
        collectives; HVT001 needs a syntactic rank read at the gate —
        both stay silent, the path pair diverges."""
        src = """
            def phase(x, flag):
                if flag:
                    psum(x)
                allgather(x)

            def step(x):
                phase(x, rank() == 0)
        """
        assert findings_of(CollectiveOrderDivergence, src) == []
        assert findings_of(CollectiveSymmetry, src) == []
        found = findings_of(ScheduleDivergence, src)
        assert len(found) == 1
        msg = found[0].message
        assert "['psum', 'allgather']" in msg
        assert "['allgather']" in msg
        assert "`psum` vs `allgather`" in msg

    def test_rank_returning_helper_gates_the_branch(self):
        """Rank taint through RETURN VALUES: branching on a helper that
        returns `rank() == 0` is a rank fork, however many modules away
        the rank read lives."""
        found = findings_of(ScheduleDivergence, """
            def is_root():
                return rank() == 0

            def step(x):
                if is_root():
                    return x
                return broadcast_object(x)
        """)
        assert len(found) == 1

    def test_rebound_uniform_local_clears_taint(self):
        """Taint soundness direction: a local once bound to a rank read
        but REBOUND to a uniform value must not keep gating — stale
        taint would invent divergences on provably-uniform branches."""
        assert findings_of(ScheduleDivergence, """
            def step(x):
                flag = rank() == 0
                flag = False
                if flag:
                    return x
                return psum(x)
        """) == []
        # AugAssign keeps the taint (the old rank value still feeds it).
        found = findings_of(ScheduleDivergence, """
            def step(x):
                n = rank()
                n += 1
                if n:
                    return x
                return psum(x)
        """)
        assert len(found) == 1

    def test_divergent_helper_reported_once(self):
        """A divergent helper is ITS finding; callers inline one
        representative path and do not re-report it."""
        found = findings_of(ScheduleDivergence, """
            def helper(x):
                if rank() == 0:
                    return x
                return psum(x)

            def caller_a(x):
                return helper(x)

            def caller_b(x):
                return helper(x)
        """)
        assert len(found) == 1

    def test_noqa_suppresses_at_fork_line(self, tmp_path):
        res = lint_tree(tmp_path, {"m.py": """
            def step(x):
                if rank() == 0:  # hvt: noqa[HVT010] single-proc test path
                    return x
                return psum(x)
        """}, select=["HVT010"])
        assert res.findings == []

    def test_entry_report_on_fixture_project(self):
        """`schedule.entry_report` summarizes the real entry automata
        (the hvt-sched check banner): path/configuration counts and the
        agree verdict are observable per entry."""
        import textwrap

        from horovod_tpu.analysis import callgraph, schedule

        m = core.ModuleSource(
            "/fake/horovod_tpu/elastic/state.py",
            "horovod_tpu/elastic/state.py",
            textwrap.dedent("""
                class ElasticState:
                    def sync(self, root):
                        votes = allgather_object(self._vote())
                        if votes:
                            self._c = broadcast_object(self._c, root=root)
                        else:
                            self._c = broadcast_object(None, root=root)
            """),
        )
        graph = callgraph.CallGraph([m])
        rows = schedule.entry_report(graph)
        assert [r["unit"] for r in rows] == [
            "horovod_tpu.elastic.state:ElasticState.sync"
        ]
        assert rows[0]["agree"]
        assert rows[0]["sequence"][0] == "allgather_object"


class TestHVT011ExpertAllToAllDiscipline:
    """EP dispatch/combine all-to-alls route through the collectives
    entry point (ROADMAP item 4's wire discipline)."""

    EP_SRC = """
        from jax import lax
        from horovod_tpu.parallel.mesh import EXPERT_AXIS
        def dispatch(x):
            return lax.all_to_all(x, EXPERT_AXIS, 0, 0, tiled=True)
    """

    def test_raw_lax_all_to_all_flagged(self):
        found = findings_of(ExpertAllToAllDiscipline, self.EP_SRC)
        assert [f.rule for f in found] == ["HVT011"]
        assert "collectives.all_to_all" in found[0].message

    def test_routed_through_entry_point_clean(self):
        assert findings_of(ExpertAllToAllDiscipline, """
            from horovod_tpu.parallel import collectives
            def dispatch(x, n_experts):
                return collectives.all_to_all(x, 'expert')
        """) == []

    def test_outside_ep_surface_not_scoped(self):
        # A quantized-wire all-to-all in a module with no EP vocabulary
        # is HVT008/entry-point territory, not this rule's.
        assert findings_of(ExpertAllToAllDiscipline, """
            from jax import lax
            def shuffle(x):
                return lax.all_to_all(x, 'data', 0, 0)
        """) == []

    def test_entry_module_exempt(self):
        assert findings_of(
            ExpertAllToAllDiscipline, self.EP_SRC,
            relpath="horovod_tpu/parallel/collectives.py",
        ) == []


class TestHVT012TunableKnobResolverOnly:
    """Raw environ reads of knobs carrying `tunable=` domain metadata are
    autotuning blind spots (ISSUE 19): `hvt-tune` writes the
    resolver-visible env surface, so a bypassing read sees values the
    tuner can neither observe nor override."""

    def test_tunable_knob_raw_reads_flagged_all_shapes(self):
        found = findings_of(TunableKnobResolverOnly, """
            import os
            a = os.environ.get("HVT_BUCKET_BYTES", "0")
            b = os.getenv("HVT_OVERLAP_REDUCTION")
            c = os.environ["HVT_COMPRESSION"]
        """)
        assert [f.rule for f in found] == ["HVT012"] * 3
        assert all("tuning blind spot" in f.message for f in found)

    def test_non_tunable_registered_knob_out_of_scope(self):
        # HVT_FAULT has no tunable= domain — an inline read is HVT004's
        # generic finding, not this rule's.
        assert findings_of(TunableKnobResolverOnly, """
            import os
            a = os.environ.get("HVT_FAULT")
        """) == []

    def test_registry_accessor_and_literal_clean(self):
        assert findings_of(TunableKnobResolverOnly, """
            from horovod_tpu.analysis import registry
            a = registry.get_int("HVT_BUCKET_BYTES")
            DOC = "tune HVT_BUCKET_BYTES via hvt-tune"  # bare literal: fine
        """) == []

    def test_registry_resolver_module_exempt(self):
        assert findings_of(TunableKnobResolverOnly, """
            import os
            raw = os.environ.get("HVT_BUCKET_BYTES")
        """, relpath="horovod_tpu/analysis/registry.py") == []

    def test_every_tunable_knob_is_in_scope(self):
        # The rule's key set IS the registry's tunable set — a knob
        # gaining tunable= metadata gains the protection automatically.
        names = sorted(registry.tunable_knobs())
        src = "import os\n" + "\n".join(
            f"v{i} = os.getenv({n!r})" for i, n in enumerate(names)
        )
        found = findings_of(TunableKnobResolverOnly, src)
        assert len(found) == len(names) == 5


class TestRulesDocAndExplain:
    def test_generated_doc_covers_every_rule(self):
        doc = core.generate_rules_doc()
        for cls in core.iter_rules():
            assert f"## {cls.rule_id}" in doc
            assert cls.title in doc

    def test_explain_prints_rationale(self, capsys):
        assert cli.main(["--explain", "HVT007"]) == 0
        out = capsys.readouterr().out
        assert "HVT007" in out and "Why:" in out and "Provenance:" in out

    def test_explain_unknown_rule_is_usage_error(self, capsys):
        assert cli.main(["--explain", "HVT999"]) == 2
        assert "unknown rule" in capsys.readouterr().err


class TestSuppressionsAndBaseline:
    SRC = """
        import os
        a = os.environ.get("HVT_FAULT")
    """

    def test_noqa_rule_scoped(self, tmp_path):
        res = lint_tree(tmp_path, {"m.py": """
            import os
            a = os.environ.get("HVT_FAULT")  # hvt: noqa[HVT004]
            b = os.environ.get("HVT_FAULT")  # hvt: noqa[HVT001]
            c = os.environ.get("HVT_FAULT")  # hvt: noqa
        """})
        # a suppressed (right rule), b NOT (wrong rule), c suppressed (all).
        assert [f.line for f in res.findings] == [4]

    def test_baseline_matches_by_snippet_not_line(self, tmp_path):
        baseline = tmp_path / "baseline.json"
        baseline.write_text(json.dumps({"findings": [{
            "rule": "HVT004", "path": "m.py",
            "snippet": 'a = os.environ.get("HVT_FAULT")',
            "justification": "grandfathered for the test",
        }]}))
        # Extra lines ABOVE the finding: line number moved, snippet same.
        res = lint_tree(tmp_path, {"m.py": """
            import os

            # comment pushing the read down some lines
            a = os.environ.get("HVT_FAULT")
        """}, baseline_path=str(baseline))
        assert res.findings == [] and len(res.baselined) == 1

        # Editing the flagged LINE invalidates the baseline entry.
        res2 = lint_tree(tmp_path, {"m.py": """
            import os
            a = os.environ.get("HVT_FAULT") or "edited"
        """}, baseline_path=str(baseline))
        assert len(res2.findings) == 1 and res2.baselined == []

    def test_baseline_requires_justification(self, tmp_path):
        bad = tmp_path / "baseline.json"
        bad.write_text(json.dumps({"findings": [{
            "rule": "HVT004", "path": "m.py", "snippet": "x",
        }]}))
        with pytest.raises(ValueError, match="justification"):
            core.load_baseline(str(bad))

    def test_syntax_error_is_a_finding(self, tmp_path):
        res = lint_tree(tmp_path, {"broken.py": "def f(:\n"})
        assert [f.rule for f in res.findings] == [core.PARSE_ERROR_RULE]

    def test_out_of_root_paths_anchor_at_package_dir(self, tmp_path):
        """Absolute inputs from another cwd (editor/CI integrations) must
        key the HVT002 sanctioned set and the baseline by the SAME
        package-relative paths as a repo-root run — not by raw absolute
        paths that match nothing."""
        pkg = tmp_path / "checkout" / "horovod_tpu"
        (pkg / "elastic").mkdir(parents=True)
        (pkg / "elastic" / "rescale.py").write_text(textwrap.dedent("""
            import jax
            def _teardown_and_interrupt():
                jax.distributed.shutdown()
        """))
        elsewhere = tmp_path / "elsewhere"
        elsewhere.mkdir()
        res = core.lint_paths(
            [str(pkg)], root=str(elsewhere), baseline_path=None
        )
        assert res.findings == []  # sanctioned module still recognized

    def test_select_subset(self, tmp_path):
        res = lint_tree(tmp_path, {"m.py": self.SRC}, select=["HVT001"])
        assert res.findings == []
        res = lint_tree(tmp_path, {"m.py": self.SRC}, select=["HVT004"])
        assert len(res.findings) == 1


class TestCLI:
    def test_exit_codes_and_write_baseline(self, tmp_path, capsys):
        (tmp_path / "m.py").write_text(
            'import os\na = os.environ.get("HVT_FAULT")\n'
        )
        baseline = tmp_path / "baseline.json"
        argv = [str(tmp_path), "--root", str(tmp_path),
                "--baseline", str(baseline)]
        assert cli.main(argv) == 1  # finding, no baseline yet
        assert "HVT004" in capsys.readouterr().out

        assert cli.main(argv + ["--write-baseline"]) == 0
        capsys.readouterr()
        assert baseline.exists()
        assert cli.main(argv) == 0  # grandfathered now
        assert "1 baselined" in capsys.readouterr().out
        assert cli.main(argv + ["--no-baseline"]) == 1
        capsys.readouterr()

        (tmp_path / "clean.py").write_text("x = 1\n")
        assert cli.main([str(tmp_path / "clean.py")]) == 0

    def test_missing_or_empty_paths_are_usage_errors(self, tmp_path,
                                                     capsys):
        """A gate that lints NOTHING must not report clean: a typo'd
        path and a .py-free directory both exit 2, not 0."""
        assert cli.main([str(tmp_path / "no_such_dir")]) == 2
        assert "no such file" in capsys.readouterr().err
        empty = tmp_path / "empty"
        empty.mkdir()
        assert cli.main([str(empty)]) == 2
        assert "nothing was linted" in capsys.readouterr().err

    def test_write_baseline_preserves_justifications(self, tmp_path,
                                                     capsys):
        """Re-running --write-baseline must keep hand-written
        justifications for findings that still fire, and a --select run
        must carry other rules' entries over instead of dropping them."""
        (tmp_path / "m.py").write_text(
            'import os\n'
            'a = os.environ.get("HVT_FAULT")\n'
            'def f(p):\n'
            '    return open(p, "w")\n'
        )
        baseline = tmp_path / "baseline.json"
        argv = [str(tmp_path), "--root", str(tmp_path),
                "--baseline", str(baseline)]
        assert cli.main(argv + ["--write-baseline"]) == 0
        entries = json.loads(baseline.read_text())["findings"]
        assert {e["rule"] for e in entries} == {"HVT004", "HVT005"}
        for e in entries:
            if e["rule"] == "HVT004":
                e["justification"] = "hand-written reason"
        baseline.write_text(json.dumps({"findings": entries}))

        # Full rewrite keeps the hand-written justification.
        assert cli.main(argv + ["--write-baseline"]) == 0
        entries = json.loads(baseline.read_text())["findings"]
        just = {e["rule"]: e["justification"] for e in entries}
        assert just["HVT004"] == "hand-written reason"

        # A rule-subset rewrite must not drop the other rules' entries.
        assert cli.main(
            argv + ["--select", "HVT004", "--write-baseline"]
        ) == 0
        entries = json.loads(baseline.read_text())["findings"]
        assert {e["rule"] for e in entries} == {"HVT004", "HVT005"}
        assert cli.main(argv) == 0  # everything still grandfathered
        capsys.readouterr()

    def test_json_format(self, tmp_path, capsys):
        (tmp_path / "m.py").write_text(
            'import os\na = os.environ.get("HVT_FAULT")\n'
        )
        code = cli.main([str(tmp_path), "--root", str(tmp_path),
                         "--format", "json", "--no-baseline"])
        assert code == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"][0]["rule"] == "HVT004"
        assert payload["findings"][0]["path"] == "m.py"

    def test_list_rules(self, capsys):
        assert cli.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in ("HVT001", "HVT002", "HVT003", "HVT004", "HVT005"):
            assert rid in out


class TestRegistryAccessors:
    def test_unknown_knob_refused(self):
        with pytest.raises(registry.UnknownKnobError):
            registry.get_str("HVT_NOT_A_KNOB")

    def test_empty_string_is_unset(self):
        env = {"HVT_COMMIT_EVERY": ""}
        assert registry.get_int("HVT_COMMIT_EVERY", environ=env) == 1
        env = {"HVT_COMMIT_EVERY": "5"}
        assert registry.get_int("HVT_COMMIT_EVERY", environ=env) == 5
        assert registry.get_int("HVT_DCN_FACTOR", environ={}) is None

    def test_flag_spellings(self):
        for off in ("", "0", "false", "FALSE", "no", "No"):
            assert not registry.get_flag(
                "HVT_NO_NATIVE", environ={"HVT_NO_NATIVE": off}
            )
        for on in ("1", "true", "yes", "anything"):
            assert registry.get_flag(
                "HVT_NO_NATIVE", environ={"HVT_NO_NATIVE": on}
            )

    def test_float_and_default_types(self):
        assert registry.get_float(
            "HVT_RESTART_LOG_MAX_MB", environ={}
        ) == 64.0
        assert registry.get_float(
            "HVT_RESTART_LOG_MAX_MB",
            environ={"HVT_RESTART_LOG_MAX_MB": "0.5"},
        ) == 0.5

    def test_runtime_env_flag_delegates(self):
        # runtime.env_flag and registry.flag_like are the SAME contract
        # by construction (delegation, not duplication).
        from horovod_tpu import runtime

        assert runtime.env_flag.__doc__  # still documented
        os.environ["HVT_FAST_RNG"] = "no"
        try:
            assert not runtime.env_flag("HVT_FAST_RNG")
            os.environ["HVT_FAST_RNG"] = "on"
            assert runtime.env_flag("HVT_FAST_RNG")
        finally:
            del os.environ["HVT_FAST_RNG"]

    def test_generate_doc_covers_every_knob(self):
        doc = registry.generate_doc()
        for name in registry.KNOBS:
            assert f"`{name}`" in doc


class TestHVT009MetricRegistryDiscipline:
    def test_undeclared_metric_name_flagged(self):
        found = findings_of(MetricRegistryDiscipline, """
            from horovod_tpu import obs
            def publish(v):
                obs.gauge("hvt_stpe_ms", v)
        """)
        assert len(found) == 1
        assert found[0].rule == "HVT009"
        assert "hvt_stpe_ms" in found[0].message
        assert "MetricSpec" in found[0].message

    def test_declared_names_clean_across_aliases(self):
        found = findings_of(MetricRegistryDiscipline, """
            from horovod_tpu import obs
            from horovod_tpu.obs import core as obs_core
            def publish(reg, v):
                obs.gauge("hvt_mfu", v)
                obs_core.counter("hvt_scrapes_total")
                obs.histogram("hvt_step_seconds", v)
        """)
        assert found == []

    def test_registry_method_sites_checked_by_convention(self):
        # A Registry instance can't be typed statically; the hvt_ naming
        # convention discriminates emission sites (obs/core naming rule).
        found = findings_of(MetricRegistryDiscipline, """
            def collect(reg):
                reg.counter_set("hvt_not_declared_total", 3)
                reg.gauge("hvt_fleet_size", 2)       # declared — clean
                other.counter("unrelated_api", 1)    # not hvt_ — skipped
        """)
        assert len(found) == 1
        assert "hvt_not_declared_total" in found[0].message

    def test_dynamic_names_skipped(self):
        found = findings_of(MetricRegistryDiscipline, """
            from horovod_tpu import obs
            def publish(name, v):
                obs.gauge(name, v)
        """)
        assert found == []

    def test_obs_call_inside_jit_flagged(self):
        found = findings_of(MetricRegistryDiscipline, """
            import jax
            from horovod_tpu import obs
            @jax.jit
            def step(x):
                obs.counter("hvt_optimizer_steps_total")
                return x
        """)
        assert len(found) == 1
        assert "trace time" in found[0].message

    def test_obs_call_inside_shard_map_and_scan_flagged(self):
        found = findings_of(MetricRegistryDiscipline, """
            from horovod_tpu import compat, obs
            from jax import lax
            def local(x):
                obs.gauge("hvt_mfu", 0.5)
                return x
            f = compat.shard_map(local, mesh=None, in_specs=(), out_specs=())
            def body(c, t):
                obs.gauge("hvt_mfu", 0.5)
                return c, t
            lax.scan(body, 0, None)
        """)
        assert len(found) == 2

    def test_host_side_emission_clean(self):
        found = findings_of(MetricRegistryDiscipline, """
            import jax
            from horovod_tpu import obs
            @jax.jit
            def step(x):
                return x + 1
            def loop(x):
                x = step(x)
                obs.counter("hvt_optimizer_steps_total")
                return x
        """)
        assert found == []

    def test_trace_span_inside_jit_flagged(self):
        # ISSUE 15: a span entered inside a traced body clocks the TRACE
        # and fires once at compile time — a frozen span poisoning the
        # merged timeline's clock anchors.
        found = findings_of(MetricRegistryDiscipline, """
            import jax
            from horovod_tpu import trace
            @jax.jit
            def step(x):
                with trace.span("step"):
                    x = x + 1
                return x
        """)
        assert len(found) == 1
        assert "clocks the TRACE" in found[0].message

    def test_trace_span_alias_inside_scan_flagged(self):
        found = findings_of(MetricRegistryDiscipline, """
            from jax import lax
            from horovod_tpu import trace as trace_lib
            def body(c, t):
                trace_lib.emit_span("decode", 0.0, 0.1)
                return c, t
            lax.scan(body, 0, None)
        """)
        assert len(found) == 1
        assert "emit_span" in found[0].message

    def test_trace_span_on_host_side_clean(self):
        found = findings_of(MetricRegistryDiscipline, """
            import jax
            from horovod_tpu import trace
            @jax.jit
            def step(x):
                return x + 1
            def loop(x):
                with trace.span("step", epoch=0):
                    x = step(x)
                return x
        """)
        assert found == []

    def test_serving_tier_names_declared_clean(self):
        # PR 17: the serving tier's scheduler/router series are declared
        # in obs/core like every other subsystem — the scrape collectors
        # and the router's pre-materialized zero-500s series lint clean,
        # and a typo'd serve series is caught like any other.
        found = findings_of(MetricRegistryDiscipline, """
            def collect(reg, s):
                reg.counter_set("hvt_serve_admitted_total", s["a"])
                reg.counter_set("hvt_serve_retired_total", s["r"])
                reg.counter_set("hvt_serve_rejected_total", s["x"])
                reg.gauge("hvt_serve_live_seqs", s["live"])
                reg.gauge("hvt_serve_kv_blocks_free", s["free"])
                reg.gauge("hvt_serve_replica_inflight", 1, replica="r0")
                reg.histogram("hvt_serve_ttft_seconds", 0.05)
                reg.counter("hvt_serve_swaps_total")
        """)
        assert found == []
        found = findings_of(MetricRegistryDiscipline, """
            def collect(reg):
                reg.gauge("hvt_serve_kv_block_free", 3)  # typo'd: block
        """)
        assert len(found) == 1
        assert "hvt_serve_kv_block_free" in found[0].message

    def test_engine_tick_span_shape_clean_but_not_inside_cont(self):
        # The continuous-batching engine's tick emits a `decode` span
        # with a caller-timed `step` child (admitted/evicted attrs) —
        # legal exactly because both wrap the HOST-side dispatch of the
        # compiled cont program. The same emit_span moved INSIDE the
        # compiled body would clock the trace once and freeze.
        found = findings_of(MetricRegistryDiscipline, """
            import time
            from horovod_tpu import trace as trace_lib
            def tick(decoder, state):
                with trace_lib.span("decode", rows=2):
                    t0w, t0p = time.time(), time.perf_counter()
                    tokens, state = decoder.step(state)
                    trace_lib.emit_span(
                        "step", t0w, time.perf_counter() - t0p,
                        admitted=1, evicted=0, live=2,
                    )
                return tokens, state
        """)
        assert found == []
        found = findings_of(MetricRegistryDiscipline, """
            import jax
            from horovod_tpu import trace as trace_lib
            @jax.jit
            def cont(params, state):
                trace_lib.emit_span("step", 0.0, 0.1, admitted=1)
                return state
        """)
        assert len(found) == 1
        assert "emit_span" in found[0].message

    def test_noqa_suppresses(self, tmp_path):
        res = lint_tree(tmp_path, {
            "pkg/mod.py": """
                from horovod_tpu import obs
                def publish(v):
                    obs.gauge("hvt_bespoke", v)  # hvt: noqa[HVT009] why
            """,
        })
        assert [f for f in res.findings if f.rule == "HVT009"] == []
