"""Preemption-graceful checkpointing (SURVEY.md §5.3 stretch): SIGTERM in
the platform's grace window → save at the epoch boundary → clean stop →
resume. Covers the single-process path, the cross-process agreement (a
signal reaching ONE rank stops the whole fleet at the same epoch), and the
full SIGTERM → exit-143 → relaunch-resume loop."""

import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.launch import launcher
from horovod_tpu.training.callbacks import (
    Callback,
    ModelCheckpoint,
    PreemptionCheckpointCallback,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _SignalSelfAt(Callback):
    """Test trigger: raise SIGTERM in our own process during the given
    epoch — the honest delivery path (a real handler interrupt), not a
    direct flag poke."""

    def __init__(self, epoch: int, when: bool = True):
        self.epoch = epoch
        self.when = when
        self._current = -1

    def on_epoch_begin(self, epoch, logs=None):
        self._current = epoch

    def on_batch_end(self, batch, logs=None):
        if self.when and self._current == self.epoch:
            os.kill(os.getpid(), signal.SIGTERM)
            self.when = False  # once


def _toy_trainer():
    import flax.linen as nn
    import jax.numpy as jnp

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            return nn.Dense(4)(x)

    return hvt.Trainer(
        Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)),
        loss="sparse_categorical_crossentropy",
    )


def test_single_process_saves_and_stops(tmp_path):
    trainer = _toy_trainer()
    rng = np.random.RandomState(0)
    x = rng.rand(256, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(256,)).astype(np.int32)
    cb = PreemptionCheckpointCallback(str(tmp_path / "checkpoint-{epoch}.msgpack"))
    hist = trainer.fit(
        x=x, y=y, epochs=6, batch_size=32,
        callbacks=[_SignalSelfAt(epoch=1), cb], verbose=0,
    )
    assert cb.preempted
    assert trainer.stop_training
    # Stopped after the signalled epoch's boundary — epochs 3..6 never ran.
    assert len(hist) == 2
    assert (tmp_path / "checkpoint-2.msgpack").exists()
    # Handlers restored: SIGTERM's disposition is no longer our handler.
    assert signal.getsignal(signal.SIGTERM) is not cb._handler


def test_exit_code_raised_after_train_end(tmp_path):
    trainer = _toy_trainer()
    rng = np.random.RandomState(0)
    x = rng.rand(128, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(128,)).astype(np.int32)
    cb = PreemptionCheckpointCallback(
        str(tmp_path / "checkpoint-{epoch}.msgpack"), exit_code=143
    )
    with pytest.raises(SystemExit) as ex:
        trainer.fit(
            x=x, y=y, epochs=6, batch_size=32,
            callbacks=[_SignalSelfAt(epoch=0), cb], verbose=0,
        )
    assert ex.value.code == 143
    assert (tmp_path / "checkpoint-1.msgpack").exists()


def test_no_signal_is_a_noop(tmp_path):
    trainer = _toy_trainer()
    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(64,)).astype(np.int32)
    cb = PreemptionCheckpointCallback(str(tmp_path / "checkpoint-{epoch}.msgpack"))
    hist = trainer.fit(x=x, y=y, epochs=2, batch_size=32, callbacks=[cb], verbose=0)
    assert len(hist) == 2
    assert not cb.preempted
    assert not list(tmp_path.glob("checkpoint-*"))


@pytest.mark.slow
def test_two_process_agreement(tmp_path):
    """SIGTERM delivered to rank 1 ONLY: the allgather agreement must stop
    rank 0 too, at the same epoch, with the checkpoint written by the
    primary (which never saw the signal)."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, signal, sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import optax
        import horovod_tpu as hvt
        from horovod_tpu.training.callbacks import (
            Callback, PreemptionCheckpointCallback)

        hvt.init()
        assert hvt.process_count() == 2

        class SignalSelf(Callback):
            def __init__(self):
                self.current = -1
                self.armed = hvt.process_rank() == 1
            def on_epoch_begin(self, epoch, logs=None):
                self.current = epoch
            def on_batch_end(self, batch, logs=None):
                if self.armed and self.current == 1:
                    os.kill(os.getpid(), signal.SIGTERM)
                    self.armed = False

        import flax.linen as nn
        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                return nn.Dense(4)(x)

        trainer = hvt.Trainer(
            Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)),
            loss='sparse_categorical_crossentropy',
        )
        rng = np.random.RandomState(0)
        x = rng.rand(256, 8).astype(np.float32)
        y = rng.randint(0, 4, size=(256,)).astype(np.int32)
        cb = PreemptionCheckpointCallback(
            {str(tmp_path)!r} + '/checkpoint-{{epoch}}.msgpack')
        hist = trainer.fit(x=x, y=y, epochs=6, batch_size=16,
                           callbacks=[SignalSelf(), cb], verbose=0)
        assert cb.preempted, 'agreement failed on rank %d' % hvt.process_rank()
        assert len(hist) == 2, len(hist)
        with open({str(tmp_path)!r} + '/ok-%d' % hvt.process_rank(), 'w') as f:
            f.write('2')
    """))
    code = launcher.run_local(
        2, [sys.executable, str(script)],
        env={
            "HVT_PLATFORM": "cpu",
            "HVT_NUM_CPU_DEVICES": "1",
        },
        tag_output=False,
    )
    assert code == 0
    assert (tmp_path / "ok-0").exists() and (tmp_path / "ok-1").exists()
    assert (tmp_path / "checkpoint-2.msgpack").exists()


@pytest.mark.slow
def test_sigterm_resume_e2e(tmp_path):
    """The full preemption loop: run trains with per-epoch checkpoints +
    preemption callback (exit_code=143); a mid-run SIGTERM produces the
    graceful exit status and a final save; an identical relaunch resumes
    from that epoch and completes."""
    epochs, steps = 6, 4
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import optax
        import horovod_tpu as hvt
        from horovod_tpu import checkpoint
        from horovod_tpu.training.callbacks import (
            ModelCheckpoint, PreemptionCheckpointCallback)
        import flax.linen as nn
        import time

        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                return nn.Dense(4)(x)

        hvt.init()
        trainer = hvt.Trainer(
            Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)),
            loss='sparse_categorical_crossentropy',
        )
        rng = np.random.RandomState(0)
        x = rng.rand(512, 8).astype(np.float32)
        y = rng.randint(0, 4, size=(512,)).astype(np.int32)
        d = {str(tmp_path)!r}
        template = trainer.build(x[:4])
        restored, start = checkpoint.restore_latest_and_broadcast(d, template)
        if start:
            trainer.state = restored
            print('Resuming from checkpoint epoch %d' % start, flush=True)

        class Slow(ModelCheckpoint):
            # Slow the epochs so the parent's SIGTERM lands mid-run.
            def on_epoch_end(self, epoch, logs=None):
                super().on_epoch_end(epoch, logs)
                time.sleep(0.4)

        trainer.fit(
            x=x, y=y, epochs={epochs}, initial_epoch=start, batch_size=32,
            steps_per_epoch={steps},
            callbacks=[
                Slow(d + '/checkpoint-{{epoch}}.msgpack'),
                PreemptionCheckpointCallback(
                    d + '/checkpoint-{{epoch}}.msgpack', exit_code=143),
            ],
            verbose=1,
        )
        print('COMPLETED', flush=True)
    """))
    env = {**os.environ, "HVT_PLATFORM": "cpu", "HVT_NUM_CPU_DEVICES": "1"}
    proc = subprocess.Popen(
        [sys.executable, str(script)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    deadline = time.time() + 300
    while time.time() < deadline:
        if (tmp_path / "checkpoint-2.msgpack").exists():
            break
        if proc.poll() is not None:
            raise AssertionError("run 1 ended early:\n" + proc.stdout.read())
        time.sleep(0.05)
    else:
        proc.kill()
        raise AssertionError("checkpoint-2 never appeared")
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    if proc.returncode == 0:
        pytest.skip("run 1 completed before SIGTERM landed")
    assert proc.returncode == 143, (proc.returncode, out)
    assert "PreemptionCheckpoint: signal received" in out
    assert "COMPLETED" not in out
    saved = max(
        int(p.name.split("-")[1].split(".")[0])
        for p in tmp_path.glob("checkpoint-*.msgpack")
    )
    assert saved < epochs

    res = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert f"Resuming from checkpoint epoch {saved}" in res.stdout
    assert "COMPLETED" in res.stdout
    assert (tmp_path / f"checkpoint-{epochs}.msgpack").exists()


def test_handlers_restored_when_fit_raises(tmp_path):
    """A training crash must still restore signal dispositions (teardown
    hooks run on the error path): a stale flag-only handler would swallow
    the NEXT real SIGTERM."""

    class Boom(Callback):
        def on_batch_end(self, batch, logs=None):
            raise RuntimeError("boom")

    before = signal.getsignal(signal.SIGTERM)
    trainer = _toy_trainer()
    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(64,)).astype(np.int32)
    cb = PreemptionCheckpointCallback(str(tmp_path / "checkpoint-{epoch}.msgpack"))
    with pytest.raises(RuntimeError, match="boom"):
        trainer.fit(x=x, y=y, epochs=2, batch_size=32,
                    callbacks=[Boom(), cb], verbose=0)
    assert signal.getsignal(signal.SIGTERM) == before


def test_handlers_restored_when_train_begin_raises(tmp_path):
    """A LATER callback's on_train_begin raising must still tear down the
    already-installed signal handler."""

    class BadBegin(Callback):
        def on_train_begin(self, logs=None):
            raise RuntimeError("begin boom")

    before = signal.getsignal(signal.SIGTERM)
    trainer = _toy_trainer()
    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(64,)).astype(np.int32)
    cb = PreemptionCheckpointCallback(str(tmp_path / "checkpoint-{epoch}.msgpack"))
    with pytest.raises(RuntimeError, match="begin boom"):
        trainer.fit(x=x, y=y, epochs=1, batch_size=32,
                    callbacks=[cb, BadBegin()], verbose=0)
    assert signal.getsignal(signal.SIGTERM) == before


def test_exit_code_does_not_skip_later_train_end(tmp_path):
    """SystemExit from the preemption callback must not skip a LATER
    callback's on_train_end (async-save joins, writer flushes)."""
    ran = []

    class After(Callback):
        def on_train_end(self, logs=None):
            ran.append(True)

    trainer = _toy_trainer()
    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(64,)).astype(np.int32)
    cb = PreemptionCheckpointCallback(
        str(tmp_path / "checkpoint-{epoch}.msgpack"), exit_code=143
    )
    with pytest.raises(SystemExit):
        trainer.fit(x=x, y=y, epochs=4, batch_size=32,
                    callbacks=[_SignalSelfAt(epoch=0), cb, After()], verbose=0)
    assert ran == [True]


@pytest.mark.slow
def test_ema_restore_broadcasts_to_fileless_ranks(tmp_path):
    """Durable-EMA restore on a pod where checkpoint_dir is host-local:
    only rank 0 has ema.msgpack; rank 1 must adopt rank 0's shadow via the
    broadcast, not silently fresh-init a divergent one."""
    # Parent prepares rank 0's file: a recognizable shadow (all 0.5).
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from horovod_tpu import checkpoint

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            return nn.Dense(4)(x)

    params = Tiny().init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.float32)
    )["params"]
    shadow = jax.tree.map(lambda a: jnp.full_like(a, 0.5), params)
    d0 = tmp_path / "rank0"
    d0.mkdir()
    (tmp_path / "rank1").mkdir()
    checkpoint.save(str(d0 / "ema.msgpack"), {"shadow": shadow, "count": 42})

    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import optax
        import jax
        import flax.linen as nn
        import horovod_tpu as hvt
        from horovod_tpu.training.callbacks import ExponentialMovingAverage

        hvt.init()
        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                return nn.Dense(4)(x)

        trainer = hvt.Trainer(
            Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)),
            loss='sparse_categorical_crossentropy',
        )
        trainer.build(np.zeros((2, 8), np.float32))
        d = {str(tmp_path)!r} + '/rank%d' % hvt.process_rank()
        ema = ExponentialMovingAverage(decay=0.9, checkpoint_dir=d)
        ema.set_trainer(trainer)
        ema.on_train_begin()
        flat = np.concatenate(
            [np.asarray(l).ravel() for l in jax.tree.leaves(ema.ema_params)]
        )
        assert ema._count == 42, ema._count
        assert np.allclose(flat, 0.5), flat[:4]
        with open({str(tmp_path)!r} + '/ema-ok-%d' % hvt.process_rank(), 'w') as f:
            f.write('ok')
    """))
    code = launcher.run_local(
        2, [sys.executable, str(script)],
        env={"HVT_PLATFORM": "cpu", "HVT_NUM_CPU_DEVICES": "1"},
        tag_output=False,
    )
    assert code == 0
    assert (tmp_path / "ema-ok-0").exists() and (tmp_path / "ema-ok-1").exists()
