"""Launcher + CI-gate tests (SURVEY.md §3.4 launch path, §4 test modes)."""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from horovod_tpu.launch import ci_gate, launcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_metrics(path, values, name="loss"):
    with open(path, "w") as f:
        for i, v in enumerate(values):
            f.write(json.dumps({"name": name, "value": v, "step": i}) + "\n")


class TestCIGate:
    def test_parse_target_reference_grammar(self):
        # The exact string from config.yaml:10.
        assert ci_gate.parse_target("0.0..0.3") == (0.0, 0.3)

    def test_aggregates(self):
        vals = [0.4, 0.2, 0.05]
        assert ci_gate.aggregate(vals, "mean") == pytest.approx(0.21666, rel=1e-3)
        assert ci_gate.aggregate(vals, "last") == 0.05
        assert ci_gate.aggregate(vals, "min") == 0.05
        assert ci_gate.aggregate(vals, "max") == 0.4

    def test_check_pass_and_fail(self, tmp_path):
        path = tmp_path / "metrics.jsonl"
        _write_metrics(path, [0.25, 0.15, 0.08])
        ok, value = ci_gate.check_metrics(str(path), "loss", (0.0, 0.3))
        assert ok and value == pytest.approx(0.16)
        ok, _ = ci_gate.check_metrics(str(path), "loss", (0.0, 0.1))
        assert not ok

    def test_missing_metric_fails_not_crashes(self, tmp_path):
        path = tmp_path / "metrics.jsonl"
        _write_metrics(path, [0.1], name="accuracy")
        ok, value = ci_gate.check_metrics(str(path), "loss", (0.0, 0.3))
        assert not ok

    def test_gate_cli(self, tmp_path):
        path = tmp_path / "metrics.jsonl"
        _write_metrics(path, [0.2, 0.1])
        assert launcher.main(["gate", "--metrics", str(path),
                              "--check", "loss=0.0..0.3"]) == 0
        assert launcher.main(["gate", "--metrics", str(path),
                              "--check", "loss=0.0..0.01"]) == 1


class TestRunLocal:
    def test_single_process_no_coordinator(self, tmp_path):
        """nprocs=1 is the bare no-launcher mode: no HVT coordinator env."""
        out = tmp_path / "env.json"
        code = launcher.run_local(
            1,
            [sys.executable, "-c", textwrap.dedent(f"""
                import json, os
                json.dump({{k: v for k, v in os.environ.items()
                           if k.startswith('HVT_')}}, open({str(out)!r}, 'w'))
            """)],
            tag_output=False,
        )
        assert code == 0
        env = json.load(open(out))
        assert "HVT_COORDINATOR_ADDRESS" not in env

    def test_multi_process_env_assignment(self, tmp_path):
        code = launcher.run_local(
            3,
            [sys.executable, "-c", textwrap.dedent(f"""
                import os
                rank = os.environ['HVT_PROCESS_ID']
                assert os.environ['HVT_NUM_PROCESSES'] == '3'
                assert os.environ['HVT_COORDINATOR_ADDRESS'].startswith('127.0.0.1:')
                open(os.path.join({str(tmp_path)!r}, f'rank-{{rank}}'), 'w').close()
            """)],
            tag_output=False,
        )
        assert code == 0
        assert sorted(p.name for p in tmp_path.glob("rank-*")) == [
            "rank-0", "rank-1", "rank-2"]

    def test_failure_propagates(self):
        code = launcher.run_local(
            2, [sys.executable, "-c", "import os,sys; sys.exit(int(os.environ['HVT_PROCESS_ID']) * 7)"],
            tag_output=False,
        )
        assert code == 7  # fail-stop: any rank's nonzero code surfaces


class TestJob:
    def test_job_runs_and_gates(self, tmp_path):
        metrics = tmp_path / "metrics.jsonl"
        spec = tmp_path / "job.yaml"
        # The command itself writes the metric stream, standing in for a
        # training run; checks then replicate config.yaml:8-11.
        writer = (
            "import json;"
            f"f=open({str(metrics)!r},'w');"
            "[f.write(json.dumps({'name':'loss','value':v})+'\\n') for v in (0.25,0.1)]"
        )
        spec.write_text(textwrap.dedent(f"""
            name: test-job
            job:
              command: ["{sys.executable}", "-c", {json.dumps(writer)}]
              nprocs: 1
            metrics: {metrics}
            checks:
              loss:
                target: "0.0..0.3"
                aggregate: mean
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 0

        spec2 = tmp_path / "job2.yaml"
        spec2.write_text(spec.read_text().replace("0.0..0.3", "0.0..0.05"))
        assert run_job(str(spec2)) == 1

    def test_job_status_port_requires_supervision(self, tmp_path, capsys):
        """status_port on an UNsupervised spec fails loudly (the status
        server is the supervisor's), matching the CLI's --status-port
        error — silently ignoring it would leave the operator's health
        probes failing against a job that looks correctly configured."""
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""
            name: test-job
            job:
              command: ["{sys.executable}", "-c", "pass"]
              nprocs: 1
              status_port: 9967
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 1
        assert "status_port" in capsys.readouterr().out

    def test_job_fresh_wipes_model_dir(self, tmp_path):
        """fresh: true — a gated run must train from scratch: a stale
        checkpoint in the job-owned PS_MODEL_PATH would make the entry
        script resume (and push nothing to the gate)."""
        model_dir = tmp_path / "models"
        model_dir.mkdir()
        (model_dir / "checkpoint-6.msgpack").write_bytes(b"stale")
        metrics = tmp_path / "metrics.jsonl"
        writer = (
            "import json;"
            f"open({str(metrics)!r},'w').write("
            "json.dumps({'name':'loss','value':0.1}) + '\\n')"
        )
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""
            name: fresh-job
            job:
              fresh: true
              command: ["{sys.executable}", "-c", {json.dumps(writer)}]
              nprocs: 1
              env:
                PS_MODEL_PATH: {model_dir}
            metrics: {metrics}
            checks:
              loss:
                target: "0.0..0.3"
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 0
        assert not (model_dir / "checkpoint-6.msgpack").exists()

    def test_job_fresh_refuses_suspicious_dir(self, tmp_path):
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""
            name: fresh-bad
            job:
              fresh: true
              command: ["true"]
              env:
                PS_MODEL_PATH: /
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 1
        assert (tmp_path / "job.yaml").exists()  # nothing was wiped

    def test_job_restart_block_supervises_and_logs(self, tmp_path):
        """The YAML `restart:` block routes through the supervisor: a
        one-shot failure is restarted (journaled), the rerun passes the
        gate, and a stale restart journal is reset first."""
        model_dir = tmp_path / "models"
        model_dir.mkdir()
        log = model_dir / "restarts.jsonl"
        log.write_text('{"name": "restarts", "value": 9}\n')  # stale
        metrics = tmp_path / "metrics.jsonl"
        stamp = tmp_path / "fired"
        body = (
            "import json, os, sys;"
            f"s = {str(stamp)!r};"
            "fired = os.path.exists(s);"
            "open(s, 'w').close();"
            "(sys.exit(3) if not fired else None);"
            f"open({str(metrics)!r}, 'w').write("
            "json.dumps({'name': 'loss', 'value': 0.1}) + '\\n')"
        )
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""
            name: supervised-job
            job:
              command: ["{sys.executable}", "-c", {json.dumps(body)}]
              nprocs: 1
              restart:
                max_restarts: 2
                backoff: 0.0
              env:
                PS_MODEL_PATH: {model_dir}
            metrics: {metrics}
            checks:
              loss:
                target: "0.0..0.3"
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 0
        records = [json.loads(line)
                   for line in log.read_text().splitlines()]
        assert len(records) == 1  # stale journal was reset
        assert records[0]["name"] == "restarts"
        assert records[0]["exit_code"] == 3

    def test_job_empty_restart_block_supervises_with_defaults(self, tmp_path):
        """`restart:` with every knob commented out (YAML None) still opts
        in — matching the CLI where any supervision flag supervises."""
        model_dir = tmp_path / "models"
        model_dir.mkdir()
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""
            name: default-supervised
            job:
              command: ["{sys.executable}", "-c", "pass"]
              nprocs: 1
              restart:
              env:
                PS_MODEL_PATH: {model_dir}
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 0
        # Supervision ran: the journal was touched at the default location.
        assert (model_dir / "restarts.jsonl").exists()

    def test_job_non_mapping_restart_rejected(self, tmp_path):
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""
            name: bad-restart
            job:
              command: ["{sys.executable}", "-c", "pass"]
              nprocs: 1
              restart: true
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 1

    def test_job_restart_block_exhausts_budget(self, tmp_path):
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""
            name: doomed-job
            job:
              command: ["{sys.executable}", "-c", "raise SystemExit(9)"]
              nprocs: 1
              restart:
                max_restarts: 1
                backoff: 0.0
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 9

    def test_job_resets_stale_metrics(self, tmp_path):
        """A previous run's appended metrics must not feed this run's gate."""
        metrics = tmp_path / "metrics.jsonl"
        _write_metrics(metrics, [0.01, 0.01])  # stale, would pass
        spec = tmp_path / "job.yaml"
        # This run's command writes nothing → gate must FAIL.
        spec.write_text(textwrap.dedent(f"""
            name: stale
            job:
              command: ["{sys.executable}", "-c", "pass"]
              nprocs: 1
            metrics: {metrics}
            checks:
              loss:
                target: "0.0..0.3"
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 1


class TestWaitFailStop:
    """Grace-window edge cases of the fail-stop wait (SURVEY.md §5.3):
    survivors of a rank failure get grace_seconds to finish on their own
    before termination, and the FIRST failure's code is the job's code."""

    def _proc(self, code, delay=0.0):
        return subprocess.Popen(
            [sys.executable, "-c",
             f"import time, sys; time.sleep({delay}); sys.exit({code})"]
        )

    def test_survivor_finishing_within_grace_is_untouched(self):
        failed = self._proc(3)
        survivor = self._proc(0, delay=0.7)
        code = launcher._wait_fail_stop([failed, survivor], grace_seconds=30.0)
        assert code == 3
        # The survivor completed on its own terms — not terminated.
        assert survivor.returncode == 0

    def test_survivor_terminated_after_grace(self):
        failed = self._proc(2)
        survivor = self._proc(0, delay=60)
        t0 = time.monotonic()
        code = launcher._wait_fail_stop([failed, survivor], grace_seconds=0.4)
        assert code == 2
        assert time.monotonic() - t0 < 30
        # Terminated by the launcher, not a clean exit: signal death.
        assert survivor.returncode is not None and survivor.returncode < 0

    def test_first_failure_code_wins_over_later_ones(self):
        first = self._proc(5)
        second = self._proc(9, delay=0.7)
        code = launcher._wait_fail_stop([first, second], grace_seconds=30.0)
        assert code == 5  # not 9: the initial fault is the job's verdict
        assert second.returncode == 9  # it did exit on its own within grace

    def test_all_zero_is_zero(self):
        code = launcher._wait_fail_stop(
            [self._proc(0), self._proc(0, delay=0.2)], grace_seconds=5.0)
        assert code == 0


class TestSupervisedCLI:
    def test_run_with_max_restarts_supervises(self, tmp_path):
        """`hvt-launch run --max-restarts` routes through the supervisor:
        a deterministic crash loop exits with the original code after the
        budget, and the restart journal lands where --restart-log says."""
        log = tmp_path / "restarts.jsonl"
        code = launcher.main([
            "run", "--nprocs", "1", "--max-restarts", "1", "--backoff", "0",
            "--restart-log", str(log),
            "--", sys.executable, "-c", "raise SystemExit(5)",
        ])
        assert code == 5
        records = [json.loads(line) for line in log.read_text().splitlines()]
        assert [r["name"] for r in records] == [
            "restarts", "supervisor_gave_up"]

    def test_restart_log_alone_enables_supervision(self, tmp_path):
        """Any supervision flag supervises: --restart-log by itself must
        journal (a silently-unsupervised run would fail its count gate)."""
        log = tmp_path / "restarts.jsonl"
        code = launcher.main([
            "run", "--nprocs", "1", "--restart-log", str(log),
            "--", sys.executable, "-c", "pass",
        ])
        assert code == 0
        assert log.exists()  # journal touched even with zero restarts

    def test_gate_count_aggregate_cli(self, tmp_path):
        """The restart journal is gateable with the count aggregate."""
        log = tmp_path / "restarts.jsonl"
        _write_metrics(log, [1.0], name="restarts")
        assert launcher.main(["gate", "--metrics", str(log),
                              "--check", "restarts=1..1",
                              "--aggregate", "count"]) == 0
        assert launcher.main(["gate", "--metrics", str(log),
                              "--check", "restarts=0..0",
                              "--aggregate", "count"]) == 1


@pytest.fixture
def fake_ssh(tmp_path, monkeypatch):
    """PATH-shimmed ssh/scp that exec locally — the multi-host launcher's
    deployment path (the mpirun --hostfile replacement) testable on one
    machine. Hosts containing 'bad' refuse the connection."""
    bin_dir = tmp_path / "fakebin"
    bin_dir.mkdir()
    ssh = bin_dir / "ssh"
    ssh.write_text(
        "#!/bin/bash\n"
        'while [[ "$1" == -* ]]; do\n'
        '  if [[ "$1" == "-o" ]]; then shift 2; else shift; fi\n'
        "done\n"
        'host="$1"; shift\n'
        'if [[ "$host" == *bad* ]]; then\n'
        '  echo "ssh: connect to host $host: Connection refused" >&2\n'
        "  exit 255\n"
        "fi\n"
        'exec sh -c "$*"\n'
    )
    ssh.chmod(0o755)
    scp = bin_dir / "scp"
    scp.write_text(
        "#!/bin/bash\n"
        'while [[ "$1" == -* ]]; do\n'
        '  if [[ "$1" == "-o" ]]; then shift 2; else shift; fi\n'
        "done\n"
        'src="$1"; dst="$2"\n'
        'if [[ "$src" == *bad*:* ]]; then exit 1; fi\n'
        'exec cp "${src#*:}" "${dst#*:}"\n'
    )
    scp.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    return bin_dir


class TestSshLauncher:
    def test_run_hosts_assigns_ranks_and_coordinator(self, tmp_path, fake_ssh):
        """One process per host: HVT_* env plays mpirun's slot-mapping role;
        host 0 is the coordinator every rank dials."""
        out = tmp_path / "envdump"
        script = (
            f"import json, os; json.dump({{k: v for k, v in os.environ.items()"
            f" if k.startswith('HVT_')}},"
            f" open({str(out)!r} + '.' + os.environ['HVT_PROCESS_ID'], 'w'))"
        )
        code = launcher.run_hosts(
            ["alpha", "user@beta"],
            [sys.executable, "-c", script],
            env={"EXTRA": "propagated"},
            coordinator_port=7700,
        )
        assert code == 0
        envs = [json.load(open(f"{out}.{r}")) for r in range(2)]
        for r, env in enumerate(envs):
            assert env["HVT_PROCESS_ID"] == str(r)
            assert env["HVT_NUM_PROCESSES"] == "2"
            # ssh-style user@host entries: the dialed address is the bare host.
            assert env["HVT_COORDINATOR_ADDRESS"] == "alpha:7700"

    def test_run_hosts_env_propagation_and_workdir(self, tmp_path, fake_ssh):
        script = (
            "import os, pathlib; pathlib.Path('cwd.txt').write_text("
            "os.getcwd() + '\\n' + os.environ['MY_FLAG'])"
        )
        code = launcher.run_hosts(
            ["solo"],
            [sys.executable, "-c", script],
            env={"MY_FLAG": "on remote"},  # space → quoting must hold
            workdir=str(tmp_path),
        )
        assert code == 0
        cwd, flag = (tmp_path / "cwd.txt").read_text().splitlines()
        assert cwd == str(tmp_path)
        assert flag == "on remote"

    def test_run_hosts_failure_propagates(self, fake_ssh):
        code = launcher.run_hosts(
            ["goodhost", "badhost"], ["true"],
        )
        assert code == 255  # fail-stop: the refused connection surfaces

    def test_job_with_hosts_fetches_remote_metrics(self, tmp_path, fake_ssh):
        """The full multi-host job path: reset stale metrics over ssh, run,
        scp the stream back, gate on it."""
        metrics = tmp_path / "metrics.jsonl"
        _write_metrics(metrics, [0.9, 0.9])  # stale — must be reset
        writer = (
            "import json;"
            f"open({str(metrics)!r}, 'w').write("
            "json.dumps({'name': 'loss', 'value': 0.1}) + '\\n')"
        )
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""
            name: pod-job
            job:
              command: ["{sys.executable}", "-c", {json.dumps(writer)}]
              hosts: [podhost]
            metrics: {metrics}
            checks:
              loss:
                target: "0.0..0.3"
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 0

    def test_job_refuses_gate_when_reset_fails(self, tmp_path, fake_ssh):
        """If the remote metrics stream can't be reset, gating could pass on
        stale values — the job must refuse instead."""
        metrics = tmp_path / "metrics.jsonl"
        _write_metrics(metrics, [0.01])  # stale pass-looking values
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""
            name: pod-job-bad
            job:
              command: ["true"]
              hosts: [badhost]
            metrics: {metrics}
            checks:
              loss:
                target: "0.0..0.3"
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) != 0


@pytest.mark.slow
class TestDistributedLaunch:
    def test_two_process_cpu_collectives(self, tmp_path):
        """Full multi-process path: 2 coordinated CPU processes, broadcast +
        allreduce agree — the 'Docker-local mpirun' test mode (README.md:53-58)."""
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {REPO!r})
            import horovod_tpu as hvt
            import numpy as np
            w = hvt.init()
            assert hvt.process_count() == 2, hvt.process_count()
            from horovod_tpu.parallel import collectives
            val = np.float32(hvt.process_rank() + 1.0)
            mean = collectives.allreduce(val)
            assert abs(float(mean) - 1.5) < 1e-6, mean
            tree = collectives.broadcast_pytree(
                {{'a': np.full((3,), hvt.process_rank(), np.float32)}})
            assert float(tree['a'][0]) == 0.0
            # Object collectives (hvd.broadcast_object / allgather_object):
            # arbitrary picklable payloads, variable size per process.
            obj = collectives.broadcast_object(
                {{'vocab': ['a', 'b'], 'rank': hvt.process_rank()}})
            assert obj == {{'vocab': ['a', 'b'], 'rank': 0}}, obj
            objs = collectives.allgather_object(
                'r' * (hvt.process_rank() + 1))
            assert objs == ['r', 'rr'], objs
            # Every round's KV keys are garbage-collected once all readers
            # fetched (bounded control-plane footprint for a long-lived
            # world). Rank 0 deletes right after the round's barrier, so
            # poll briefly; the sentinel proves dir_get itself works.
            import time
            client = collectives._kv_client()
            assert client is not None
            if hvt.process_rank() == 0:
                client.key_value_set('hvt-sentinel/x', '1')
            client.wait_at_barrier('sentinel-ready', 30000)
            assert client.key_value_dir_get('hvt-sentinel/')
            deadline = time.time() + 10
            leftover = client.key_value_dir_get_bytes('hvt/')
            while leftover and time.time() < deadline:
                time.sleep(0.1)
                leftover = client.key_value_dir_get_bytes('hvt/')
            assert not leftover, [k for k, _ in leftover]
            open({str(tmp_path)!r} + f'/ok-{{hvt.process_rank()}}', 'w').close()
        """))
        code = launcher.run_local(
            2,
            [sys.executable, str(script)],
            env={"HVT_PLATFORM": "cpu", "HVT_NUM_CPU_DEVICES": "1"},
            tag_output=False,
        )
        assert code == 0
        assert (tmp_path / "ok-0").exists() and (tmp_path / "ok-1").exists()


@pytest.mark.slow
class TestJobStatusPortE2E:
    def test_mnist_ci_2proc_serves_status_and_journal(self, tmp_path):
        """The real mnist-ci-2proc.yaml spec with `status_port:` set (the
        ROADMAP item PR 5 left open): while the supervised 2-proc run is
        live, the supervisor's own HTTP endpoint answers GET /status with
        the fleet summary and GET /journal with the restart journal —
        operator probes need no serving bundle. Budget shrunk to CPU-test
        size; the convergence gate is mnist-ci-2proc's own job, not this
        test's."""
        import socket
        import threading
        import urllib.request

        import yaml

        with open(os.path.join(
            REPO, "horovod_tpu", "launch", "jobs", "mnist-ci-2proc.yaml"
        )) as f:
            spec = yaml.safe_load(f)

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()

        model_dir = str(tmp_path / "models")
        spec["job"]["status_port"] = port
        # Absolute entry-script path: run_job launches from the test's cwd.
        spec["job"]["command"] = (
            f"{sys.executable} {os.path.join(REPO, 'examples', 'tf2_style_mnist.py')}"
        )
        env = spec["job"]["env"]
        env["PS_MODEL_PATH"] = model_dir
        env["DRIVE_STEPS"] = "8"
        env["DRIVE_EPOCHS"] = "2"
        spec["metrics"] = os.path.join(model_dir, "metrics.jsonl")
        # 8 steps x 2 epochs is far below the convergence budget — keep the
        # gate structurally exercised but trivially satisfiable.
        spec["checks"]["loss"]["target"] = "0.0..100.0"
        mod = tmp_path / "job.yaml"
        mod.write_text(yaml.safe_dump(spec))

        from horovod_tpu.launch.job import run_job

        result: dict = {}
        t = threading.Thread(
            target=lambda: result.setdefault("code", run_job(str(mod)))
        )
        t.start()

        def get(route):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{route}", timeout=5
            ) as r:
                return json.loads(r.read())

        # The server starts with the supervisor, before the ranks finish
        # compiling — poll until it answers, then hold the assertions
        # while the run is still live.
        status = None
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and t.is_alive():
            try:
                status = get("/status")
                break
            except OSError:
                time.sleep(0.5)
        assert status is not None, "status endpoint never came up"
        assert "fleet" in status and "coordinator" in status
        assert status["coordinator"] is None  # restart-supervised, not elastic
        fleet = status["fleet"]
        assert fleet["restarts"] == 0 and fleet["shrinks"] == 0
        assert fleet["journal"].startswith(model_dir)
        journal = get("/journal")
        assert journal["records"] == []  # clean run: journal touched, empty
        assert get("/healthz")["status"] == "ok"

        t.join(timeout=600)
        assert not t.is_alive(), "job did not finish"
        assert result["code"] == 0
