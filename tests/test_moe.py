"""Mixture-of-Experts layer + expert parallelism over the `expert` mesh axis.

Covers: routing correctness (tokens reach the expert the router picked),
capacity overflow drops (zero contribution, not garbage), the load-balancing
aux loss reaching the training objective through the Trainer's 'losses'
channel, EP sharding of expert weights and optimizer mirrors, and a
MoE transformer actually training on an expert-parallel mesh.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.data import datasets
from horovod_tpu.models.moe import MoEMlp
from horovod_tpu.models.transformer import (
    ShardingConfig,
    TransformerLM,
    param_specs,
)
from horovod_tpu.parallel import mesh as mesh_lib

VOCAB = 32


def _init(module, x, train=False):
    return module.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)}, x, train=train)


@pytest.mark.slow
class TestRouting:
    def test_tokens_reach_their_expert(self):
        """Force the router with a hand-built kernel: token feature i routes
        to expert i; give each expert a constant-output transform and check
        every token carries its own expert's constant."""
        d, e = 4, 4
        layer = MoEMlp(d, n_experts=e, k=1, capacity_factor=4.0, mlp_ratio=1)
        x = jnp.eye(e).reshape(1, e, d)  # token i = one-hot(i) → expert i
        variables = _init(layer, x)
        params = jax.device_get(variables["params"])
        # Router kernel = large identity → softmax puts ~all mass on expert i.
        params["router"]["kernel"] = np.eye(d, e, dtype=np.float32) * 50.0
        # Expert j: w_up zeros→gelu(0)=0 trick won't distinguish; instead use
        # w_up so hidden = tokens @ w_up = row sums, and w_down scaled by
        # (j+1): output magnitude identifies the expert.
        params["moe_up"] = np.ones((e, d, d), np.float32)
        params["moe_down"] = np.stack(
            [np.eye(d, dtype=np.float32) * (j + 1) for j in range(e)]
        )
        out = layer.apply({"params": params}, x)
        # Token i (one-hot) → hidden = gelu(1,1,1,1 row? token·w_up = ones) →
        # out = gelu(1)·(i+1) per dim; ratio across tokens identifies expert.
        base = float(out[0, 0, 0])
        for i in range(e):
            np.testing.assert_allclose(
                np.asarray(out[0, i]), base * (i + 1), rtol=1e-5
            )

    def test_capacity_overflow_drops_to_zero(self):
        """All tokens prefer expert 0 with capacity 1: exactly one token gets
        through, the rest contribute zero (safe with a residual add)."""
        d, e, n_tok = 4, 2, 8
        layer = MoEMlp(d, n_experts=e, k=1, capacity_factor=1e-9, mlp_ratio=1)
        x = jnp.ones((1, n_tok, d))
        variables = _init(layer, x)
        params = jax.device_get(variables["params"])
        params["router"]["kernel"] = np.zeros((d, e), np.float32)
        params["router"]["kernel"][:, 0] = 50.0  # everyone → expert 0
        params["moe_up"] = np.ones((e, d, d), np.float32)
        params["moe_down"] = np.ones((e, d, d), np.float32)
        out = np.asarray(layer.apply({"params": params}, x))
        nonzero = np.abs(out).sum(-1) > 1e-6  # [1, n_tok]
        assert nonzero.sum() == 1  # capacity 1 → exactly one survivor

    def test_grouped_dispatch_matches_single_group(self):
        """Dispatch groups are a cost optimization, not a semantics change:
        with ample capacity, 4 groups and 1 group compute the same output."""
        d, e = 8, 4
        x = jnp.asarray(np.random.RandomState(7).rand(2, 8, d), jnp.float32)
        one = MoEMlp(d, n_experts=e, k=2, capacity_factor=8.0, group_size=16)
        four = MoEMlp(d, n_experts=e, k=2, capacity_factor=8.0, group_size=4)
        variables = _init(one, x)
        np.testing.assert_allclose(
            np.asarray(one.apply(variables, x)),
            np.asarray(four.apply(variables, x)),
            rtol=1e-5, atol=1e-6,
        )

    def test_switch_k1_router_gets_task_gradient(self):
        """k=1 must use the RAW top probability as the gate (renormalizing
        would make it constant 1.0 and freeze the router)."""
        d, e = 8, 4
        layer = MoEMlp(d, n_experts=e, k=1, capacity_factor=4.0)
        x = jnp.asarray(np.random.RandomState(1).rand(1, 8, d), jnp.float32)
        variables = _init(layer, x)

        def task_loss(params):
            out = layer.apply({"params": params}, x)
            return (out ** 2).sum()

        g = jax.grad(task_loss)(variables["params"])
        router_grad = float(np.abs(np.asarray(g["router"]["kernel"])).sum())
        assert router_grad > 1e-6  # not cut off from the task loss

    def test_indivisible_experts_rejected(self):
        """Misconfigured EP (experts not divisible by the expert axis) must
        fail loudly — silent replication would quietly discard the memory
        scaling EP exists for. Both the layer and param_specs guard it."""
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, expert=4))
        model = TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2, dropout=0.0,
            moe_every=2, n_experts=6,  # 6 % 4 != 0
            sharding=ShardingConfig(mesh=mesh, attn="dense"),
        )
        toks = jnp.zeros((8, 16), jnp.int32)
        with pytest.raises(ValueError, match="divisible"):
            model.init(
                {"params": jax.random.PRNGKey(0),
                 "dropout": jax.random.PRNGKey(1)},
                toks,
            )
        # param_specs guards independently (callers can hand-build params).
        plain = TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2, dropout=0.0,
            moe_every=2, n_experts=6,
        )
        params = plain.init(
            {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
            toks,
        )["params"]
        with pytest.raises(ValueError, match="divisible"):
            param_specs(params, mesh)

    def test_top2_gates_renormalized(self):
        d, e = 8, 4
        layer = MoEMlp(d, n_experts=e, k=2, capacity_factor=4.0)
        x = jnp.asarray(np.random.RandomState(0).rand(2, 6, d), jnp.float32)
        variables = _init(layer, x)
        out = layer.apply(variables, x)
        assert out.shape == x.shape
        assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
class TestAuxLoss:
    def test_sown_during_train_only(self):
        d = 8
        layer = MoEMlp(d, n_experts=4, k=1)
        x = jnp.ones((1, 4, d))
        variables = _init(layer, x)
        _, state = layer.apply(
            variables, x, train=True, mutable=["losses"],
            rngs={"dropout": jax.random.PRNGKey(0)},
        )
        assert "moe_load_balance" in state["losses"]
        aux = jax.tree.leaves(state["losses"])[0]
        assert float(np.asarray(aux)) >= 0.0
        _, state_eval = layer.apply(variables, x, train=False, mutable=["losses"])
        assert not state_eval.get("losses", {})

    def test_trainer_adds_aux_to_objective(self):
        """The same model with aux_loss_coef 0 vs large must report different
        training loss — proof the sown value reaches the objective."""

        def run(coef):
            model = TransformerLM(
                vocab_size=VOCAB, d_model=32, n_heads=2, n_layers=2,
                dropout=0.0, moe_every=2, n_experts=4, moe_aux_coef=coef,
            )
            trainer = hvt.Trainer(
                model, hvt.DistributedOptimizer(optax.sgd(0.0))
            )
            x, y = datasets.copy_task(64, 16, vocab_size=VOCAB, seed=0)
            hist = trainer.fit(
                x=x, y=y, batch_size=2, epochs=1, steps_per_epoch=2,
                shuffle_buffer=1, verbose=0,
            )
            return hist[0]["loss"]

        assert run(100.0) > run(0.0) + 1.0


@pytest.mark.slow
class TestExpertParallel:
    def _mesh(self):
        return mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, expert=4))

    def _trainer(self, mesh, **model_kw):
        model = TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2, dropout=0.0,
            moe_every=2, n_experts=4,
            sharding=ShardingConfig(mesh=mesh, attn="dense"),
            **model_kw,
        )
        return hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            mesh=mesh,
            param_specs=param_specs,
            batch_specs=(P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq")),
        )

    def test_expert_weights_sharded_on_expert_axis(self):
        trainer = self._trainer(self._mesh())
        x, _ = datasets.copy_task(8, 16, vocab_size=VOCAB)
        state = trainer.build(x)

        def expert_sharded(tree):
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
            return [
                path for path, leaf in flat
                if hasattr(leaf, "sharding")
                and any(
                    "expert" in (ax if isinstance(ax, tuple) else (ax,))
                    for ax in getattr(leaf.sharding, "spec", P())
                    if ax is not None
                )
            ]

        # moe_up + moe_down in the one MoE block.
        assert len(expert_sharded(state.params)) == 2
        # Optimizer mirrors (mu, nu) inherit the layout.
        assert len(expert_sharded(state.opt_state)) == 4

    def test_moe_transformer_trains_on_ep_mesh(self):
        trainer = self._trainer(self._mesh())
        x, y = datasets.copy_task(256, 16, vocab_size=VOCAB, seed=1)
        history = trainer.fit(
            x=x, y=y, batch_size=8, epochs=2, steps_per_epoch=8, verbose=0
        )
        assert np.isfinite(history[-1]["loss"])
        assert history[-1]["loss"] < history[0]["loss"]

    def test_ep_tp_composition(self):
        """EP × TP on one mesh: expert weights shard dim 0 over `expert` AND
        their hidden dim over `model` (param_specs moe rules); the function
        must still match the unsharded layer and train end-to-end."""
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, expert=2, model=2)
        )
        d, e = 16, 4
        plain = MoEMlp(d, n_experts=e, k=2, capacity_factor=2.0)
        sharded = MoEMlp(
            d, n_experts=e, k=2, capacity_factor=2.0,
            sharding=ShardingConfig(mesh=mesh),
        )
        x = jnp.asarray(np.random.RandomState(7).rand(2, 8, d), jnp.float32)
        variables = _init(plain, x)
        out_plain = plain.apply(variables, x)
        out_sharded = jax.jit(lambda v, t: sharded.apply(v, t))(variables, x)
        np.testing.assert_allclose(
            np.asarray(out_plain), np.asarray(out_sharded), rtol=1e-4, atol=1e-5
        )
        trainer = self._trainer(mesh)
        xt, yt = datasets.copy_task(128, 16, vocab_size=VOCAB, seed=2)
        hist = trainer.fit(
            x=xt, y=yt, batch_size=8, epochs=1, steps_per_epoch=4, verbose=0
        )
        assert np.isfinite(hist[-1]["loss"])
        state = trainer.state
        up = state.params["Block_1"]["moe"]["moe_up"]
        spec = up.sharding.spec
        assert spec[0] == "expert" and spec[2] == "model", spec

    def test_moe_matches_unsharded(self):
        """EP-sharded MoE must compute the same function as the unsharded
        layer (same params, same tokens)."""
        mesh = self._mesh()
        d, e = 16, 4
        plain = MoEMlp(d, n_experts=e, k=2, capacity_factor=2.0)
        sharded = MoEMlp(
            d, n_experts=e, k=2, capacity_factor=2.0,
            sharding=ShardingConfig(mesh=mesh),
        )
        x = jnp.asarray(np.random.RandomState(3).rand(2, 8, d), jnp.float32)
        variables = _init(plain, x)
        out_plain = plain.apply(variables, x)
        out_sharded = jax.jit(lambda v, t: sharded.apply(v, t))(variables, x)
        np.testing.assert_allclose(
            np.asarray(out_plain), np.asarray(out_sharded), rtol=1e-4, atol=1e-5
        )


@pytest.mark.slow
class TestDropRateObservability:
    """Router overflow drops are safe but must be VISIBLE: the layer sows
    'metrics'/'moe_drop_rate' and the Trainer surfaces it in the step
    metrics and epoch logs (an EP config silently dropping a third of its
    tokens was round-2's Weak #6)."""

    def _train(self, capacity_factor, steps=2):
        model = TransformerLM(
            vocab_size=VOCAB, d_model=32, n_heads=2, n_layers=2,
            dropout=0.0, moe_every=2, n_experts=4,
            capacity_factor=capacity_factor,
        )
        trainer = hvt.Trainer(model, hvt.DistributedOptimizer(optax.sgd(0.0)))
        x, y = datasets.copy_task(64, 16, vocab_size=VOCAB, seed=0)
        hist = trainer.fit(
            x=x, y=y, batch_size=2, epochs=1, steps_per_epoch=steps,
            shuffle_buffer=1, verbose=0,
        )
        return trainer, hist

    def test_drop_rate_in_epoch_logs(self):
        trainer, hist = self._train(capacity_factor=1.25)
        assert "moe_drop_rate" in trainer.metric_names
        rate = hist[0]["moe_drop_rate"]
        assert 0.0 <= rate <= 1.0

    def test_tight_capacity_reports_high_drop_rate(self):
        """capacity_factor well below 1 MUST drop tokens — with k=2 and
        cf=0.25, at most 1/8 of routed pairs fit, so the reported rate must
        be large; ample capacity must report (near) zero."""
        _, starved = self._train(capacity_factor=0.25)
        _, ample = self._train(capacity_factor=8.0)
        assert starved[0]["moe_drop_rate"] > 0.5
        assert ample[0]["moe_drop_rate"] < 0.05
        assert starved[0]["moe_drop_rate"] > ample[0]["moe_drop_rate"]

    def test_drop_rate_value_matches_direct_count(self):
        """The sown scalar equals a direct recount of overflowed (token,
        choice) pairs from the routing math on the same inputs."""
        d, e, k, cf = 16, 4, 2, 0.5
        layer = MoEMlp(d, n_experts=e, k=k, capacity_factor=cf)
        x = jnp.asarray(np.random.RandomState(0).rand(2, 16, d), jnp.float32)
        variables = _init(layer, x)
        # Init itself sows 'metrics'; apply with the bare params so the
        # collection holds exactly this apply's sow.
        _, state = layer.apply(
            {"params": variables["params"]}, x, mutable=["metrics"]
        )
        sown = jax.tree.leaves(state["metrics"])
        assert len(sown) == 1
        reported = float(sown[0])

        # Direct recount, mirroring the routing definition.
        s = x.shape[0] * x.shape[1]  # one group at this size
        probs = jax.nn.softmax(
            x.reshape(1, s, d).astype(jnp.float32)
            @ variables["params"]["router"]["kernel"],
            axis=-1,
        )
        _, top_idx = jax.lax.top_k(probs, k)
        capacity = max(1, int(k * s / e * cf))
        choice = jnp.moveaxis(jax.nn.one_hot(top_idx, e), -2, 1)
        flat = choice.reshape(1, k * s, e)
        pos = jnp.cumsum(flat, axis=1) * flat - 1.0
        kept = ((pos >= 0) & (pos < capacity)).sum()
        expected = 1.0 - float(kept) / (k * s)
        assert reported == pytest.approx(expected, abs=1e-6)

    def test_train_gated_metric_sow_is_loud(self):
        """'metrics' sows must be unconditional: a train-gated sow cannot be
        discovered at build() and must fail with the explanatory error, not
        an opaque pytree mismatch."""
        import flax.linen as fnn

        class Gated(fnn.Module):
            @fnn.compact
            def __call__(self, x, *, train=False):
                y = fnn.Dense(4)(x.reshape((x.shape[0], -1)))
                if train:
                    self.sow("metrics", "gated", jnp.mean(y))
                return y

        tr = hvt.Trainer(Gated(), hvt.DistributedOptimizer(optax.sgd(0.1)))
        x = np.random.RandomState(0).rand(16, 4).astype(np.float32)
        y = np.zeros(16, np.int64)
        with pytest.raises(ValueError, match="unconditional"):
            tr.fit(x=x, y=y, batch_size=2, epochs=1, steps_per_epoch=1)

    def test_reserved_metric_name_is_loud(self):
        import flax.linen as fnn

        class BadName(fnn.Module):
            @fnn.compact
            def __call__(self, x, *, train=False):
                y = fnn.Dense(4)(x.reshape((x.shape[0], -1)))
                self.sow("metrics", "loss", jnp.mean(y))
                return y

        tr = hvt.Trainer(BadName(), hvt.DistributedOptimizer(optax.sgd(0.1)))
        with pytest.raises(ValueError, match="rename the sow"):
            tr.build(np.zeros((8, 4), np.float32))


@pytest.mark.slow
class TestMoESeqComposition:
    """dp x sp x ep on one mesh: MoE blocks under GSPMD compose with the
    partially-manual ring-attention seq axis — the routing einsums stay a
    global function of the full token stream (GSPMD inserts the
    collectives), so the sharded forward must match the unsharded one."""

    def _models(self, mesh):
        kw = dict(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2,
            dropout=0.0, moe_every=2, n_experts=4,
        )
        return (
            TransformerLM(**kw),
            TransformerLM(
                **kw, sharding=ShardingConfig(mesh=mesh, attn="ring")
            ),
        )

    def test_forward_matches_unsharded_and_trains(self):
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, seq=2, expert=2)
        )
        plain, sharded = self._models(mesh)
        rng = np.random.RandomState(71)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(4, 32)).astype(np.int32))
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        out_plain = plain.apply({"params": params}, toks)
        out_sh = jax.jit(
            lambda p, t: sharded.apply({"params": p}, t)
        )(params, toks)
        np.testing.assert_allclose(
            np.asarray(out_sh), np.asarray(out_plain), rtol=2e-4, atol=2e-5
        )

        bspec = P(("data", "fsdp"), "seq")
        trainer = hvt.Trainer(
            sharded,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            mesh=mesh,
            param_specs=param_specs,
            batch_specs=(bspec, bspec),
        )
        x, y = datasets.copy_task(128, 32, vocab_size=VOCAB, seed=1)
        hist = trainer.fit(
            x=x, y=y, batch_size=8, epochs=2, steps_per_epoch=4, verbose=0
        )
        assert np.isfinite(hist[-1]["loss"])
        assert hist[-1]["loss"] < hist[0]["loss"]
        assert "moe_drop_rate" in trainer.metric_names


@pytest.mark.slow
class TestExpertChoice:
    """Expert-choice routing (arXiv:2202.09368): experts pick tokens —
    perfectly balanced and drop-free by construction, no aux loss."""

    def _mlp(self, **kw):
        from horovod_tpu.models.moe import MoEMlp

        kw.setdefault("n_experts", 4)
        kw.setdefault("capacity_factor", 1.0)
        kw.setdefault("router", "expert_choice")
        return MoEMlp(16, **kw)

    def test_every_expert_exactly_full(self):
        """The dispatch tensor assigns each expert exactly `capacity`
        distinct tokens — balance is structural, not incentivized."""
        import jax
        import jax.numpy as jnp

        mlp = self._mlp()
        x = jnp.asarray(
            np.random.RandomState(0).randn(2, 32, 16), jnp.float32
        )
        params = mlp.init(jax.random.PRNGKey(0), x)["params"]

        # Recompute the dispatch the layer builds internally.
        probs = jax.nn.softmax(
            x.reshape(1, 64, 16).astype(jnp.float32)
            @ params["router"]["kernel"], axis=-1
        )
        capacity = max(1, int(2 * 64 / 4 * 1.0))
        _, g_idx = jax.lax.top_k(jnp.moveaxis(probs, -1, 1), capacity)
        for row in np.asarray(g_idx[0]):
            assert len(set(row.tolist())) == capacity  # distinct tokens

    def test_output_and_metrics(self):
        import jax
        import jax.numpy as jnp

        mlp = self._mlp()
        x = jnp.asarray(
            np.random.RandomState(1).randn(2, 32, 16), jnp.float32
        )
        params = mlp.init(jax.random.PRNGKey(0), x)["params"]
        out, state = mlp.apply(
            {"params": params}, x, train=True, mutable=["metrics", "losses"]
        )
        assert out.shape == x.shape
        assert "moe_uncovered_rate" in state["metrics"]
        # Drop-free: no load-balance aux loss is sown.
        assert "losses" not in state or not state["losses"]
        rate = float(np.asarray(jax.tree.leaves(state["metrics"])[0]).ravel()[0])
        assert 0.0 <= rate < 1.0

    def test_router_gets_gradient(self):
        import jax
        import jax.numpy as jnp

        mlp = self._mlp()
        x = jnp.asarray(
            np.random.RandomState(2).randn(1, 32, 16), jnp.float32
        )
        params = mlp.init(jax.random.PRNGKey(0), x)["params"]

        def loss(p):
            return (mlp.apply({"params": p}, x) ** 2).sum()

        g = jax.grad(loss)(params)
        assert float(jnp.abs(g["router"]["kernel"]).max()) > 0.0

    def test_unknown_router_rejected(self):
        import jax
        import jax.numpy as jnp

        mlp = self._mlp(router="nope")
        with pytest.raises(ValueError, match="router must be"):
            mlp.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 16)))

    def test_trains_in_transformer_and_refuses_decode(self):
        import jax
        import jax.numpy as jnp
        import optax

        import horovod_tpu as hvt
        from horovod_tpu.data import datasets
        from horovod_tpu.models.transformer import TransformerLM

        model = TransformerLM(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, dropout=0.0,
            moe_every=2, n_experts=4, moe_router="expert_choice",
        )
        trainer = hvt.Trainer(
            model, hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
        )
        x, y = datasets.copy_task(64, 16, vocab_size=32)
        hist = trainer.fit(x=np.asarray(x), y=np.asarray(y), batch_size=8,
                           epochs=3, verbose=0)
        assert hist[-1]["loss"] < hist[0]["loss"]
        assert "moe_uncovered_rate" in hist[-1]

        from horovod_tpu.models.decoding import generate

        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        with pytest.raises(ValueError, match="training-only"):
            generate(model, params, np.zeros((1, 4), np.int32), 2)

    def test_ep_mesh_matches_unsharded(self):
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models.transformer import ShardingConfig
        from horovod_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, expert=4), devices=jax.devices()[:8]
        )
        x = jnp.asarray(
            np.random.RandomState(3).randn(2, 32, 16), jnp.float32
        )
        plain = self._mlp()
        sharded = self._mlp(sharding=ShardingConfig(mesh=mesh))
        params = plain.init(jax.random.PRNGKey(0), x)["params"]
        a = plain.apply({"params": params}, x)
        b = sharded.apply({"params": params}, x)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
        )
