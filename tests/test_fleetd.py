"""Unit tests for `hvt-launch fleet` (launch/fleetd.py) — the multi-job
control plane: the pure scheduler (priority, placement math, preemption
planning, quarantine), the per-job `JobController` (host units,
host-loss classification, preempt/regrow ledgers), budget isolation,
fleet-journal crash recovery, spec validation — plus the satellites
that ride along: the ``hostdown`` fault kind and `ci_gate`'s ``job=``
scoping. No training processes anywhere in this file; the full fleet
e2e lives in tests/test_fleetd_e2e.py (slow lane)."""

import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.analysis import registry
from horovod_tpu.launch import ci_gate, fleetd, supervisor
from horovod_tpu.obs import prom as obs_prom
from horovod_tpu.testing import faults


# --------------------------------------------------------------------------
# satellite: the hostdown fault kind
# --------------------------------------------------------------------------

class TestHostdownFault:
    def test_parse_plan_accepts_hostdown(self):
        plan = faults.parse_plan("0:4:hostdown")
        assert plan.kind == "hostdown"
        assert plan.rank == 0 and plan.epoch == 4
        assert "hostdown" in faults.KINDS

    def test_parse_plan_rejects_unknown_kind(self):
        with pytest.raises(ValueError, match="hostdown"):
            faults.parse_plan("0:0:hostdowner")

    def test_register_host_pid_and_listing(self, tmp_path):
        pid_dir = str(tmp_path / "h0")
        path = faults.register_host_pid(pid_dir)
        assert os.path.exists(path)
        assert faults.host_pids(pid_dir) == [os.getpid()]
        # Non-pid noise in the directory is ignored.
        (tmp_path / "h0" / "README").write_text("not a pid")
        assert faults.host_pids(pid_dir) == [os.getpid()]
        assert faults.host_pids(str(tmp_path / "missing")) == []

    def test_registration_sweeps_dead_pids(self, tmp_path):
        pid_dir = str(tmp_path / "h0")
        dead = subprocess.Popen(["true"])
        dead.wait()
        faults.register_host_pid(pid_dir, pid=dead.pid)
        # Registering the live self sweeps the dead predecessor.
        faults.register_host_pid(pid_dir)
        assert faults.host_pids(pid_dir) == [os.getpid()]

    def test_hostdown_inert_on_wrong_rank_and_epoch(self, tmp_path):
        # Wrong rank: never fires (we are rank 0 in-process).
        cb = faults.FaultInjectionCallback(faults.parse_plan("5:0:hostdown"))
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        # Wrong epoch: never fires.
        cb = faults.FaultInjectionCallback(faults.parse_plan("0:3:hostdown"))
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        # Still alive — the faults were inert.

    def test_hostdown_one_shot_stamp(self, tmp_path):
        stamp = tmp_path / "stamp"
        stamp.write_text("")
        cb = faults.FaultInjectionCallback(
            faults.parse_plan("0:0:hostdown"), stamp=str(stamp)
        )
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)  # pre-existing stamp: spent — must not fire

    def test_hostdown_fires_kills_registered_cohort(self, tmp_path):
        """The whole-host stroke, in a sacrificial child: the firing rank
        SIGKILLs every registered co-resident pid, then itself."""
        pid_dir = str(tmp_path / "h0")
        sleeper = subprocess.Popen([sys.executable, "-c",
                                    "import time; time.sleep(600)"])
        try:
            faults.register_host_pid(pid_dir, pid=sleeper.pid)
            script = textwrap.dedent("""
                from horovod_tpu.testing import faults
                cb = faults.FaultInjectionCallback(
                    faults.parse_plan("0:0:hostdown"))
                cb.on_epoch_begin(0)
                cb.on_batch_end(0)
                raise SystemExit(7)  # unreachable: _fire SIGKILLs self
            """)
            env = dict(os.environ, HVT_FAULT_HOST_PIDS=pid_dir,
                       JAX_PLATFORMS="cpu")
            proc = subprocess.run([sys.executable, "-c", script], env=env,
                                  timeout=60)
            assert proc.returncode == -signal.SIGKILL
            assert sleeper.wait(timeout=10) == -signal.SIGKILL
        finally:
            if sleeper.poll() is None:
                sleeper.kill()
                sleeper.wait()

    def test_hostdown_degrades_to_self_kill_without_registry(self):
        script = textwrap.dedent("""
            from horovod_tpu.testing import faults
            cb = faults.FaultInjectionCallback(
                faults.parse_plan("0:0:hostdown"))
            cb.on_epoch_begin(0)
            cb.on_batch_end(0)
        """)
        env = {k: v for k, v in os.environ.items()
               if k != "HVT_FAULT_HOST_PIDS"}
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              timeout=60)
        assert proc.returncode == -signal.SIGKILL


# --------------------------------------------------------------------------
# satellite: ci_gate job= scoping
# --------------------------------------------------------------------------

def _write_journal(path, records):
    with open(path, "w") as f:  # hvt: noqa[HVT005] — test fixture
        for rec in records:
            f.write(json.dumps(rec) + "\n")


class TestCiGateJobScoping:
    def test_read_metric_filters_by_job(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        _write_journal(path, [
            {"name": "preempt", "value": 1.0, "job": "a"},
            {"name": "preempt", "value": 2.0, "job": "b"},
            {"name": "preempt", "value": 3.0},
            {"name": "other", "value": 9.0, "job": "a"},
        ])
        assert ci_gate.read_metric(path, "preempt") == [1.0, 2.0, 3.0]
        assert ci_gate.read_metric(path, "preempt", job="a") == [1.0]
        assert ci_gate.read_metric(path, "preempt", job="b") == [2.0]
        # A scoped read never matches records without attribution.
        assert ci_gate.read_metric(path, "preempt", job="c") == []

    def test_check_metrics_scoped_count(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        _write_journal(path, [
            {"name": "regrow", "value": 1.0, "job": "lm"},
            {"name": "regrow", "value": 1.0, "job": "lm"},
            {"name": "regrow", "value": 1.0, "job": "hi"},
        ])
        ok, value = ci_gate.check_metrics(
            path, "regrow", (2.0, 2.0), "count", job="lm")
        assert ok and value == 2.0
        ok, value = ci_gate.check_metrics(path, "regrow", (3.0, 3.0),
                                          "count")
        assert ok and value == 3.0

    def test_run_checks_rule_job_key(self, tmp_path, capsys):
        path = str(tmp_path / "j.jsonl")
        _write_journal(path, [
            {"name": "preempt", "value": 1.0, "job": "lm"},
            {"name": "preempt", "value": 1.0, "job": "hi"},
        ])
        assert ci_gate.run_checks(path, {
            "preempt": {"target": "1..1", "aggregate": "count",
                        "job": "lm"},
        })
        assert "job=lm" in capsys.readouterr().out
        # The same rule WITHOUT scoping sees both jobs' records — the
        # single-job grammar is unchanged, it just counts everything.
        assert not ci_gate.run_checks(path, {
            "preempt": {"target": "1..1", "aggregate": "count"},
        })


# --------------------------------------------------------------------------
# the pure scheduler
# --------------------------------------------------------------------------

def _pool(**hosts):
    return {h: {"slots": n, "until": 0.0} for h, n in hosts.items()}


def _job(name, priority, state, alloc=(), minimum=1, target=2,
         requested=None, preemptible=True, arrival=0.0):
    alloc = list(alloc)
    return {
        "name": name, "priority": priority, "state": state,
        "arrival": arrival, "alloc": alloc, "min": minimum,
        "target": target,
        "requested": len(alloc) if requested is None else requested,
        "preemptible": preemptible,
    }


class TestFreeUnits:
    def test_subtracts_allocations(self):
        free = fleetd.free_units(_pool(h0=2, h1=2),
                                 {"a": ["h0", "h1"]}, now=100.0)
        assert free == {"h0": 1, "h1": 1}

    def test_quarantined_host_contributes_nothing(self):
        pool = _pool(h0=2, h1=2)
        pool["h0"]["until"] = 200.0
        assert fleetd.free_units(pool, {}, now=100.0) == {"h1": 2}
        # Cooldown expiry makes it schedulable again.
        assert fleetd.free_units(pool, {}, now=200.5) == {"h0": 2, "h1": 2}

    def test_full_host_omitted(self):
        assert fleetd.free_units(_pool(h0=1), {"a": ["h0"]}, 0.0) == {}


class TestSchedule:
    def test_places_pending_at_full_target(self):
        acts = fleetd.schedule(
            [_job("a", 1, "pending", target=3)], _pool(h0=2, h1=2), 0.0)
        assert acts == [{"op": "place", "job": "a",
                         "hosts": ["h0", "h0", "h1"]}]

    def test_placement_packs_most_free_host_first(self):
        # h1 has more free units: a 2-unit gang lands whole on h1, not
        # one slot on each host.
        acts = fleetd.schedule(
            [_job("busy", 1, "running", alloc=["h0"], target=1),
             _job("a", 2, "pending", target=2)],
            _pool(h0=2, h1=2), 0.0)
        assert {"op": "place", "job": "a", "hosts": ["h1", "h1"]} in acts

    def test_priority_order_when_capacity_for_one(self):
        acts = fleetd.schedule(
            [_job("lo", 1, "pending", target=2, minimum=2),
             _job("hi", 9, "pending", target=2, minimum=2)],
            _pool(h0=2), 0.0)
        assert acts[0] == {"op": "place", "job": "hi",
                          "hosts": ["h0", "h0"]}
        assert {"op": "wait", "job": "lo", "need": 2} in acts

    def test_arrival_delay_holds_admission(self):
        acts = fleetd.schedule(
            [_job("a", 1, "pending", target=1, arrival=50.0)],
            _pool(h0=1), now=10.0)
        assert acts == []
        acts = fleetd.schedule(
            [_job("a", 1, "pending", target=1, arrival=50.0)],
            _pool(h0=1), now=50.0)
        assert acts == [{"op": "place", "job": "a", "hosts": ["h0"]}]

    def test_preempts_lower_priority_elastic_to_min(self):
        acts = fleetd.schedule(
            [_job("lm", 1, "running", alloc=["h0", "h0", "h1", "h1"],
                  minimum=1, target=4),
             _job("hi", 10, "pending", target=2, minimum=2)],
            _pool(h0=2, h1=2), 0.0)
        assert {"op": "shrink", "job": "lm", "target": 2,
                "for": "hi"} in acts
        assert {"op": "wait", "job": "hi", "need": 2} in acts

    def test_never_preempts_below_min(self):
        acts = fleetd.schedule(
            [_job("lm", 1, "running", alloc=["h0", "h0"], minimum=2,
                  target=2),
             _job("hi", 10, "pending", target=2, minimum=2)],
            _pool(h0=2), 0.0)
        assert all(a["op"] != "shrink" for a in acts)

    def test_never_preempts_equal_or_higher_priority(self):
        acts = fleetd.schedule(
            [_job("peer", 5, "running", alloc=["h0", "h0"], minimum=1,
                  target=2),
             _job("hi", 5, "pending", target=2, minimum=2)],
            _pool(h0=2), 0.0)
        assert all(a["op"] != "shrink" for a in acts)

    def test_non_elastic_job_is_not_preemptible(self):
        acts = fleetd.schedule(
            [_job("static", 1, "running", alloc=["h0", "h0"],
                  preemptible=False),
             _job("hi", 10, "pending", target=2, minimum=2)],
            _pool(h0=2), 0.0)
        assert all(a["op"] != "shrink" for a in acts)

    def test_in_flight_preemption_is_not_repeated(self):
        """The over-preemption regression: once a victim's requested size
        is below its allocation (shrink acknowledged, clean leave still
        landing), the claimant counts those in-flight units instead of
        squeezing the victim further every tick."""
        acts = fleetd.schedule(
            [_job("lm", 1, "running", alloc=["h0", "h0", "h1", "h1"],
                  minimum=1, target=4, requested=2),
             _job("hi", 10, "pending", target=2, minimum=2)],
            _pool(h0=2, h1=2), 0.0)
        assert all(a["op"] != "shrink" for a in acts)
        assert {"op": "wait", "job": "hi", "need": 2} in acts

    def test_degraded_admission_when_nothing_reclaimable(self):
        acts = fleetd.schedule(
            [_job("a", 1, "pending", target=4, minimum=1)],
            _pool(h0=1), 0.0)
        assert acts == [{"op": "place", "job": "a", "hosts": ["h0"]}]

    def test_waits_when_below_min_and_nothing_reclaimable(self):
        acts = fleetd.schedule(
            [_job("a", 1, "pending", target=4, minimum=2)],
            _pool(h0=1), 0.0)
        assert acts == [{"op": "wait", "job": "a", "need": 4}]

    def test_grows_shrunken_job_when_units_free(self):
        acts = fleetd.schedule(
            [_job("lm", 1, "running", alloc=["h0", "h0"], minimum=1,
                  target=4)],
            _pool(h0=2, h1=2), 0.0)
        assert acts == [{"op": "grow", "job": "lm",
                         "hosts": ["h1", "h1"]}]

    def test_high_priority_regrow_preempts_lower(self):
        # Host loss shrank `hi`; regrowing it may preempt `lo`.
        acts = fleetd.schedule(
            [_job("lo", 1, "running", alloc=["h1", "h1"], minimum=1,
                  target=2),
             _job("hi", 10, "running", alloc=["h0"], minimum=1,
                  target=2)],
            _pool(h0=2, h1=2), 0.0)
        assert {"op": "grow", "job": "hi", "hosts": ["h0"]} in acts

    def test_quarantined_host_not_schedulable(self):
        pool = _pool(h0=2)
        pool["h0"]["until"] = 500.0
        acts = fleetd.schedule(
            [_job("a", 1, "pending", target=2, minimum=1)], pool, 100.0)
        assert acts == [{"op": "wait", "job": "a", "need": 2}]
        acts = fleetd.schedule(
            [_job("a", 1, "pending", target=2, minimum=1)], pool, 500.5)
        assert acts == [{"op": "place", "job": "a",
                         "hosts": ["h0", "h0"]}]


# --------------------------------------------------------------------------
# JobController — host units, preempt/regrow ledgers, host_lost rules
# --------------------------------------------------------------------------

class _FakeProc:
    def __init__(self, code=None):
        self.code = code
        self.pid = 12345

    def poll(self):
        return self.code


def _controller(monkeypatch, hosts, fleet_dir="/tmp/fleet-unit"):
    spawned = []

    def fake_spawn(argv, env, member_id, slot, tag_output=True):
        spawned.append((member_id, slot, dict(env)))
        return _FakeProc()

    monkeypatch.setattr(supervisor, "_spawn_member_local", fake_spawn)
    ctl = fleetd.JobController("job", hosts, fleet_dir, ["python", "x.py"])
    return ctl, spawned


class TestJobController:
    def test_spawn_fills_hosts_in_sorted_order(self, monkeypatch):
        ctl, spawned = _controller(monkeypatch, ["h1", "h0", "h0"])
        for i in range(3):
            ctl.spawn(f"m{i}", i, {})
        assert [ctl._members[f"m{i}"]["host"] for i in range(3)] == \
            ["h0", "h0", "h1"]
        env = spawned[0][2]
        assert env["HVT_FLEET_HOST"] == "h0"
        assert env["HVT_FAULT_HOST_PIDS"].endswith(
            os.path.join("hostpids", "h0"))
        assert ctl.capacity() == 3

    def test_take_preempts_releases_unoccupied_units_first(
            self, monkeypatch):
        ctl, _ = _controller(monkeypatch, ["h0", "h0", "h1"])
        ctl.spawn("m0", 0, {})  # h0 — the only live member
        ctl.shrink(1)
        assert ctl.take_preempts() == []  # two empty units freed, no kill
        snap = ctl.snapshot()
        assert sorted(snap["released"]) == ["h0", "h1"]
        assert snap["alloc"] == ["h0"]

    def test_take_preempts_victims_reverse_host_order(self, monkeypatch):
        ctl, _ = _controller(monkeypatch, ["h0", "h0", "h1", "h1"])
        for i in range(4):
            ctl.spawn(f"m{i}", i, {})
        ctl.shrink(2)
        victims = ctl.take_preempts()
        # Live victims come off the highest-named host first, newest
        # member first — releases concentrate on whole hosts.
        assert victims == ["m3", "m2"]
        assert ctl.alloc == ["h0", "h0"]
        # The units left the allocation immediately; the RELEASE ledger
        # waits for the members to actually vacate.
        assert ctl.snapshot()["released"] == []
        ctl.on_exit("m3", "preempt")
        ctl.on_exit("m2", "preempt")
        assert ctl.snapshot()["released"] == ["h1", "h1"]

    def test_take_preempts_idempotent(self, monkeypatch):
        ctl, _ = _controller(monkeypatch, ["h0", "h0"])
        ctl.spawn("m0", 0, {})
        ctl.spawn("m1", 1, {})
        ctl.shrink(1)
        assert ctl.take_preempts() == ["m1"]
        assert ctl.take_preempts() == []  # already at target

    def test_classify_lone_sigkill_stays_oom(self, monkeypatch):
        ctl, _ = _controller(monkeypatch, ["h0", "h1"])
        ctl.spawn("m0", 0, {})
        ctl.spawn("m1", 1, {})
        ctl._members["m0"]["proc"].code = -signal.SIGKILL
        # m0 is alone on h0: no cohort, classic classification keeps.
        assert ctl.classify_exit("m0", -signal.SIGKILL, "oom-kill") is None

    def test_classify_host_cohort_charges_once(self, monkeypatch):
        ctl, _ = _controller(monkeypatch, ["h0", "h0", "h1"])
        for i in range(3):
            ctl.spawn(f"m{i}", i, {})
        ctl._members["m0"]["proc"].code = -signal.SIGKILL
        ctl._members["m1"]["proc"].code = 128 + signal.SIGKILL
        first = ctl.classify_exit("m0", -signal.SIGKILL, "oom-kill")
        assert first == ("host_lost", True)
        assert "h0" not in ctl.alloc and ctl.alloc == ["h1"]
        assert ctl.snapshot()["lost_hosts"] == ["h0"]
        sibling = ctl.classify_exit(
            "m1", 128 + signal.SIGKILL, "oom-kill")
        assert sibling == ("host_lost", False)
        # The incident reported the host exactly once.
        assert ctl.snapshot()["lost_hosts"] == ["h0"]

    def test_classify_sibling_after_first_reap_rides_free(
            self, monkeypatch):
        # The real reap interleaving: the first victim is classified AND
        # popped (on_exit) before the sibling's death is looked at. The
        # sibling is then the host's last live member — the lost-host
        # ledger, not the cohort size, must carry the classification.
        ctl, _ = _controller(monkeypatch, ["h0", "h0", "h1"])
        for i in range(3):
            ctl.spawn(f"m{i}", i, {})
        ctl._members["m0"]["proc"].code = -signal.SIGKILL
        ctl._members["m1"]["proc"].code = -signal.SIGKILL
        assert ctl.classify_exit(
            "m0", -signal.SIGKILL, "oom-kill") == ("host_lost", True)
        ctl.on_exit("m0", "host_lost")
        assert ctl.classify_exit(
            "m1", -signal.SIGKILL, "oom-kill") == ("host_lost", False)

    def test_regrown_host_sheds_lost_marker(self, monkeypatch):
        # After quarantine the scheduler may hand the SAME host back; a
        # later lone SIGKILL there is an oom-kill again, not a free ride
        # on the old incident.
        ctl, _ = _controller(monkeypatch, ["h0", "h0"])
        ctl.spawn("m0", 0, {})
        ctl.spawn("m1", 1, {})
        ctl._members["m0"]["proc"].code = -signal.SIGKILL
        ctl._members["m1"]["proc"].code = -signal.SIGKILL
        assert ctl.classify_exit(
            "m0", -signal.SIGKILL, "oom-kill") == ("host_lost", True)
        ctl.on_exit("m0", "host_lost")
        ctl.on_exit("m1", "host_lost")
        ctl.grow(["h0", "h1"])
        ctl.spawn("m2", 0, {})  # lands on h0 again
        ctl._members["m2"]["proc"].code = -signal.SIGKILL
        assert ctl._members["m2"]["host"] == "h0"
        assert ctl.classify_exit(
            "m2", -signal.SIGKILL, "oom-kill") is None

    def test_classify_ignores_non_sigkill_and_preempting(
            self, monkeypatch):
        ctl, _ = _controller(monkeypatch, ["h0", "h0"])
        ctl.spawn("m0", 0, {})
        ctl.spawn("m1", 1, {})
        assert ctl.classify_exit("m0", 1, "crash") is None
        ctl._members["m0"]["preempting"] = True
        ctl._members["m0"]["proc"].code = -signal.SIGKILL
        ctl._members["m1"]["proc"].code = -signal.SIGKILL
        assert ctl.classify_exit(
            "m0", -signal.SIGKILL, "oom-kill") is None
        # The surviving cohort is just m1 — lone, so no host_lost either.
        assert ctl.classify_exit(
            "m1", -signal.SIGKILL, "oom-kill") is None

    def test_grow_queues_budget_free_launches(self, monkeypatch):
        ctl, _ = _controller(monkeypatch, ["h0"])
        ctl.spawn("m0", 0, {})
        ctl.grow(["h1", "h1"])
        assert ctl.capacity() == 3
        assert ctl.take_grows() == 2
        assert ctl.take_grows() == 0  # drained
        ctl.spawn("m1", 1, {})
        assert ctl._members["m1"]["host"] == "h1"


# --------------------------------------------------------------------------
# budget isolation
# --------------------------------------------------------------------------

class TestBudgetIsolation:
    def test_flags_foreign_attribution(self, tmp_path):
        path = str(tmp_path / "restarts.jsonl")
        _write_journal(path, [
            {"name": "restarts", "value": 1.0, "job": "mine"},
            {"name": "restarts", "value": 1.0, "job": "other"},
            {"name": "join", "value": 1.0},
        ])
        bad = fleetd.budget_isolation_violations("mine", path)
        assert len(bad) == 1 and bad[0]["job"] == "other"

    def test_clean_journal_passes(self, tmp_path):
        path = str(tmp_path / "restarts.jsonl")
        _write_journal(path, [
            {"name": "restarts", "value": 1.0, "job": "mine"},
        ])
        assert fleetd.budget_isolation_violations("mine", path) == []
        assert fleetd.budget_isolation_violations("mine", None) == []


# --------------------------------------------------------------------------
# spec validation
# --------------------------------------------------------------------------

def _fleet_spec(tmp_path, **overrides):
    spec = {
        "fleet": {"pool": {"h0": {"slots": 2}, "h1": {"slots": 2}},
                  "dir": str(tmp_path / "state")},
        "jobs": [
            {"name": "lm", "priority": 1, "job": {
                "command": "python train.py",
                "elastic": {"min_ranks": 1, "max_ranks": 4},
                "env": {"PS_MODEL_PATH": str(tmp_path / "lm")},
            }},
            {"name": "hi", "priority": 10, "delay_s": 5, "job": {
                "command": "python train.py",
                "elastic": {"min_ranks": 2, "max_ranks": 2},
                "env": {"PS_MODEL_PATH": str(tmp_path / "hi")},
            }},
        ],
    }
    spec.update(overrides)
    return spec


class TestLoadEntries:
    def test_parses_valid_spec(self, tmp_path):
        cfg, entries = fleetd.load_entries(_fleet_spec(tmp_path))
        assert cfg["pool"] == {"h0": 2, "h1": 2}
        lm, hi = entries
        assert (lm.min_units, lm.target_units, lm.elastic) == (1, 4, True)
        assert (hi.min_units, hi.target_units) == (2, 2)
        assert hi.delay_s == 5.0 and hi.priority == 10
        assert lm.log_path.endswith("restarts.jsonl")

    def test_static_job_min_equals_nprocs(self, tmp_path):
        spec = _fleet_spec(tmp_path, jobs=[
            {"name": "s", "job": {
                "command": "python t.py", "nprocs": 3,
                "env": {"PS_MODEL_PATH": str(tmp_path / "s")},
            }},
        ])
        _, entries = fleetd.load_entries(spec)
        assert (entries[0].min_units, entries[0].target_units) == (3, 3)
        assert not entries[0].elastic

    def test_reports_every_error_at_once(self, tmp_path):
        spec = _fleet_spec(tmp_path)
        spec["fleet"]["pool"] = {}
        spec["jobs"][0]["job"]["hosts"] = ["a", "b"]
        spec["jobs"][1]["name"] = "lm"  # duplicate
        spec["jobs"].append({"priority": 3})  # nameless
        with pytest.raises(ValueError) as err:
            fleetd.load_entries(spec)
        msg = str(err.value)
        assert "pool" in msg
        assert "hosts: conflicts" in msg
        assert "duplicate name" in msg
        assert "needs a name" in msg

    def test_missing_journal_path_is_an_error(self, tmp_path):
        spec = _fleet_spec(tmp_path, jobs=[
            {"name": "j", "job": {"command": "python t.py",
                                  "nprocs": 1}},
        ])
        with pytest.raises(ValueError, match="budget-isolation"):
            fleetd.load_entries(spec)

    def test_launcher_delegates_fleet_subcommand(self, tmp_path, capsys):
        from horovod_tpu.launch import launcher
        bad = tmp_path / "bad.yaml"
        bad.write_text("fleet: {pool: {}}\njobs: []\n")
        assert launcher.main(["fleet", str(bad)]) == 1
        assert "pool" in capsys.readouterr().out

    def test_fleet_knobs_registered(self):
        assert registry.get_float("HVT_FLEET_TICK_S") == 0.5
        assert registry.get_float("HVT_FLEET_QUARANTINE_S") == 60.0
        assert registry.get_raw("HVT_FLEET_HOST") is None
        assert registry.get_raw("HVT_FAULT_HOST_PIDS") is None


# --------------------------------------------------------------------------
# fleetd journal recovery
# --------------------------------------------------------------------------

def _dead_pid():
    proc = subprocess.Popen(["true"])
    proc.wait()
    return proc.pid


class TestFleetdRecovery:
    def test_fresh_run_wipes_finished_journal(self, tmp_path):
        spec = _fleet_spec(tmp_path)
        state_dir = str(tmp_path / "state")
        os.makedirs(state_dir, exist_ok=True)
        journal = os.path.join(state_dir, fleetd.JOURNAL_NAME)
        _write_journal(journal, [
            {"name": "fleet_start", "value": 1.0, "start": 100.0},
            {"name": "fleet_done", "value": 1.0, "ok": True},
        ])
        daemon = fleetd.Fleetd(spec, verbose=False)
        assert daemon._maybe_recover() is False
        assert not os.path.exists(journal)
        assert all(st["state"] == "pending"
                   for st in daemon.jobs.values())

    def test_recovery_replays_state_and_cursors(self, tmp_path):
        spec = _fleet_spec(tmp_path)
        state_dir = str(tmp_path / "state")
        os.makedirs(state_dir, exist_ok=True)
        journal = os.path.join(state_dir, fleetd.JOURNAL_NAME)
        dead = _dead_pid()
        _write_journal(journal, [
            {"name": "fleet_start", "value": 1.0, "start": 100.0},
            {"name": "place", "value": 4.0, "job": "lm",
             "hosts": ["h0", "h0", "h1", "h1"], "pid": dead,
             "ctl_port": 1, "status_port": 2},
            {"name": "preempt", "value": 1.0, "victim": "lm", "job": "lm",
             "target": 2, "for": "hi"},
            {"name": "release", "value": 2.0, "job": "lm",
             "hosts": ["h1", "h1"], "source": "ctl"},
            {"name": "place", "value": 2.0, "job": "hi",
             "hosts": ["h1", "h1"], "pid": dead, "ctl_port": 3,
             "status_port": 4},
            {"name": "host_lost", "value": 1.0, "job": "lm", "host": "h0",
             "until": 9e12},
            {"name": "regrow", "value": 1.0, "job": "lm",
             "hosts": ["h1"]},
        ])
        daemon = fleetd.Fleetd(spec, verbose=False)
        assert daemon._maybe_recover() is True
        assert daemon.start_wall == 100.0
        lm, hi = daemon.jobs["lm"], daemon.jobs["hi"]
        # lm: placed on 4, shrunk to 2 (preempt), released 2, lost h0,
        # regrown 1 — allocation is the journal's net: just the regrow.
        assert lm["alloc"] == ["h1"]
        assert lm["requested"] == 1  # regrow reset it to len(alloc)
        assert lm["seen_released"] == 2  # ctl cursor survives the crash
        assert lm["seen_lost"] == 1
        assert hi["alloc"] == ["h1", "h1"]
        # The lost host is still quarantined.
        assert daemon.pool["h0"]["until"] == 9e12
        # Both recorded pids are dead: adopted, then finished by the
        # first tick through the normal gates path.
        assert lm["adopted"] and hi["adopted"]
        assert lm["pid"] is None and hi["pid"] is None

    def test_recovery_marks_done_jobs_done(self, tmp_path):
        spec = _fleet_spec(tmp_path)
        state_dir = str(tmp_path / "state")
        os.makedirs(state_dir, exist_ok=True)
        journal = os.path.join(state_dir, fleetd.JOURNAL_NAME)
        _write_journal(journal, [
            {"name": "fleet_start", "value": 1.0, "start": 100.0},
            {"name": "place", "value": 2.0, "job": "hi",
             "hosts": ["h0", "h0"], "pid": _dead_pid()},
            {"name": "release", "value": 2.0, "job": "hi",
             "hosts": ["h0", "h0"], "source": "exit"},
            {"name": "job_done", "value": 1.0, "job": "hi",
             "exit_code": 0, "gates": True},
        ])
        daemon = fleetd.Fleetd(spec, verbose=False)
        assert daemon._maybe_recover() is True
        assert daemon.jobs["hi"]["state"] == "done"
        assert daemon.jobs["hi"]["alloc"] == []
        assert daemon.jobs["lm"]["state"] == "pending"


# --------------------------------------------------------------------------
# fleetd metrics
# --------------------------------------------------------------------------

class TestFleetdMetrics:
    def test_series_from_journal_and_state(self, tmp_path):
        journal = str(tmp_path / "fleet-journal.jsonl")
        _write_journal(journal, [
            {"name": "preempt", "value": 1.0, "job": "lm"},
            {"name": "regrow", "value": 1.0, "job": "lm"},
            {"name": "regrow", "value": 1.0, "job": "lm"},
            {"name": "host_lost", "value": 1.0, "job": "lm"},
        ])
        jobs = {
            "lm": {"state": "running", "alloc": ["h0", "h0"],
                   "budget": 2.0},
            "hi": {"state": "done", "alloc": [], "budget": None},
        }
        pool = {"h0": {"slots": 2, "until": 0.0},
                "h1": {"slots": 2, "until": 9e12}}
        text = obs_prom.render(fleetd.fleetd_metrics(
            journal, jobs, pool, now=100.0))
        assert "hvt_fleetd_preempts_total 1" in text
        assert "hvt_fleetd_regrows_total 2" in text
        assert "hvt_fleetd_host_lost_total 1" in text
        assert 'hvt_fleetd_job_size{job="lm"} 2' in text
        assert ('hvt_fleetd_job_restart_budget_remaining{job="lm"} 2'
                in text)
        assert 'hvt_fleetd_jobs{state="running"} 1' in text
        assert 'hvt_fleetd_jobs{state="done"} 1' in text
        assert 'hvt_fleetd_hosts{state="up"} 1' in text
        assert 'hvt_fleetd_hosts{state="quarantined"} 1' in text


# --------------------------------------------------------------------------
# sticky leave intent: a preemption SIGTERM can never be dropped
# --------------------------------------------------------------------------

class TestStickyLeaveIntent:
    """The fleet's preemption contract end: SIGTERM may land in the
    rendezvous -> runtime-init -> trainer-build window where fit()'s
    handler isn't installed yet (and `jax.distributed.initialize`
    re-claims the signal for XLA's notifier). The intent must stick at
    module scope and be honored at the next boundary — the alternative
    is the grace escalation SIGKILLing the victim mid-collective and
    crashing (and CHARGING) the survivors."""

    @pytest.fixture(autouse=True)
    def _clean_flag(self):
        from horovod_tpu.elastic import state as elastic_state
        elastic_state.clear_leave_signal()
        yield
        elastic_state.clear_leave_signal()

    def test_signal_leave_sticks_until_cleared(self):
        from horovod_tpu.elastic import state as elastic_state
        assert not elastic_state.leave_signaled()
        elastic_state.signal_leave()
        assert elastic_state.leave_signaled()
        elastic_state.signal_leave(signal.SIGTERM, None)  # handler shape
        assert elastic_state.leave_signaled()
        elastic_state.clear_leave_signal()
        assert not elastic_state.leave_signaled()

    def test_callback_handler_sets_module_flag(self):
        from horovod_tpu.elastic import state as elastic_state
        cb = elastic_state.ElasticStateCallback(
            elastic_state.ElasticState(), client=None,
            commit_every=1, commit_every_steps=0, rescale_every_steps=0,
        )
        cb._handler(signal.SIGTERM, None)
        assert cb._leave_requested
        assert elastic_state.leave_signaled()

    def test_run_exits_143_on_pending_leave_before_rendezvous(self):
        from horovod_tpu.elastic import rescale
        from horovod_tpu.elastic import state as elastic_state

        class _Client:
            member_id = "m0"

            def __init__(self):
                self.left = []

            def leave(self, reason="leave"):
                self.left.append(reason)

            def sync(self, progress=0):
                raise AssertionError(
                    "a leave-pending member must not re-rendezvous")

        client = _Client()
        elastic_state.signal_leave()
        with pytest.raises(SystemExit) as ex:
            rescale.run(lambda state, world: None, client=client)
        assert ex.value.code == 143
        assert client.left == ["sigterm"]
        # The intent was CONSUMED — a later in-process run starts clean.
        assert not elastic_state.leave_signaled()

    def test_preempt_term_resent_through_swallowed_first_signal(
        self, tmp_path
    ):
        """Regression for the fleet e2e's charged-crash failure: the
        victim's first SIGTERM is swallowed (exactly what XLA's
        preemption notifier does while jax.distributed.initialize is in
        flight). The supervisor must RE-SEND TERM inside the grace
        window so the clean leave still happens — escalating straight
        to SIGKILL strands the survivors in a collective until the gloo
        timeout aborts them, turning a free preemption into charged
        crashes."""
        from test_elastic import write_fake_worker

        class _DeafPreempter:
            """Preempts m1 only once its deaf TERM trap is armed, then
            caps capacity at 1 so the freed slot is not backfilled."""

            def __init__(self, armed_path):
                self.armed_path = armed_path
                self.fired = False

            def take_preempts(self):
                if not self.fired and os.path.exists(self.armed_path):
                    self.fired = True
                    return ["m1"]
                return []

            def capacity(self):
                return 1 if self.fired else 2

            def take_grows(self):
                return 0

            def classify_exit(self, member_id, code, kind):
                return None

            def on_exit(self, member_id, kind):
                pass

        argv = write_fake_worker(tmp_path)
        log = tmp_path / "restarts.jsonl"
        armed = tmp_path / "deaf-armed"
        code = supervisor.supervise_elastic(
            2, argv,
            env={"FAKE_EPOCHS": "60", "FAKE_PACE": "0.1",
                 "FAKE_DEAF": "m1", "FAKE_DEAF_STAMP": str(armed)},
            policy=supervisor.RestartPolicy(
                max_restarts=3, backoff=0.1, grace_seconds=20.0),
            elastic=supervisor.ElasticPolicy(
                min_ranks=1, max_ranks=2, rendezvous_timeout=20.0),
            log_path=str(log),
            controller=_DeafPreempter(str(armed)),
        )
        assert code == 0
        # The re-sent TERM (not a SIGKILL at grace expiry, 20s out) was
        # honored: the victim left cleanly and stamped on its way.
        assert (tmp_path / "deaf-armed.left").exists()
        with open(log) as f:
            records = [json.loads(line) for line in f if line.strip()]
        assert [r["member"] for r in records
                if r["name"] == "preempt"] == ["m1"]
        # ZERO budget spent: no restarts records at all.
        assert not [r for r in records if r["name"] == "restarts"]
