"""File-backed sharded dataset: write/read round-trip through memory
maps, per-process striping, epoch permutations, Trainer integration."""

import json
import os

import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.data.filedataset import FileDataset, write_shards


@pytest.fixture()
def store(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.rand(100, 5).astype(np.float32)
    y = np.arange(100).astype(np.int32)
    d = write_shards({"x": x, "y": y}, str(tmp_path / "ds"), shard_size=16)
    return d, x, y


class TestRoundTrip:
    def test_content_and_mmap(self, store):
        d, x, y = store
        ds = FileDataset(d)
        assert ds.num_examples == 100
        got = ds.gather(np.arange(100))
        np.testing.assert_array_equal(got["x"], x)
        np.testing.assert_array_equal(got["y"], y)
        # Shards are MAPPED, not loaded.
        assert isinstance(ds._map(0, "x"), np.memmap)

    def test_gather_arbitrary_order_crossing_shards(self, store):
        d, x, y = store
        ds = FileDataset(d)
        rows = np.array([99, 0, 17, 16, 15, 63, 2])
        got = ds.gather(rows)
        np.testing.assert_array_equal(got["y"], y[rows])
        np.testing.assert_array_equal(got["x"], x[rows])

    def test_ragged_last_shard(self, tmp_path):
        d = write_shards(
            {"a": np.arange(10)}, str(tmp_path / "r"), shard_size=4
        )
        ds = FileDataset(d)
        assert ds.num_examples == 10
        np.testing.assert_array_equal(
            ds.gather(np.arange(10))["a"], np.arange(10)
        )

    def test_bad_dir_rejected(self, tmp_path):
        p = tmp_path / "not_ds"
        p.mkdir()
        (p / "index.json").write_text(json.dumps({"format": "nope"}))
        with pytest.raises(ValueError, match="not a shard"):
            FileDataset(str(p))

    def test_writer_validation(self, tmp_path):
        with pytest.raises(ValueError, match="leading"):
            write_shards(
                {"a": np.arange(4), "b": np.arange(5)}, str(tmp_path / "v")
            )
        with pytest.raises(ValueError, match="non-empty dict"):
            write_shards({}, str(tmp_path / "v2"))


class TestIteration:
    def test_epoch_is_a_permutation(self, store):
        d, _, y = store
        ds = FileDataset(d)
        seen = np.concatenate(
            [b["y"] for b in ds.batches(10, seed=3)]
        )
        assert sorted(seen.tolist()) == list(range(100))
        assert not np.array_equal(seen, np.arange(100))  # actually shuffled

    def test_striped_sharding_disjoint_exhaustive(self, store):
        d, _, _ = store
        ds = FileDataset(d)
        parts = [
            {int(v) for b in ds.batches(5, shard=(i, 4), shuffle=False)
             for v in b["y"]}
            for i in range(4)
        ]
        assert set().union(*parts) == set(range(100))
        for i in range(4):
            for j in range(i + 1, 4):
                assert not parts[i] & parts[j]

    def test_repeat_crosses_epochs_with_fresh_permutations(self, store):
        d, _, _ = store
        ds = FileDataset(d)
        it = ds.batches(100, repeat=True, seed=1)
        first, second = next(it)["y"], next(it)["y"]
        assert sorted(first.tolist()) == sorted(second.tolist())
        assert not np.array_equal(first, second)


class TestShardViews:
    """`.shard(i, n)`/`.reshard(i, n)` — the ArrayDataset parity views
    the elastic N→M rescale recuts on the file-backed path (ISSUE 8
    satellite)."""

    def test_shard_view_defaults_batches(self, store):
        d, _, _ = store
        ds = FileDataset(d)
        view = ds.shard(1, 4)
        assert view.shard_spec == (1, 4)
        seen = {int(v) for b in view.batches(5, shuffle=False)
                for v in b["y"]}
        assert seen == set(range(1, 100, 4))

    def test_reshard_recuts_from_full(self, store):
        d, _, _ = store
        ds = FileDataset(d)
        # Unlike shard-of-shard, reshard derives from the FULL row space:
        # a 2-way view resharded 4-way still partitions all 100 rows.
        views = [ds.shard(0, 2).reshard(i, 4) for i in range(4)]
        parts = [
            {int(v) for b in v.batches(5, shuffle=False) for v in b["y"]}
            for v in views
        ]
        assert set().union(*parts) == set(range(100))
        for i in range(4):
            for j in range(i + 1, 4):
                assert not parts[i] & parts[j]

    def test_reshard_same_size_identical_stream(self, store):
        d, _, _ = store
        ds = FileDataset(d).shard(0, 2)
        a = [b["y"] for _, b in zip(
            range(8), ds.batches(10, seed=4, repeat=True))]
        r = ds.reshard(0, 2)
        b = [bb["y"] for _, bb in zip(
            range(4), r.batches(10, seed=4, repeat=True, skip=4))]
        for p, q in zip(a[4:], b):
            np.testing.assert_array_equal(p, q)

    def test_out_of_range_rejected(self, store):
        d, _, _ = store
        with pytest.raises(ValueError, match="out of range"):
            FileDataset(d).shard(3, 2)


class TestTrainerIntegration:
    def test_fit_from_disk(self, tmp_path):
        import flax.linen as nn

        rng = np.random.RandomState(0)
        x = rng.rand(256, 8).astype(np.float32)
        w = rng.rand(8)
        y = (x @ w > w.sum() / 2).astype(np.int32)
        d = write_shards({"x": x, "y": y}, str(tmp_path / "ds"), shard_size=64)

        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, a, train: bool = False):
                return nn.Dense(2)(a)

        trainer = hvt.Trainer(
            Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)),
            loss="sparse_categorical_crossentropy",
        )
        ds = FileDataset(d)
        hist = trainer.fit(
            dataset=ds.pairs("x", "y", batch_size=32, repeat=True),
            steps_per_epoch=8, epochs=4, verbose=0,
        )
        assert hist[-1]["loss"] < hist[0]["loss"]


def test_rewrite_refused(tmp_path):
    d = write_shards({"a": np.arange(8)}, str(tmp_path / "once"), shard_size=4)
    with pytest.raises(ValueError, match="already holds"):
        write_shards({"a": np.arange(8)}, d, shard_size=4)


def test_starved_stripe_refused(tmp_path):
    d = write_shards({"a": np.arange(10)}, str(tmp_path / "tiny"), shard_size=4)
    ds = FileDataset(d)
    with pytest.raises(ValueError, match="stripe"):
        next(ds.batches(8, shard=(0, 4), repeat=True))
    # drop_remainder=False yields the short batch instead.
    b = next(ds.batches(8, shard=(0, 4), drop_remainder=False))
    assert len(b["a"]) == 3


def test_string_columns_roundtrip(tmp_path):
    """dtype round-trip for non-numeric columns (dtype.str, not .name)."""
    labels = np.array(["cat", "doggo", "x"])
    d = write_shards(
        {"label": labels, "v": np.arange(3)}, str(tmp_path / "s"), shard_size=2
    )
    ds = FileDataset(d)
    got = ds.gather(np.array([2, 0, 1]))
    np.testing.assert_array_equal(got["label"], labels[[2, 0, 1]])
