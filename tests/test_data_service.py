"""hvt-data — the distributed data service, tier-1 lane (PR 20).

In-process, bounded units over the dispatcher (`data.service.DataService`)
and the trainer-side client (`data.client.ServiceClient`):

* wire protocol framing (round-trip, torn-frame detection);
* byte identity: served batches == the client's local stream, batch for
  batch (the failover argument's foundation);
* `StreamCursor` refusals SURVIVE serialization — foreign format, wrong
  engine kind, wrong geometry all come back as loud, never-retried
  `StreamCursorError`s and count on ``hvt_data_cursor_refusals_total``;
* journal recovery: a stopped dispatcher restarted on the same ``--dir``
  adopts its admissions and serves a SPEC-LESS re-attach;
* the degrade → rank-local → re-attach arc, byte-identical throughout;
* per-job isolation: a wedged job never delays another job's serving;
* the ``netdrop``/``dataslow`` fault kinds (parse + firing windows);
* the retries-outcome collector export and the fleet data_service spec
  plumbing.

The subprocess chaos runs (dispatcher SIGKILL mid-fit, checkpoint
byte-identity against a locally-fed control) live in
tests/test_data_service_e2e.py, slow lane.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from horovod_tpu.data import client as client_lib
from horovod_tpu.data import service as service_lib
from horovod_tpu.data import stream as stream_lib
from horovod_tpu.data.client import ServiceClient, build_source
from horovod_tpu.data.service import DataService
from horovod_tpu.obs import prom as obs_prom

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def corpus(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype(np.float32)
    y = (np.arange(64) % 4).astype(np.int64)
    path = str(tmp_path / "corpus.npz")
    np.savez(path, x=x, y=y)
    return path


def _spec(path, batch=8, seed=11, shard=None):
    return {
        "source": "npz", "path": path, "keys": ["x", "y"],
        "batch_size": batch, "seed": seed, "shuffle_buffer": 0,
        "shard": shard,
    }


@pytest.fixture()
def svc(tmp_path):
    s = DataService(str(tmp_path / "svc")).start()
    yield s
    s.stop()


@pytest.fixture(autouse=True)
def _retry_stats_hygiene():
    """RETRY_STATS is process-global (the trainer exporter mirrors it);
    the degrade/retry arcs exercised here must not leak counts into
    later tests' scrapes."""
    saved = dict(stream_lib.RETRY_STATS)
    yield
    stream_lib.RETRY_STATS.clear()
    stream_lib.RETRY_STATS.update(saved)


@pytest.fixture()
def fast_retries(monkeypatch):
    monkeypatch.setenv("HVT_DATA_RETRIES", "1")
    monkeypatch.setenv("HVT_DATA_BACKOFF_S", "0.001")
    monkeypatch.delenv("HVT_FAULT", raising=False)
    monkeypatch.delenv("HVT_FAULT_STAMP", raising=False)


def _batch_bytes(batch):
    import jax.tree_util

    return b"".join(
        np.ascontiguousarray(a).tobytes()
        for a in jax.tree_util.tree_leaves(batch)
    )


def _refusals(svc):
    values = obs_prom.parse_text(obs_prom.render(svc.registry))
    return values.get("hvt_data_cursor_refusals_total")


# --- wire protocol ---------------------------------------------------------


class TestFraming:
    def test_frame_round_trip_with_payload(self):
        a, b = socket.socketpair()
        try:
            service_lib.send_frame(a, {"op": "x", "n": 3}, b"\x00\x01pay")
            header, payload = service_lib.recv_frame(b)
            assert header == {"op": "x", "n": 3}
            assert payload == b"\x00\x01pay"
        finally:
            a.close()
            b.close()

    def test_clean_eof_is_none_torn_frame_raises(self):
        a, b = socket.socketpair()
        a.close()
        assert service_lib.recv_frame(b) == (None, b"")
        b.close()
        a, b = socket.socketpair()
        try:
            # A header promising more bytes than ever arrive: EOF lands
            # MID-frame and must raise, not read as a clean close.
            a.sendall(service_lib._FRAME.pack(100, 0) + b"{}")
            a.close()
            with pytest.raises(ConnectionError, match="mid-frame"):
                service_lib.recv_frame(b)
        finally:
            b.close()


# --- serving: byte identity ------------------------------------------------


class TestServedByteIdentity:
    def test_served_batches_equal_local_stream(self, corpus, svc):
        spec = _spec(corpus)
        client = ServiceClient(
            build_source(spec), spec, job="idjob", address=svc.address
        )
        served = client.batches(batches_per_epoch=4)
        local = build_source(spec).batches(batches_per_epoch=4)
        # 10 batches = two epoch boundaries crossed while ATTACHED.
        for _ in range(10):
            assert _batch_bytes(next(served)) == _batch_bytes(next(local))
        client.close()
        assert client.events == []  # no degrade, no re-attach

    def test_sharded_specs_stay_disjoint_per_client(self, corpus, svc):
        specs = [_spec(corpus, shard=[i, 2]) for i in range(2)]
        got = []
        for i, spec in enumerate(specs):
            c = ServiceClient(
                build_source(spec), spec, job="shards", shard=(i, 2),
                address=svc.address,
            )
            it = c.batches(batches_per_epoch=2)
            got.append([_batch_bytes(next(it)) for _ in range(2)])
            c.close()
        assert got[0] != got[1]  # distinct shards, distinct bytes
        for i, spec in enumerate(specs):
            local = build_source(spec).batches(batches_per_epoch=2)
            assert got[i] == [_batch_bytes(next(local)) for _ in range(2)]


# --- refusals over the wire ------------------------------------------------


class TestCursorRefusalsOverTheWire:
    def _attach(self, svc, spec, job="refuse"):
        sock = socket.create_connection(
            ("127.0.0.1", svc.port), timeout=5
        )
        service_lib.send_frame(sock, {
            "op": "hello", "job": job, "shard": [0, 1], "spec": spec,
        })
        resp, _ = service_lib.recv_frame(sock)
        assert resp["ok"]
        return sock

    def _next(self, sock, job, cursor):
        service_lib.send_frame(sock, {
            "op": "next", "job": job, "shard": [0, 1], "cursor": cursor,
        })
        resp, _ = service_lib.recv_frame(sock)
        return resp

    def test_foreign_format_wrong_kind_wrong_geometry_all_refused(
        self, corpus, svc
    ):
        spec = _spec(corpus, batch=8)
        good = build_source(spec).stream_cursor(
            0, 0, batches_per_epoch=4
        ).to_dict()
        sock = self._attach(svc, spec)
        try:
            assert _refusals(svc) == 0  # pre-seeded, not absent
            foreign = dict(good, format=99)
            wrong_kind = dict(good, kind="file")
            wrong_geometry = build_source(
                _spec(corpus, batch=4)
            ).stream_cursor(0, 0, batches_per_epoch=4).to_dict()
            for i, cursor in enumerate(
                [foreign, wrong_kind, wrong_geometry], start=1
            ):
                resp = self._next(sock, "refuse", cursor)
                assert resp["ok"] is False
                assert resp["refusal"] is True
                assert _refusals(svc) == i
            # The connection SURVIVES a refusal — the good cursor still
            # serves on it (refusal is a verdict, not a transport error).
            resp = self._next(sock, "refuse", good)
            assert resp["ok"] is True
        finally:
            sock.close()

    def test_client_raises_refusals_without_spending_retries(
        self, corpus, svc, fast_retries
    ):
        # Admit batch_size=8; present cursors from a batch_size=4 source:
        # geometry refusal, surfaced as StreamCursorError, NOT retried.
        client = ServiceClient(
            build_source(_spec(corpus, batch=4)), _spec(corpus, batch=8),
            job="georefuse", address=svc.address,
        )
        before = dict(stream_lib.RETRY_STATS)
        with pytest.raises(stream_lib.StreamCursorError, match="refused"):
            next(client.batches(batches_per_epoch=4))
        assert stream_lib.RETRY_STATS == before  # no retry spent
        client.close()

    def test_first_admission_requires_a_spec(self, corpus, svc):
        client = ServiceClient(
            build_source(_spec(corpus)), None, job="specless",
            address=svc.address,
        )
        with pytest.raises(ValueError, match="spec"):
            next(client.batches(batches_per_epoch=4))
        client.close()


# --- journal recovery ------------------------------------------------------


class TestJournalRecovery:
    def test_restarted_dispatcher_adopts_and_serves_specless_reattach(
        self, corpus, tmp_path, fast_retries
    ):
        root = str(tmp_path / "svc")
        spec = _spec(corpus)
        svc1 = DataService(root).start()
        client = ServiceClient(
            build_source(spec), spec, job="durable", address=svc1.address
        )
        it = client.batches(batches_per_epoch=4)
        first = [_batch_bytes(next(it)) for _ in range(2)]
        port = svc1.port
        svc1.stop()  # indistinguishable from SIGKILL at the socket layer

        svc2 = DataService(root, port=port).start()
        try:
            # The journal recorded the admission; the restarted instance
            # journals its adoption...
            with open(svc2.journal_path) as f:
                names = [json.loads(l)["name"] for l in f if l.strip()]
            assert "recover" in names
            # ...and the client's NEXT fetch rides a retry through a
            # reconnect + SPEC-LESS hello (`_ever_admitted` is set) — the
            # recovery proof — continuing the stream byte-exactly.
            assert client._ever_admitted
            client.spec = None  # a re-attach hello must not need it
            more = [_batch_bytes(next(it)) for _ in range(2)]
            local = build_source(spec).batches(batches_per_epoch=4)
            want = [_batch_bytes(next(local)) for _ in range(4)]
            assert first + more == want
            assert client.events == []  # absorbed by retries, no degrade
        finally:
            svc2.stop()
            client.close()

    def test_fresh_dispatcher_does_not_adopt_unknown_jobs(
        self, corpus, tmp_path, fast_retries
    ):
        # A dispatcher with a DIFFERENT (empty) journal must refuse to
        # guess: the spec-less hello errors, the client's budget drains,
        # and it degrades to local rather than forking the stream.
        svc = DataService(str(tmp_path / "other")).start()
        spec = _spec(corpus)
        client = ServiceClient(
            build_source(spec), spec, job="ghost", address=svc.address
        )
        client._ever_admitted = True  # simulate a pre-crash admission
        client.spec = None
        try:
            it = client.batches(batches_per_epoch=4)
            batch = next(it)  # degraded, still correct bytes
            local = build_source(spec).batches(batches_per_epoch=4)
            assert _batch_bytes(batch) == _batch_bytes(next(local))
            assert [e["event"] for e in client.events] == ["degrade"]
        finally:
            svc.stop()
            client.close()


# --- the degrade → local → re-attach arc -----------------------------------


class TestDegradeAndReattach:
    def test_outage_degrades_byte_identically_and_reattaches(
        self, corpus, tmp_path, fast_retries
    ):
        root = str(tmp_path / "svc")
        spec = _spec(corpus)
        B = 3
        svc = DataService(root).start()
        port = svc.port
        client = ServiceClient(
            build_source(spec), spec, job="arc", address=svc.address
        )
        it = client.batches(batches_per_epoch=B)
        control = build_source(spec).batches(batches_per_epoch=B)

        got = [_batch_bytes(next(it)) for _ in range(2)]  # served
        svc.stop()
        # Budget (1 retry) drains on the outage → degrade; the stream
        # continues LOCALLY from the same cursor, byte-identically.
        got += [_batch_bytes(next(it)) for _ in range(B)]
        assert [e["event"] for e in client.events] == ["degrade"]
        # Restart on the SAME dir + port; the next epoch BOUNDARY
        # re-attaches (mid-epoch stays local — order never forks).
        svc2 = DataService(root, port=port).start()
        try:
            got += [_batch_bytes(next(it)) for _ in range(2 * B)]
            events = [e["event"] for e in client.events]
            assert events == ["degrade", "reattach"]
            assert client.events[1]["epoch"] >= 1
            want = [_batch_bytes(next(control)) for _ in range(len(got))]
            assert got == want
        finally:
            svc2.stop()
            client.close()

    def test_unset_service_is_pure_local_passthrough(
        self, corpus, monkeypatch
    ):
        monkeypatch.delenv("HVT_DATA_SERVICE", raising=False)
        spec = _spec(corpus)
        client = ServiceClient(build_source(spec), spec, job="local")
        assert client.address is None
        it = client.batches(batches_per_epoch=4)
        local = build_source(spec).batches(batches_per_epoch=4)
        for _ in range(5):
            assert _batch_bytes(next(it)) == _batch_bytes(next(local))
        assert client.events == []


# --- per-job isolation -----------------------------------------------------


class _WedgedSource:
    """A source whose stream blocks on a gate INSIDE the dispatcher's
    serving path — the pathological job of the isolation unit."""

    def __init__(self):
        self.gate = threading.Event()

    def batches_from(self, cursor):
        def gen():
            self.gate.wait()
            while True:
                yield (np.zeros((2, 2), np.float32),)

        return gen()


class TestPerJobIsolation:
    def test_wedged_job_never_delays_another_jobs_serving(
        self, corpus, svc
    ):
        wedge = _WedgedSource()
        svc.register_local("wedged", (0, 1), wedge)
        cursor = stream_lib.StreamCursor(
            kind="array", seed=0, epoch=0, step=0, position={}
        ).to_dict()

        wedged_done = threading.Event()

        def fetch_wedged():
            sock = socket.create_connection(
                ("127.0.0.1", svc.port), timeout=60
            )
            try:
                service_lib.send_frame(sock, {
                    "op": "next", "job": "wedged", "shard": [0, 1],
                    "cursor": cursor,
                })
                resp, _ = service_lib.recv_frame(sock)
                if resp and resp.get("ok"):
                    wedged_done.set()
            finally:
                sock.close()

        t = threading.Thread(target=fetch_wedged, daemon=True)
        t.start()
        # The wedged request is now parked inside job A's serving path.
        time.sleep(0.2)
        assert not wedged_done.is_set()

        # Job B — admission AND serving — completes promptly regardless.
        spec = _spec(corpus)
        client = ServiceClient(
            build_source(spec), spec, job="brisk", address=svc.address
        )
        start = time.monotonic()
        batch = next(client.batches(batches_per_epoch=4))
        elapsed = time.monotonic() - start
        client.close()
        assert elapsed < 5.0
        local = build_source(spec).batches(batches_per_epoch=4)
        assert _batch_bytes(batch) == _batch_bytes(next(local))
        assert not wedged_done.is_set()  # A is still parked...

        wedge.gate.set()  # ...and completes once its own job unwedges
        t.join(timeout=10)
        assert wedged_done.is_set()


# --- the netdrop / dataslow fault kinds ------------------------------------


class TestDataFaultKinds:
    def test_parse_plan_accepts_both_kinds(self):
        from horovod_tpu.testing import faults

        plan = faults.parse_plan("1:2:netdrop:50")
        assert (plan.rank, plan.epoch) == (1, 2)
        assert plan.netdrop_ms == 50.0
        assert plan.dataslow_ms is None
        plan = faults.parse_plan("0:3:dataslow:25")
        assert plan.dataslow_ms == 25.0
        assert plan.netdrop_ms is None
        with pytest.raises(ValueError, match="netdrop"):
            faults.parse_plan("0:1:netdrop:nope")
        with pytest.raises(ValueError, match="dataslow:MS"):
            faults.parse_plan("0:1:sever")

    def test_netdrop_window_is_the_target_epoch_only(self, monkeypatch):
        from horovod_tpu.testing import faults

        monkeypatch.setenv("HVT_FAULT", "1:2:netdrop:40")
        monkeypatch.delenv("HVT_FAULT_STAMP", raising=False)
        ms = faults.data_fault_ms
        assert ms("netdrop", epoch=2, rank=1) == 40.0
        assert ms("netdrop", epoch=2, rank=1) == 40.0  # stamp-less: recurs
        assert ms("netdrop", epoch=1, rank=1) is None  # before the window
        assert ms("netdrop", epoch=3, rank=1) is None  # bounded brownout
        assert ms("netdrop", epoch=2, rank=0) is None  # other rank
        assert ms("dataslow", epoch=2, rank=1) is None  # other kind

    def test_netdrop_stamp_makes_it_one_shot(self, tmp_path, monkeypatch):
        from horovod_tpu.testing import faults

        monkeypatch.setenv("HVT_FAULT", "0:1:netdrop:10")
        monkeypatch.setenv("HVT_FAULT_STAMP", str(tmp_path / "stamp"))
        assert faults.data_fault_ms("netdrop", epoch=1, rank=0) == 10.0
        assert faults.data_fault_ms("netdrop", epoch=1, rank=0) is None

    def test_dataslow_fires_from_target_epoch_on(self, monkeypatch):
        from horovod_tpu.testing import faults

        monkeypatch.setenv("HVT_FAULT", "0:2:dataslow:15")
        ms = faults.data_fault_ms
        assert ms("dataslow", epoch=1, rank=0) is None
        assert ms("dataslow", epoch=2, rank=0) == 15.0
        assert ms("dataslow", epoch=9, rank=0) == 15.0  # a rate, like slow

    def test_unset_or_foreign_plan_is_no_fault(self, monkeypatch):
        from horovod_tpu.testing import faults

        monkeypatch.delenv("HVT_FAULT", raising=False)
        assert faults.data_fault_ms("netdrop", epoch=0) is None
        monkeypatch.setenv("HVT_FAULT", "0:1:kill")
        assert faults.data_fault_ms("netdrop", epoch=1, rank=0) is None
        with pytest.raises(ValueError, match="netdrop or dataslow"):
            faults.data_fault_ms("kill", epoch=1)

    def test_client_netdrop_drops_the_connection_during_the_epoch(
        self, corpus, svc, monkeypatch
    ):
        monkeypatch.setenv("HVT_FAULT", "0:1:netdrop:1")
        monkeypatch.delenv("HVT_FAULT_STAMP", raising=False)
        monkeypatch.setenv("HVT_DATA_RETRIES", "4")
        monkeypatch.setenv("HVT_DATA_BACKOFF_S", "0.001")
        spec = _spec(corpus)
        client = ServiceClient(
            build_source(spec), spec, job="dropjob", shard=(0, 1),
            address=svc.address,
        )
        before = stream_lib.RETRY_STATS["retried"]
        it = client.batches(batches_per_epoch=2)
        control = build_source(spec).batches(batches_per_epoch=2)
        # Epoch 0 serves cleanly; EVERY epoch-1 fetch hits the injected
        # drop and retries also hit it → budget drains → degrade → local,
        # byte-identical; epoch 2 re-attaches (the window closed).
        got = [_batch_bytes(next(it)) for _ in range(6)]
        want = [_batch_bytes(next(control)) for _ in range(6)]
        assert got == want
        assert stream_lib.RETRY_STATS["retried"] > before
        events = [e["event"] for e in client.events]
        assert events == ["degrade", "reattach"]
        assert client.events[0]["epoch"] == 1
        assert client.events[1]["epoch"] == 2
        client.close()


# --- observability ---------------------------------------------------------


class TestObservability:
    def test_retry_outcome_collector_mirrors_stream_stats(self):
        from horovod_tpu.obs import core as obs_core
        from horovod_tpu.obs.server import _retry_collector

        reg = obs_core.Registry()
        reg.register_collector(_retry_collector)
        saved = dict(stream_lib.RETRY_STATS)
        try:
            stream_lib.RETRY_STATS["retried"] = 7
            stream_lib.RETRY_STATS["exhausted"] = 2
            values = obs_prom.parse_text(obs_prom.render(reg))
            assert values['hvt_data_retries_total{outcome="retried"}'] == 7
            assert (
                values['hvt_data_retries_total{outcome="exhausted"}'] == 2
            )
        finally:
            stream_lib.RETRY_STATS.update(saved)

    def test_dispatcher_metrics_series(self, corpus, svc):
        spec = _spec(corpus)
        client = ServiceClient(
            build_source(spec), spec, job="metered", address=svc.address
        )
        it = client.batches(batches_per_epoch=4)
        for _ in range(3):
            next(it)
        client.close()
        values = obs_prom.parse_text(obs_prom.render(svc.registry))
        assert values['hvt_data_batches_served_total{job="metered"}'] == 3
        assert values['hvt_data_admissions_total{job="metered"}'] == 1
        assert values["hvt_data_cursor_refusals_total"] == 0
        assert values["hvt_data_jobs"] >= 1

    def test_metrics_server_serves_healthz_and_series(
        self, corpus, tmp_path
    ):
        import urllib.request

        svc = DataService(str(tmp_path / "m"), metrics_port=0).start()
        try:
            base = f"http://127.0.0.1:{svc.metrics_port}"
            with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
                assert r.status == 200
            with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
                values = obs_prom.parse_text(r.read().decode())
            assert values["hvt_data_cursor_refusals_total"] == 0
        finally:
            svc.stop()


# --- fleet spec plumbing ---------------------------------------------------


class TestFleetDataServiceSpec:
    SPEC = os.path.join(REPO, "horovod_tpu", "launch", "jobs",
                        "fleet-shared-data-2job.yaml")

    def test_shipped_shared_data_fleet_spec_loads(self):
        import yaml

        from horovod_tpu.launch import fleetd

        with open(self.SPEC) as f:
            spec = yaml.safe_load(f)
        cfg, entries = fleetd.load_entries(spec)
        assert cfg["data_service"]["dir"].endswith("data-service")
        assert sorted(e.name for e in entries) == ["alpha", "beta"]
        jobs = {e.name: e for e in entries}
        assert {jobs[n].env["HVT_DATA_JOB"] for n in jobs} == {
            "alpha", "beta"
        }
        mc = spec["metrics_checks"]
        assert 'hvt_data_batches_served_total{job="alpha"}' in mc
        assert 'hvt_data_batches_served_total{job="beta"}' in mc
        assert mc["hvt_data_cursor_refusals_total"]["target"] == "0..0"

    def test_data_service_must_be_a_mapping(self):
        from horovod_tpu.launch import fleetd

        spec = {
            "fleet": {"pool": {"h0": {"slots": 1}},
                      "data_service": "yes please"},
            "jobs": [{"name": "j", "job": {
                "command": "true",
                "env": {"PS_MODEL_PATH": "/tmp/x"},
            }}],
        }
        with pytest.raises(ValueError, match="data_service"):
            fleetd.load_entries(spec)

    def test_fleetd_injects_service_address_into_job_envs(
        self, corpus, tmp_path, monkeypatch
    ):
        import yaml

        from horovod_tpu.launch import fleetd

        with open(self.SPEC) as f:
            text = f.read()
        assert "/tmp/hvt-fleet-data" in text  # the relocatable paths
        spec = yaml.safe_load(
            text.replace("/tmp/hvt-fleet-data", str(tmp_path))
        )
        daemon = fleetd.Fleetd(spec, verbose=False)
        from horovod_tpu.launch import supervisor

        daemon.log = supervisor.RestartLog(daemon.journal_path)
        os.makedirs(daemon.fleet_dir, exist_ok=True)
        daemon._start_data_service(recovered=False)
        try:
            addr = f"127.0.0.1:{daemon.data_port}"
            for st in daemon.jobs.values():
                e = st["entry"]
                assert e.env["HVT_DATA_SERVICE"] == addr
                assert e.spec["job"]["env"]["HVT_DATA_SERVICE"] == addr
            # The address is journaled for same-port restart on recovery.
            with open(daemon.journal_path) as f:
                recs = [json.loads(l) for l in f if l.strip()]
            ds = [r for r in recs if r.get("name") == "data_service"]
            assert ds and ds[0]["port"] == daemon.data_port
            # And the dispatcher is really up: gate PASSES on a live
            # scrape once a served batch lands for each gated job.
            for jobname in ("alpha", "beta"):
                s = _spec(corpus)
                c = ServiceClient(
                    build_source(s), s, job=jobname, address=addr
                )
                next(c.batches(batches_per_epoch=2))
                c.close()
            assert daemon._data_gates() is True
            assert os.path.exists(
                os.path.join(daemon.fleet_dir, "data-metrics.prom")
            )
        finally:
            daemon._stop_data_service()
        # With the dispatcher gone and the dump removed, the gate FAILS
        # loudly instead of passing vacuously.
        os.remove(os.path.join(daemon.fleet_dir, "data-metrics.prom"))
        assert daemon._data_gates() is False
