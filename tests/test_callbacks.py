"""Callback semantics: warmup schedule, checkpoint writing, logger, ordering."""

import json
import os

import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.models import MnistCNN
from horovod_tpu.training.callbacks import (
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    ModelCheckpoint,
    ScalarLogger,
)


class _Recorder:
    """Minimal trainer stand-in for schedule-only tests."""

    update_scale = 1.0


def test_warmup_schedule_matches_reference_ramp():
    """lr ramps base -> base×size over 3 epochs (tensorflow2_keras_mnist.py:78-82).
    Optimizer holds base×size, so scale must go 1/size -> 1."""
    cb = LearningRateWarmupCallback(warmup_epochs=3, world_size=8)
    t = _Recorder()
    cb.trainer = t
    scales = []
    for epoch in range(5):
        cb.on_epoch_begin(epoch)
        scales.append(t.update_scale)
    assert scales[0] == pytest.approx(1 / 8)  # epoch 0: base lr
    assert scales[1] < scales[2] < 1.0  # monotonic ramp
    assert scales[3] == scales[4] == 1.0  # post-warmup: full scaled lr


def test_warmup_noop_at_world_size_one():
    cb = LearningRateWarmupCallback(warmup_epochs=3, world_size=1)
    t = _Recorder()
    cb.trainer = t
    cb.on_epoch_begin(0)
    assert t.update_scale == 1.0


def test_lr_schedule_constant_and_callable():
    """hvd.callbacks.LearningRateScheduleCallback parity: float or
    epoch->float multiplier, active only within [start_epoch, end_epoch)."""
    t = _Recorder()
    cb = LearningRateScheduleCallback(0.1, start_epoch=2, end_epoch=4)
    cb.trainer = t
    for epoch, expected in [(0, 1.0), (1, 1.0), (2, 0.1), (4, 1.0)]:
        t.update_scale = 1.0  # the Trainer resets each epoch
        cb.on_epoch_begin(epoch)
        assert t.update_scale == pytest.approx(expected), epoch

    cb = LearningRateScheduleCallback(lambda e: 0.5 ** e)
    cb.trainer = t
    t.update_scale = 1.0
    cb.on_epoch_begin(3)
    assert t.update_scale == pytest.approx(0.125)


def test_lr_schedule_stacks_with_warmup():
    """Horovod's documented stacking: warmup first, then decay schedules
    with later start_epoch — composes in callback-list order because
    warmup assigns and schedules multiply."""
    t = _Recorder()
    warmup = LearningRateWarmupCallback(warmup_epochs=3, world_size=8)
    decay = LearningRateScheduleCallback(0.1, start_epoch=5)
    warmup.trainer = decay.trainer = t
    seen = {}
    for epoch in range(7):
        t.update_scale = 1.0
        warmup.on_epoch_begin(epoch)
        decay.on_epoch_begin(epoch)
        seen[epoch] = t.update_scale
    assert seen[0] == pytest.approx(1 / 8)  # warmup ramp start
    assert seen[3] == seen[4] == 1.0  # between warmup and decay
    assert seen[5] == seen[6] == pytest.approx(0.1)  # decayed


def test_lr_schedule_drives_training_scale():
    """End-to-end through Trainer.fit: a zero multiplier freezes params
    (the update_scale plumbing, reset each epoch)."""
    import jax

    hvt.init()
    rng = np.random.RandomState(1)
    x = rng.rand(16, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 16).astype(np.int64)
    trainer = hvt.Trainer(MnistCNN(), hvt.DistributedOptimizer(optax.adam(1e-2)))
    trainer.build(x)
    before = jax.device_get(trainer.state.params)
    trainer.fit(
        x=x, y=y, batch_size=2, epochs=1,
        callbacks=[LearningRateScheduleCallback(0.0)],
    )
    after = jax.device_get(trainer.state.params)
    assert all(
        np.allclose(a, b)
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after))
    )


def test_metric_average_single_process_identity():
    cb = MetricAverageCallback()
    logs = {"loss": 0.25, "accuracy": 0.75}
    cb.on_epoch_end(0, logs)
    assert logs == {"loss": 0.25, "accuracy": 0.75}


def test_broadcast_callback_single_process_noop():
    hvt.init()
    x = np.random.RandomState(0).rand(16, 28, 28, 1).astype(np.float32)
    trainer = hvt.Trainer(MnistCNN(), optax.adam(1e-3))
    trainer.build(x)
    cb = BroadcastGlobalVariablesCallback(0)
    cb.set_trainer(trainer)
    cb.on_train_begin()  # must not raise / must keep state intact
    assert trainer.state is not None


def test_model_checkpoint_writes_per_epoch(tmp_path):
    hvt.init()
    rng = np.random.RandomState(0)
    x = rng.rand(32, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 32).astype(np.int64)
    trainer = hvt.Trainer(MnistCNN(), optax.adam(1e-3))
    template = str(tmp_path / "checkpoint-{epoch}.msgpack")
    trainer.fit(x=x, y=y, batch_size=4, epochs=2,
                callbacks=[ModelCheckpoint(template)])
    assert os.path.exists(tmp_path / "checkpoint-1.msgpack")
    assert os.path.exists(tmp_path / "checkpoint-2.msgpack")


def test_scalar_logger_writes_jsonl(tmp_path):
    hvt.init()
    cb = ScalarLogger(str(tmp_path), update_freq="epoch")
    cb.on_epoch_end(0, {"loss": 0.5, "accuracy": 0.8})
    cb.on_train_end()
    lines = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    assert lines[0]["epoch/loss"] == 0.5
    assert lines[0]["step"] == 1


def test_full_reference_callback_stack_runs():
    """The TF2 script's exact callback list (tensorflow2_keras_mnist.py:67-92)
    wired through a real fit."""
    hvt.init()
    rng = np.random.RandomState(1)
    x = rng.rand(64, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int64)
    trainer = hvt.Trainer(
        MnistCNN(),
        hvt.DistributedOptimizer(optax.adam(hvt.scale_lr(0.001))),
    )
    cbs = [
        BroadcastGlobalVariablesCallback(0),
        MetricAverageCallback(),
        LearningRateWarmupCallback(warmup_epochs=3),
    ]
    hist = trainer.fit(x=x, y=y, batch_size=4, epochs=4, callbacks=cbs)
    assert len(hist) == 4
    # after warmup the scale must be back to 1.0
    assert trainer.update_scale == 1.0


class TestExponentialMovingAverage:
    def _fit(self, cb_list, steps=4):
        import flax.linen as nn

        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                return nn.Dense(3)(x)

        trainer = hvt.Trainer(
            Tiny(), hvt.DistributedOptimizer(optax.sgd(0.5)),
            loss="sparse_categorical_crossentropy",
        )
        rng = np.random.RandomState(0)
        x = rng.rand(32 * steps, 5).astype(np.float32)
        y = rng.randint(0, 3, size=(32 * steps,)).astype(np.int32)
        trainer.fit(x=x, y=y, epochs=1, batch_size=32, callbacks=cb_list, verbose=0)
        return trainer

    def test_exact_math(self):
        """Shadow starts AT the initial params; per-execution recurrence
        ema_t = d*ema_{t-1} + (1-d)*p_t, verified in numpy leaf-wise."""
        from horovod_tpu.training.callbacks import (
            Callback,
            ExponentialMovingAverage,
        )
        import jax

        seen = []

        class Recorder(Callback):
            def on_train_begin(self, logs=None):
                seen.append(jax.device_get(self.trainer.state.params))

            def on_batch_end(self, batch, logs=None):
                seen.append(jax.device_get(self.trainer.state.params))

        d = 0.5
        ema = ExponentialMovingAverage(decay=d)
        self._fit([Recorder(), ema], steps=4)
        expect = seen[0]  # p_init
        for p in seen[1:]:
            expect = jax.tree.map(lambda a, b: d * a + (1 - d) * b, expect, p)
        got = jax.device_get(ema.ema_params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            expect, got,
        )

    def test_exact_math_zero_debias(self):
        from horovod_tpu.training.callbacks import (
            Callback,
            ExponentialMovingAverage,
        )
        import jax

        seen = []

        class Recorder(Callback):
            def on_batch_end(self, batch, logs=None):
                seen.append(jax.device_get(self.trainer.state.params))

        d = 0.5
        ema = ExponentialMovingAverage(decay=d, zero_debias=True)
        trainer = self._fit([Recorder(), ema], steps=4)
        # Zero-init shadow has the closed form:
        # ema_t = (1-d) * sum_i d^(t-i) p_i ; debiased by (1 - d^t).
        t = len(seen)
        expect = None
        for i, p in enumerate(seen, start=1):
            w = (1 - d) * d ** (t - i)
            expect = jax.tree.map(
                lambda a, b=None: w * a if expect is None else None, p
            ) if expect is None else jax.tree.map(
                lambda acc, a: acc + w * a, expect, p
            )
        corr = 1 - d ** t
        expect = jax.tree.map(lambda a: a / corr, expect)
        got = jax.device_get(ema.ema_params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            expect, got,
        )

    def test_averaged_swaps_and_restores(self):
        from horovod_tpu.training.callbacks import ExponentialMovingAverage
        import jax

        ema = ExponentialMovingAverage(decay=0.9)
        trainer = self._fit([ema], steps=3)
        live = jax.device_get(trainer.state.params)
        avg = jax.device_get(ema.ema_params)
        with ema.averaged(trainer):
            inside = jax.device_get(trainer.state.params)
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(a, b), inside, avg
            )
        after = jax.device_get(trainer.state.params)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b), after, live
        )

    def test_decay_validation(self):
        from horovod_tpu.training.callbacks import ExponentialMovingAverage

        with pytest.raises(ValueError):
            ExponentialMovingAverage(decay=1.0)
        with pytest.raises(ValueError):
            ExponentialMovingAverage(decay=0.0)

    def test_ema_read_survives_continued_training(self):
        """ema_params must return FRESH buffers: the next update donates
        the shadow, so a returned live reference would be deleted."""
        from horovod_tpu.training.callbacks import ExponentialMovingAverage
        import jax

        ema = ExponentialMovingAverage(decay=0.9)
        trainer = self._fit([ema], steps=2)
        held = ema.ema_params
        # Continue training with the same callback: shadow buffers donate.
        rng = np.random.RandomState(1)
        x = rng.rand(64, 5).astype(np.float32)
        y = rng.randint(0, 3, size=(64,)).astype(np.int32)
        trainer.fit(x=x, y=y, epochs=1, batch_size=32, callbacks=[ema], verbose=0)
        # The earlier read is still alive and fetchable.
        jax.tree.map(lambda a: np.asarray(a), held)

    def test_ema_checkpoint_roundtrip(self, tmp_path):
        """With checkpoint_dir set, the shadow persists across a restart —
        a fresh callback (new process, restored model) resumes the SAME
        running average instead of restarting it from the live weights."""
        from horovod_tpu.training.callbacks import ExponentialMovingAverage
        import jax

        d = str(tmp_path)
        ema = ExponentialMovingAverage(decay=0.7, checkpoint_dir=d)
        trainer = self._fit([ema], steps=3)
        saved = jax.device_get(ema.ema_params)
        count = ema._count
        assert (tmp_path / "ema.msgpack").exists()

        ema2 = ExponentialMovingAverage(decay=0.7, checkpoint_dir=d)
        ema2.set_trainer(trainer)
        ema2.on_train_begin()
        assert ema2._count == count
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            jax.device_get(ema2.ema_params), saved,
        )

    def test_ema_restore_incompatible_file_raises(self, tmp_path):
        """A stale/incompatible ema.msgpack raises a clear error instead of
        restoring garbage (and on a pod, instead of stranding non-primary
        ranks in the broadcast)."""
        from horovod_tpu.training.callbacks import ExponentialMovingAverage

        (tmp_path / "ema.msgpack").write_bytes(b"not msgpack at all")
        ema = ExponentialMovingAverage(decay=0.9, checkpoint_dir=str(tmp_path))
        trainer = self._fit([], steps=1)
        ema.set_trainer(trainer)
        with pytest.raises(RuntimeError, match="EMA shadow restore failed"):
            ema.on_train_begin()


@pytest.mark.slow
class TestEMAShardedLayouts:
    """EMA durability under model-parallel layouts (VERDICT Weak #5): the
    shadow carries the params' shardings, and its persistence follows the
    layout — single-host TP/FSDP through the single-file path, ZeRO-1
    (shard_update) likewise; the cross-process sharded-directory format is
    exercised in tests/test_multiprocess.py."""

    def _lm_trainer(self, mesh, **kw):
        from horovod_tpu.models.transformer import (
            TransformerLM, param_specs,
        )
        from jax.sharding import PartitionSpec as P

        return hvt.Trainer(
            TransformerLM(
                vocab_size=32, d_model=32, n_heads=4, n_layers=2,
                dropout=0.0,
            ),
            hvt.DistributedOptimizer(optax.adam(1e-2)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=param_specs,
            batch_specs=(P(("data", "fsdp")), P(("data", "fsdp"))),
            **kw,
        )

    def _tokens(self, n=32, t=16):
        rng = np.random.RandomState(0)
        x = rng.randint(1, 32, size=(n, t)).astype(np.int32)
        return x, np.roll(x, -1, axis=1).astype(np.int32)

    def test_roundtrip_under_fsdp_tp(self, tmp_path):
        import jax

        from horovod_tpu.parallel import mesh as mesh_lib
        from horovod_tpu.training.callbacks import ExponentialMovingAverage

        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, fsdp=2, model=2)
        )
        d = str(tmp_path)
        ema = ExponentialMovingAverage(decay=0.8, checkpoint_dir=d)
        trainer = self._lm_trainer(mesh)
        x, y = self._tokens()
        trainer.fit(
            x=x, y=y, epochs=2, batch_size=8, callbacks=[ema], verbose=0
        )
        saved = jax.device_get(ema.ema_params)
        count = ema._count
        assert count > 0
        assert (tmp_path / "ema.msgpack").exists()

        ema2 = ExponentialMovingAverage(decay=0.8, checkpoint_dir=d)
        ema2.set_trainer(trainer)
        ema2.on_train_begin()
        assert ema2._count == count
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
            jax.device_get(ema2.ema_params), saved,
        )
        # The restored shadow carries the params' shardings, so the next
        # donated update composes (and actually runs).
        for p, e in zip(
            jax.tree.leaves(trainer.state.params),
            jax.tree.leaves(ema2._ema),
        ):
            assert p.sharding == e.sharding, (p.sharding, e.sharding)
        ema2.on_batch_end(0)
        assert ema2._count == count + 1

    def test_roundtrip_under_shard_update(self, tmp_path):
        import jax

        from horovod_tpu.training.callbacks import ExponentialMovingAverage

        import flax.linen as nn

        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                return nn.Dense(8)(nn.relu(nn.Dense(16)(x)))

        d = str(tmp_path)
        trainer = hvt.Trainer(
            Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)),
            loss="sparse_categorical_crossentropy",
            shard_update=True,
        )
        rng = np.random.RandomState(1)
        x = rng.rand(64, 12).astype(np.float32)
        y = rng.randint(0, 8, size=(64,)).astype(np.int32)
        ema = ExponentialMovingAverage(decay=0.9, checkpoint_dir=d)
        trainer.fit(
            x=x, y=y, epochs=2, batch_size=8, callbacks=[ema], verbose=0
        )
        saved = jax.device_get(ema.ema_params)
        count = ema._count
        assert count > 0

        ema2 = ExponentialMovingAverage(decay=0.9, checkpoint_dir=d)
        ema2.set_trainer(trainer)
        ema2.on_train_begin()
        assert ema2._count == count
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
            jax.device_get(ema2.ema_params), saved,
        )
        ema2.on_batch_end(0)
        assert ema2._count == count + 1
