"""Shared honest-timing helper for the on-chip benchmark scripts.

One fused `lax.scan` chains N iterations of a step function with a carried
perturbation; the clock stops only after fetching a scalar that
data-depends on the whole chain. Two hazards this guards against on
tunneled TPU runtimes (measured, see BASELINE.md "Measurement
methodology"):

* dispatch-loop timing: `block_until_ready` on chained dispatches can
  return before the device finished — hence ONE compiled scan + a value
  fetch;
* XLA optimizing the chain away: a `0 * out` perturbation gets folded to
  0, the carry becomes loop-invariant, and LICM hoists the body out of the
  loop (a "305 TFLOP/s matmul" on a 197-peak chip); linear functionals of
  a matmul (slices, sums) get rewritten into contractions of the operands
  — consume outputs nonlinearly and fold with a tiny-but-NONZERO factor.

The residual bias is one tunnel round-trip over the whole chain (~RTT/N);
min-of-`repeats` filters RTT spikes. Two-point slope timing between chain
lengths was tried and rejected: RTT jitter between runs exceeds the
per-step work difference.
"""

from __future__ import annotations

import time

import jax


def timed_chain(step, x0, *, steps: int, repeats: int = 3) -> float:
    """Seconds per iteration of ``step`` (carry -> device scalar)."""

    def body(carry, _):
        out_scalar = step(carry)
        eps = (1.0 + 1e-30 * out_scalar).astype(carry.dtype)
        return carry * eps, out_scalar

    @jax.jit
    def run(x):
        carry, outs = jax.lax.scan(body, x, None, length=steps)
        return outs[-1] + 0.0 * carry.sum()

    float(jax.device_get(run(x0)))  # compile + warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(jax.device_get(run(x0)))
        best = min(best, time.perf_counter() - t0)
    return best / steps
