"""Conv-family train-step attribution on-chip (not part of the test suite).

What `lm_profile.py` does for the transformer, for the CNN families: times
nested subsets of the MNIST-CNN and ResNet-20 train steps (forward /
forward+backward / +optimizer+BN / the device-resident input gather), an
op-size ceiling comparison (each model's dominant ops in isolation vs an
MXU-saturating matmul), and a per-chip batch sweep — the evidence behind
BASELINE.md's conv attribution note.

Timing is `_timing.timed_chain` (one fused scan, min-of-3, nonzero carry
perturbation); see that module's docstring for the hazards it guards.

Usage: python benchmarks/conv_profile.py [mnist|resnet|gather|ceiling|sweep ...]
Env: CVP_N=512  CVP_BATCH=128
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from _timing import timed_chain

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Chains must amortize the tunnel RTT (~50-100 ms observed): at N=64 a
# sub-ms op reads as "1.2 ms" of pure round-trip. 512 keeps the floor
# under ~0.2 ms; raise further for sub-100us ops.
N = int(os.environ.get("CVP_N", 512))
BATCH = int(os.environ.get("CVP_BATCH", 128))


def _build(which, batch):
    if which == "resnet":
        from horovod_tpu.models.resnet import ResNetCIFAR

        model = ResNetCIFAR(depth=20, compute_dtype=jnp.bfloat16)
        x = jnp.asarray(
            np.random.RandomState(0).randint(0, 255, (batch, 32, 32, 3)),
            jnp.uint8,
        )
    else:
        from horovod_tpu.models.cnn import MnistCNN

        model = MnistCNN(compute_dtype=jnp.bfloat16)
        x = jnp.asarray(
            np.random.RandomState(0).randint(0, 255, (batch, 28, 28, 1)),
            jnp.uint8,
        )
    y = jnp.asarray(np.random.RandomState(1).randint(0, 10, batch), jnp.int32)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, train=False,
    )
    params = variables["params"]
    bn = {k: v for k, v in variables.items() if k != "params"}
    return model, params, bn, x, y


def _flops(model, params, bn, x, y):
    from horovod_tpu import trace

    def step(p):
        def loss(p):
            mut = list(bn.keys()) or False
            out = model.apply(
                {"params": p, **bn}, x, train=True,
                rngs={"dropout": jax.random.PRNGKey(0)},
                mutable=mut,
            )
            logits = out[0] if mut is not False else out
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y
            ).mean()

        return jax.grad(loss)(p)

    return trace.compiled_flops(jax.jit(step), params)


def profile_model(which):
    os.environ.setdefault("HVT_FAST_RNG", "1")
    model, params, bn, x, y = _build(which, BATCH)
    mutable = list(bn.keys())
    print(f"== {which} (batch {BATCH}) ==")
    x0 = jnp.float32(1.0)

    def perturbed(c):
        return (x + (1e-30 * c).astype(x.dtype)) % 255

    def fwd_loss(p, xi, train):
        mut = mutable if (train and mutable) else False
        out = model.apply(
            {"params": p, **bn}, xi, train=train,
            rngs={"dropout": jax.random.PRNGKey(0)},
            mutable=mut,
        )
        logits = out[0] if mut is not False else out
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y
        ).mean()

    s_f = timed_chain(lambda c: fwd_loss(params, perturbed(c), False), x0, steps=N)
    print(f"forward+loss (eval mode):   {s_f*1e3:.3f} ms")

    s_ft = timed_chain(lambda c: fwd_loss(params, perturbed(c), True), x0, steps=N)
    print(f"forward+loss (train, BN+dropout): {s_ft*1e3:.3f} ms")

    g = jax.grad(lambda p, xi: fwd_loss(p, xi, True))

    def bwd(c):
        gr = g(params, perturbed(c))
        return jax.tree.leaves(gr)[0].astype(jnp.float32).sum()

    s_b = timed_chain(bwd, x0, steps=N)
    print(f"forward+backward:           {s_b*1e3:.3f} ms")

    # full train step through the Trainer's own compiled path (adam + BN
    # threading + metric accumulation), batch preloaded — no input leg.
    import horovod_tpu as hvt
    from horovod_tpu.parallel import sharding as sharding_lib

    tr = hvt.Trainer(model, hvt.DistributedOptimizer(optax.adam(1e-3)))
    state = tr.build(np.asarray(x[: tr.dp_size]))
    batch = tr._shard((np.asarray(x), np.asarray(y)))
    acc = sharding_lib.replicate(tr.zero_metrics(), tr.mesh)
    import time as _time

    compiled = tr._train_chunk.lower(
        state,
        tuple(jnp.broadcast_to(b, (N,) + b.shape) for b in batch),
        jnp.float32(1.0), acc,
    ).compile()
    mega = tuple(jnp.broadcast_to(b, (N,) + b.shape) for b in batch)
    st, _, a = compiled(state, mega, jnp.float32(1.0), acc)
    float(jax.device_get(a["loss"]))
    best = float("inf")
    for _ in range(3):
        t0 = _time.perf_counter()
        st, _, a = compiled(st, mega, jnp.float32(1.0), acc)
        float(jax.device_get(a["loss"]))
        best = min(best, _time.perf_counter() - t0)
    s_full = best / N
    print(f"full step (fwd+bwd+adam):   {s_full*1e3:.3f} ms")

    fl = _flops(model, params, bn, x, y)
    if fl:
        from horovod_tpu import trace

        print(
            f"flops/step {fl/1e9:.2f} GF -> MFU at full step: "
            f"{trace.mfu(fl, s_full, 1):.3f}"
        )
    print(
        f"attribution: fwd {s_ft*1e3:.2f} | bwd {(s_b-s_ft)*1e3:.2f} | "
        f"opt+thread {(s_full-s_b)*1e3:.2f} ms"
    )
    return s_full


def profile_gather():
    """The device-resident epoch's input leg in isolation: per-step shard
    gather of `batch` rows from an HBM-resident [1, N, ...] dataset —
    round 2 measured it at 31% of the MNIST e2e step."""
    print("== input gather (device-cached epoch leg) ==")
    rng = np.random.RandomState(0)
    data = jnp.asarray(rng.rand(1, 60000, 28, 28, 1), jnp.float32)
    order = jnp.argsort(jax.random.uniform(jax.random.PRNGKey(0), (1, 60000)), axis=1)

    def gather_vmap(c):
        t = (c.astype(jnp.int32) % (data.shape[1] // BATCH))
        idx = jax.lax.dynamic_slice_in_dim(order, t * BATCH, BATCH, axis=1)
        out = jax.vmap(lambda rows, ii: rows[ii])(data, idx)
        return out.astype(jnp.float32).sum()

    s = timed_chain(gather_vmap, jnp.float32(1.0), steps=N)
    print(f"vmap row-gather [{BATCH}]: {s*1e3:.3f} ms")

    flat = data.reshape(60000, -1)

    def gather_flat(c):
        t = (c.astype(jnp.int32) % (data.shape[1] // BATCH))
        idx = jax.lax.dynamic_slice_in_dim(order[0], t * BATCH, BATCH, axis=0)
        out = jnp.take(flat, idx, axis=0)
        return out.astype(jnp.float32).sum()

    s = timed_chain(gather_flat, jnp.float32(1.0), steps=N)
    print(f"flat jnp.take  [{BATCH}]: {s*1e3:.3f} ms")

    data_u8 = (data * 255).astype(jnp.uint8)

    def gather_u8(c):
        t = (c.astype(jnp.int32) % (data.shape[1] // BATCH))
        idx = jax.lax.dynamic_slice_in_dim(order, t * BATCH, BATCH, axis=1)
        out = jax.vmap(lambda rows, ii: rows[ii])(data_u8, idx)
        return out.astype(jnp.float32).sum()

    s = timed_chain(gather_u8, jnp.float32(1.0), steps=N)
    print(f"vmap row-gather uint8 dataset [{BATCH}]: {s*1e3:.3f} ms "
          f"(4x smaller HBM reads)")

    def gather_vmap_flat(c):
        # The winner (now trainer.train_epoch's formulation): per-shard row
        # gather over FLATTENED trailing dims — a clean [N, F] row gather,
        # ~9x the multi-dim-trailing-shape gather at f32.
        t = (c.astype(jnp.int32) % (data.shape[1] // BATCH))
        idx = jax.lax.dynamic_slice_in_dim(order, t * BATCH, BATCH, axis=1)
        a2 = data.reshape(data.shape[0], data.shape[1], -1)
        out = jax.vmap(lambda rows, ii: jnp.take(rows, ii, axis=0))(a2, idx)
        return out.astype(jnp.float32).sum()

    s = timed_chain(gather_vmap_flat, jnp.float32(1.0), steps=N)
    print(f"vmap take over flattened [S,N,F] f32 [{BATCH}]: {s*1e3:.3f} ms "
          f"(trainer.train_epoch formulation)")


def profile_ceiling():
    """Op-size ceiling: the models' dominant ops in isolation vs a
    saturating matmul — how much of the gap is 'small ops cannot fill the
    MXU' vs 'our step wastes time'."""
    print("== op-size ceiling ==")

    def time_op(name, f, x0, flops):
        s = timed_chain(f, x0, steps=N)
        print(f"{name}: {s*1e3:.3f} ms  {flops/s/1e12:.1f} TFLOP/s")

    n = 4096
    m = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16) * 0.01
    time_op(
        f"matmul {n}^3 bf16 (ceiling)",
        lambda c: jnp.vdot(
            (y := jnp.dot((m * (1 + 1e-30 * c)).astype(jnp.bfloat16), m,
                          preferred_element_type=jnp.float32)), y
        ),
        jnp.float32(1.0),
        2.0 * n ** 3,
    )

    # MNIST CNN dominant op: conv 26x26x32 -> 24x24x64 at batch 128.
    xa = jax.random.normal(jax.random.PRNGKey(1), (BATCH, 26, 26, 32), jnp.bfloat16)
    ka = jax.random.normal(jax.random.PRNGKey(2), (3, 3, 32, 64), jnp.bfloat16)
    fl = 2.0 * BATCH * 24 * 24 * 64 * 3 * 3 * 32
    time_op(
        f"mnist conv2 3x3x32->64 @26^2 b{BATCH}",
        lambda c: (jax.lax.conv_general_dilated(
            (xa * (1 + 1e-30 * c)).astype(jnp.bfloat16), ka, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32) ** 2).sum(),
        jnp.float32(1.0), fl,
    )

    # ResNet-20 dominant op family: 3x3 conv at 32x32x16 and 8x8x64.
    for (hw, cin, cout) in ((32, 16, 16), (8, 64, 64)):
        xb = jax.random.normal(
            jax.random.PRNGKey(3), (BATCH, hw, hw, cin), jnp.bfloat16
        )
        kb = jax.random.normal(
            jax.random.PRNGKey(4), (3, 3, cin, cout), jnp.bfloat16
        )
        fl = 2.0 * BATCH * hw * hw * cout * 9 * cin
        time_op(
            f"resnet conv 3x3x{cin}->{cout} @{hw}^2 b{BATCH}",
            lambda c, xb=xb, kb=kb: (jax.lax.conv_general_dilated(
                (xb * (1 + 1e-30 * c)).astype(jnp.bfloat16), kb, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32) ** 2).sum(),
            jnp.float32(1.0), fl,
        )


def profile_sweep(which):
    print(f"== {which} batch sweep (full step, img/s/chip) ==")
    for b in (128, 256, 512, 1024):
        global BATCH
        old, BATCH = BATCH, b
        try:
            s = profile_model(which)
            print(f"  -> batch {b}: {b/s:,.0f} img/s")
        finally:
            BATCH = old


def main():
    cases = sys.argv[1:] or ["mnist", "resnet", "gather", "ceiling"]
    print(f"devices: {jax.devices()}")
    for c in cases:
        if c in ("mnist", "resnet"):
            profile_model(c)
        elif c == "gather":
            profile_gather()
        elif c == "ceiling":
            profile_ceiling()
        elif c.startswith("sweep"):
            profile_sweep(c.split(":")[1] if ":" in c else "resnet")


if __name__ == "__main__":
    main()
