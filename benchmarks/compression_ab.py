"""A/B: gradient wire compression on the compiled DP path, 8-device mesh.

Measures the reference MNIST CNN's train step with
``DistributedOptimizer(compression=...)`` across the full wire ladder —
``none`` (f32), ``bf16``, and the quantized EQuARX-style wires ``int8`` /
``fp8`` each with AND without error feedback — on the virtual 8-device CPU
mesh (the suite's multi-process-without-a-cluster mode, SURVEY.md §4b):
steps/s, per-step gradient wire bytes (param count × wire element width —
what crosses ICI/DCN per reduction; quantized wires add one f32 scale per
fusion bucket, noise at any real model size), and the final-loss delta
after a fixed number of steps against the uncompressed run.

The wire-dtype change itself is proven at the HLO level in
tests/test_compression_path.py / tests/test_overlap_compression.py; this
script puts numbers on it for BASELINE.md. The STATED TOLERANCE for the
quantized wires: with error feedback the final loss must track the bf16
path within ``--tolerance`` (default 10% relative) — the acceptance bound
the bench asserts (``within_tolerance``; exit non-zero on a miss). The
no-error-feedback legs are the ablation: they are *allowed* to drift (the
uncorrected quantization bias compounding across steps is exactly what
error feedback removes).

Run:  python benchmarks/compression_ab.py  [--steps 30] [--tolerance 0.1]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:  # jax >= 0.4.34 spells the device-count override as config too;
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older floors use the XLA_FLAGS set above
    pass

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvt  # noqa: E402
from horovod_tpu.models.cnn import MnistCNN  # noqa: E402
from horovod_tpu.parallel import sharding as sharding_lib  # noqa: E402
from horovod_tpu.training.trainer import Trainer  # noqa: E402

#: wire element width in bytes per compression mode
_WIRE_BYTES = {"none": 4, "bf16": 2, "int8": 1, "fp8": 1}


def run(compression: str, steps: int, x, y, *, error_feedback: bool = True):
    tx = hvt.DistributedOptimizer(
        optax.adam(1e-3), compression=compression,
        error_feedback=error_feedback,
    )
    tr = Trainer(MnistCNN(), tx)
    state = tr.build(x[: tr.dp_size])
    batch = tr._shard((x, y))
    scale = jnp.asarray(1.0, jnp.float32)
    acc = sharding_lib.replicate(
        {"loss": jnp.zeros(()), "accuracy": jnp.zeros(())}, tr.mesh
    )
    n_params = sum(p.size for p in jax.tree.leaves(state.params))
    # Warm up (compile) + 2 steps out of the timing window.
    for _ in range(2):
        state, metrics, acc = tr._train_step(state, batch, scale, acc)
    jax.block_until_ready(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics, acc = tr._train_step(state, batch, scale, acc)
    loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    quantized = compression in ("int8", "fp8")
    label = compression
    if quantized:
        label += "+ef" if error_feedback else "-noef"
    return {
        "compression": label,
        "steps_per_s": steps / dt,
        "loss": loss,
        "n_params": int(n_params),
        "wire_bytes_per_reduction": int(n_params * _WIRE_BYTES[compression]),
        "error_feedback": error_feedback if quantized else None,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument(
        "--tolerance", type=float, default=0.1,
        help="max relative final-loss delta of the error-feedback "
        "quantized wires vs the bf16 path (the stated acceptance bound)",
    )
    args = ap.parse_args()
    rng = np.random.RandomState(0)
    # Global batch 256 over 8 shards of the reference's 28x28x1 images.
    x = rng.rand(256, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int64)
    legs = [
        run("none", args.steps, x, y),
        run("bf16", args.steps, x, y),
        run("int8", args.steps, x, y, error_feedback=True),
        run("int8", args.steps, x, y, error_feedback=False),
        run("fp8", args.steps, x, y, error_feedback=True),
        run("fp8", args.steps, x, y, error_feedback=False),
    ]
    loss_f32 = legs[0]["loss"]
    loss_bf16 = legs[1]["loss"]
    ok = True
    for leg in legs[1:]:
        leg["loss_delta_vs_f32"] = abs(leg["loss"] - loss_f32)
        if leg["error_feedback"]:
            rel = abs(leg["loss"] - loss_bf16) / max(abs(loss_bf16), 1e-9)
            leg["rel_delta_vs_bf16"] = rel
            leg["within_tolerance"] = rel <= args.tolerance
            ok = ok and leg["within_tolerance"]
    out = {"tolerance_rel_vs_bf16": args.tolerance, "legs": legs}
    print(json.dumps(out, indent=2))
    if not ok:
        print(
            "compression_ab: an error-feedback quantized leg missed the "
            f"stated tolerance ({args.tolerance} rel vs bf16)",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
