"""A/B: gradient wire compression on the compiled DP path, 8-device mesh.

Measures the reference MNIST CNN's train step with
``DistributedOptimizer(compression='none')`` vs ``'bf16'`` on the virtual
8-device CPU mesh (the suite's multi-process-without-a-cluster mode,
SURVEY.md §4b): steps/s, per-step gradient wire bytes (param count × wire
dtype width — what crosses ICI/DCN per all-reduce), and the loss delta after
a fixed number of steps. The wire-dtype change itself is proven at the HLO
level in tests/test_compression_path.py; this script puts numbers on it for
BASELINE.md.

Run:  python benchmarks/compression_ab.py  [--steps 30]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvt  # noqa: E402
from horovod_tpu.models.cnn import MnistCNN  # noqa: E402
from horovod_tpu.parallel import sharding as sharding_lib  # noqa: E402
from horovod_tpu.training.trainer import Trainer  # noqa: E402


def run(compression: str, steps: int, x, y):
    tx = hvt.DistributedOptimizer(optax.adam(1e-3), compression=compression)
    tr = Trainer(MnistCNN(), tx)
    state = tr.build(x[: tr.dp_size])
    batch = tr._shard((x, y))
    scale = jnp.asarray(1.0, jnp.float32)
    acc = sharding_lib.replicate(
        {"loss": jnp.zeros(()), "accuracy": jnp.zeros(())}, tr.mesh
    )
    n_params = sum(p.size for p in jax.tree.leaves(state.params))
    # Warm up (compile) + 2 steps out of the timing window.
    for _ in range(2):
        state, metrics, acc = tr._train_step(state, batch, scale, acc)
    jax.block_until_ready(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics, acc = tr._train_step(state, batch, scale, acc)
    loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    wire_bytes = n_params * (2 if compression != "none" else 4)
    return {
        "compression": compression,
        "steps_per_s": steps / dt,
        "loss": loss,
        "n_params": int(n_params),
        "wire_bytes_per_allreduce": int(wire_bytes),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()
    rng = np.random.RandomState(0)
    # Global batch 256 over 8 shards of the reference's 28x28x1 images.
    x = rng.rand(256, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 256).astype(np.int64)
    out = [run(c, args.steps, x, y) for c in ("none", "bf16")]
    out[1]["loss_delta_vs_f32"] = abs(out[1]["loss"] - out[0]["loss"])
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
