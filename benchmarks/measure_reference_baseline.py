"""Measure the reference's training math on this machine (baseline numbers).

The reference publishes no performance figures (SURVEY.md §6), so the
baseline must be measured: this script rebuilds the reference's model and
input pipeline in TF/Keras — same architecture (tensorflow2_keras_mnist.py:
43-52), same batch size (128), same optimizer family (Adam 1e-3) — and times
steady-state training throughput on CPU (BASELINE.json config 1: the
reference single-process mode, ``hvd.size()==1``, README.md:49-52).

Writes ``benchmarks/baseline_measured.json``; ``bench.py`` reads it to
compute ``vs_baseline``. Run once per machine:

    python benchmarks/measure_reference_baseline.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCH = 128          # tensorflow2_keras_mnist.py:41
WARMUP_STEPS = 30
MEASURE_STEPS = 200


def main() -> None:
    import numpy as np
    import tensorflow as tf

    from horovod_tpu.data import datasets

    tf.config.set_visible_devices([], "GPU")

    (x_train, y_train), _ = datasets.mnist()
    x = (x_train.astype("float32") / 255.0)[..., None]
    y = y_train.astype("int64")

    ds = (
        tf.data.Dataset.from_tensor_slices((x, y))
        .repeat()
        .shuffle(10000)
        .batch(BATCH)
    )

    model = tf.keras.Sequential(
        [
            tf.keras.layers.Conv2D(32, [3, 3], activation="relu",
                                   input_shape=(28, 28, 1)),
            tf.keras.layers.Conv2D(64, [3, 3], activation="relu"),
            tf.keras.layers.MaxPooling2D(pool_size=(2, 2)),
            tf.keras.layers.Dropout(0.25),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(128, activation="relu"),
            tf.keras.layers.Dropout(0.5),
            tf.keras.layers.Dense(10, activation="softmax"),
        ]
    )
    model.compile(
        loss=tf.losses.SparseCategoricalCrossentropy(),
        optimizer=tf.optimizers.Adam(0.001),
        metrics=["accuracy"],
    )

    class Timer(tf.keras.callbacks.Callback):
        def __init__(self):
            self.t0 = None
            self.elapsed = None

        def on_train_batch_begin(self, batch, logs=None):
            if batch == WARMUP_STEPS:
                self.t0 = time.perf_counter()

        def on_train_batch_end(self, batch, logs=None):
            if batch == WARMUP_STEPS + MEASURE_STEPS - 1:
                self.elapsed = time.perf_counter() - self.t0
                self.model.stop_training = True

    timer = Timer()
    model.fit(
        ds,
        steps_per_epoch=WARMUP_STEPS + MEASURE_STEPS,
        epochs=1,
        callbacks=[timer],
        verbose=2,
    )

    images_per_sec = MEASURE_STEPS * BATCH / timer.elapsed
    result = {
        "config": "reference-equivalent TF2/Keras MNIST CNN, single process",
        "hardware": "CPU (this machine)",
        "batch_size": BATCH,
        "measure_steps": MEASURE_STEPS,
        "images_per_sec": round(images_per_sec, 1),
        "step_time_ms": round(1000 * timer.elapsed / MEASURE_STEPS, 2),
        "tf_version": tf.__version__,
    }
    out = os.path.join(REPO, "benchmarks", "baseline_measured.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
