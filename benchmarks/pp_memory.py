"""Pipeline-schedule backward-memory comparison (the BASELINE.md 6.7× row).

Compares XLA's `memory_analysis()` of the compiled gradient computation for
`PipelinedLM(schedule='gpipe')` (AD-derived backward: the scan stash holds
every tick's stage internals) vs `schedule='1f1b'` (hand-scheduled staggered
backward with per-microbatch rematerialization — the 1F1B activation
discipline). Runs on the virtual 8-device CPU mesh (data=2 × pipe=4), so it
reproduces anywhere.

Run:  python benchmarks/pp_memory.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from horovod_tpu.models.pipelined_lm import PipelinedLM  # noqa: E402
from horovod_tpu.parallel import mesh as mesh_lib  # noqa: E402

VOCAB = 64
D_MODEL, N_HEADS, N_LAYERS, N_MICRO = 128, 4, 8, 8
BATCH, SEQ = 16, 256


def temp_bytes(schedule: str, mesh, params, toks, labels) -> int:
    model = PipelinedLM(
        vocab_size=VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
        n_layers=N_LAYERS, n_micro=N_MICRO, mesh=mesh, schedule=schedule,
    )

    def loss(p):
        logits = model.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    compiled = jax.jit(jax.grad(loss)).lower(params).compile()
    return int(compiled.memory_analysis().temp_size_in_bytes)


def main():
    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, pipe=4))
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(1, VOCAB, size=(BATCH, SEQ)).astype(np.int32))
    labels = jnp.asarray(rng.randint(1, VOCAB, size=(BATCH, SEQ)).astype(np.int32))
    params = PipelinedLM(
        vocab_size=VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
        n_layers=N_LAYERS, n_micro=N_MICRO, mesh=None,
    ).init(jax.random.PRNGKey(0), toks)["params"]

    g = temp_bytes("gpipe", mesh, params, toks, labels)
    f = temp_bytes("1f1b", mesh, params, toks, labels)
    print(json.dumps({
        "config": f"d{D_MODEL}x{N_LAYERS}L seq {SEQ}, pipe=4 x data=2, "
                  f"{N_MICRO} microbatches",
        "gpipe_temp_bytes": g,
        "1f1b_temp_bytes": f,
        "gpipe_over_1f1b": round(g / f, 2),
    }, indent=2))


if __name__ == "__main__":
    main()
