"""On-chip flash-attention tuning harness (not part of the test suite).

Times our pallas kernel (fwd and fwd+bwd) across block sizes against XLA
dense attention and the stock JAX pallas TPU kernel, plus a pure-matmul
ceiling row that establishes what this chip + tunnel measurement can reach.

Honest-timing rules are the same as bench.py: one fused lax.scan chains N
iterations with a data dependence, and the clock stops only after fetching a
scalar that depends on the whole chain (BASELINE.md "Measurement
methodology").

Usage: python benchmarks/fa_tune.py [case ...]
  cases: matmul dense ours stock  (default: all)
Env: FA_SHAPES="B,T,H,D;..."  FA_STEPS=256
"""

from __future__ import annotations

import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = int(os.environ.get("FA_STEPS", 256))


def timed_chain(step, x0):
    from _timing import timed_chain as _tc

    return _tc(step, x0, steps=STEPS)


def attn_flops(b, t, h, d, causal=True, with_bwd=True):
    full = 4.0 * b * h * t * t * d  # QK^T + PV, 2 FLOP/MAC
    if causal:
        full /= 2
    return full * (1 + 2.5 * with_bwd)


def case_matmul():
    n = 4096
    x = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16) * 0.01

    def step(c):
        y = jnp.dot(c, c, preferred_element_type=jnp.float32)
        # consume NONLINEARLY: any linear functional of a matmul (a slice, a
        # sum) gets algebraically rewritten to a cheap contraction of the
        # operands — sum(y²) forces the full product to exist.
        return jnp.vdot(y, y)

    s = timed_chain(step, x)
    fl = 2.0 * n**3
    print(f"matmul {n}^3 bf16: {s*1e3:.3f} ms  {fl/s/1e12:.1f} TFLOP/s")


def _mk(b, t, h, d, dtype=jnp.bfloat16):
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 3)
    return tuple(
        jax.random.normal(k, (b, t, h, d), dtype) * 0.02 for k in ks
    )


def bench_attn(name, fn, q, k, v, *, grad: bool, flops: float):
    if grad:
        def loss(args):
            o = fn(*args)
            return jnp.sum(o.astype(jnp.float32) ** 2) * 1e-3

        g = jax.grad(lambda args: loss(args))

        def step(carry):
            # consume ALL grads: an unused dk/dv would let XLA dead-code
            # eliminate the dkv kernel and the row would time fwd+dq only
            gq, gk, gv = g((carry, k, v))
            return (
                gq.astype(jnp.float32).sum()
                + gk.astype(jnp.float32).sum()
                + gv.astype(jnp.float32).sum()
            )
    else:
        def step(carry):
            return fn(carry, k, v).astype(jnp.float32).sum()

    try:
        s = timed_chain(step, q)
    except Exception as e:  # noqa: BLE001
        print(f"  {name}: FAILED {type(e).__name__}: {str(e)[:120]}")
        return None
    print(f"  {name}: {s*1e3:.3f} ms  {flops/s/1e12:.1f} TFLOP/s")
    return s


def main():
    cases = sys.argv[1:] or ["matmul", "dense", "ours", "stock"]
    shapes = os.environ.get("FA_SHAPES", "8,1024,8,64;1,8192,8,64;1,16384,8,64")
    print(f"devices: {jax.devices()}")
    if "matmul" in cases:
        case_matmul()

    from horovod_tpu.ops.attention import dense_attention
    from horovod_tpu.ops import flash_attention as ours

    for spec in shapes.split(";"):
        b, t, h, d = (int(v) for v in spec.split(","))
        q, k, v = _mk(b, t, h, d)
        for grad in (False, True):
            fl = attn_flops(b, t, h, d, with_bwd=grad)
            tag = "fwd+bwd" if grad else "fwd"
            print(f"[B{b} T{t} H{h} D{d} bf16 causal {tag}] ideal FLOPs {fl/1e9:.0f}G")
            if "dense" in cases:
                bench_attn(
                    "xla dense", functools.partial(dense_attention, causal=True),
                    q, k, v, grad=grad, flops=fl,
                )
            if "ours" in cases:
                for bq, bk in ((512, 512), (256, 512), (512, 1024), (1024, 512), (256, 256), (1024, 1024)):
                    if t % bq or t % bk:
                        continue
                    fn = functools.partial(
                        ours.flash_attention, causal=True,
                        block_q=bq, block_k=bk, interpret=False,
                    )
                    bench_attn(f"ours bq{bq} bk{bk}", fn, q, k, v, grad=grad, flops=fl)
            if "stock" in cases:
                from jax.experimental.pallas.ops.tpu import flash_attention as st

                def stock(q, k, v):
                    # stock kernel wants [B, H, T, D]
                    qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
                    o = st.flash_attention(qt, kt, vt, causal=True)
                    return jnp.transpose(o, (0, 2, 1, 3))

                bench_attn("stock pallas", stock, q, k, v, grad=grad, flops=fl)


if __name__ == "__main__":
    main()
