"""Transformer train-step ablation on-chip (not part of the test suite).

Times nested subsets of the bench transformer config's train step to
attribute step time: full forward / forward+backward / +optimizer /
dense-vs-flash attention / lm_head+CE alone. Timing is `_timing.timed_chain`
(one fused scan, min-of-3, nonzero carry perturbation) — see that module's
docstring for the measurement hazards it guards against; the residual bias
is one tunnel RTT over the N-step chain, identical across cases.

Usage: python benchmarks/lm_profile.py
Env: LMP_SEQ=1024 LMP_BATCH=8 LMP_N=64
"""

from __future__ import annotations

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from _timing import timed_chain

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEQ = int(os.environ.get("LMP_SEQ", 1024))
BATCH = int(os.environ.get("LMP_BATCH", 8))
N = int(os.environ.get("LMP_N", 64))
VOCAB, D, HEADS, LAYERS = 8192, 512, 8, 8


def main():
    from horovod_tpu.models.transformer import TransformerLM
    import horovod_tpu as hvt

    os.environ.setdefault("HVT_FAST_RNG", "1")
    hvt.init()
    print(f"devices: {jax.devices()}  seq={SEQ} batch={BATCH}")

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, (BATCH, SEQ)), jnp.int32
    )
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, VOCAB, (BATCH, SEQ)), jnp.int32
    )

    def build(attn):
        m = TransformerLM(
            vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=LAYERS,
            compute_dtype=jnp.bfloat16, dropout=0.0,
        )
        if attn == "dense":
            import dataclasses

            m = dataclasses.replace(
                m, sharding=dataclasses.replace(m.sharding, attn="dense")
            )
        params = m.init(jax.random.PRNGKey(0), tokens, train=False)["params"]
        return m, params

    model, params = build("flash")
    x0 = jnp.float32(1.0)

    def perturbed_tokens(c):
        # the carry must reach the model input through a non-foldable path
        return (tokens + (1e-30 * c).astype(jnp.int32)) % VOCAB

    # --- forward only ------------------------------------------------------
    def fwd_loss(params, toks):
        logits = model.apply({"params": params}, toks, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    s = timed_chain(lambda c: fwd_loss(params, perturbed_tokens(c)), x0, steps=N)
    print(f"forward+loss: {s*1e3:.3f} ms/step")

    # --- fwd+bwd -----------------------------------------------------------
    gfn = jax.grad(fwd_loss)

    def bwd_step(c):
        g = gfn(params, perturbed_tokens(c))
        return jax.tree.leaves(g)[0].astype(jnp.float32).sum()

    s = timed_chain(bwd_step, x0, steps=N)
    print(f"forward+backward: {s*1e3:.3f} ms/step")

    # --- full train step (fwd+bwd+adamw): params/opt genuinely chain -------
    tx = optax.adamw(3e-4)
    opt0 = tx.init(params)

    @jax.jit
    def full(params, opt):
        def body(carry, _):
            p, o = carry
            g = gfn(p, tokens)
            up, o = tx.update(g, o, p)
            p = optax.apply_updates(p, up)
            return (p, o), jax.tree.leaves(g)[0].astype(jnp.float32).sum()

        (p, o), outs = jax.lax.scan(body, (params, opt), None, length=N)
        return outs[-1] + 0.0 * jax.tree.leaves(p)[0].astype(jnp.float32).sum()

    float(jax.device_get(full(params, opt0)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(jax.device_get(full(params, opt0)))
        best = min(best, time.perf_counter() - t0)
    print(f"full step (fwd+bwd+adamw): {best/N*1e3:.3f} ms/step")

    # --- attention ablation: dense vs flash at this seq --------------------
    model_d, params_d = build("dense")

    def fwd_dense(c):
        logits = model_d.apply({"params": params_d}, perturbed_tokens(c), train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    s = timed_chain(fwd_dense, x0, steps=N)
    print(f"forward+loss (dense attn): {s*1e3:.3f} ms/step")

    # --- lm_head + CE alone ------------------------------------------------
    acts = jnp.ones((BATCH, SEQ, D), jnp.bfloat16) * 0.01
    w = params["lm_head"]["kernel"]

    def head_loss(a):
        logits = a.reshape(-1, D) @ w.astype(jnp.bfloat16)
        logits = logits.astype(jnp.float32).reshape(BATCH, SEQ, VOCAB)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    s = timed_chain(lambda c: head_loss(acts * c.astype(jnp.bfloat16)), x0, steps=N)
    print(f"lm_head matmul + CE (fwd only): {s*1e3:.3f} ms")

    ghead = jax.grad(head_loss)

    def head_bwd_step(c):
        return ghead(acts * c.astype(jnp.bfloat16)).astype(jnp.float32).sum()

    s = timed_chain(head_bwd_step, x0, steps=N)
    print(f"lm_head + CE fwd+bwd: {s*1e3:.3f} ms")


if __name__ == "__main__":
    main()
