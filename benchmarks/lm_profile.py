"""Transformer train-step ablation on-chip (not part of the test suite).

Times nested subsets of the bench transformer config's train step to
attribute step time: embed / blocks-minus-attention / full forward /
forward+backward / +optimizer. Slope timing: each case is timed at two chain
lengths and the per-step cost is (t2 - t1) / (n2 - n1), which cancels the
tunnel's fixed per-dispatch round-trip (BASELINE.md "Measurement
methodology").

Usage: python benchmarks/lm_profile.py
Env: LMP_SEQ=1024 LMP_BATCH=8 LMP_N1=16 LMP_N2=48
"""

from __future__ import annotations

import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEQ = int(os.environ.get("LMP_SEQ", 1024))
BATCH = int(os.environ.get("LMP_BATCH", 8))
N2 = int(os.environ.get("LMP_N2", 64))
VOCAB, D, HEADS, LAYERS = 8192, 512, 8, 8


def slope_time(make_run):
    """make_run(n) -> zero-arg callable returning a device scalar after n
    chained steps. One long chain (N2), min of 3 runs — slope between two
    single runs is unusable here (tunnel RTT jitter exceeds the work delta);
    the residual bias is RTT/N2, identical across cases."""
    run = make_run(N2)
    float(jax.device_get(run()))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(jax.device_get(run()))
        best = min(best, time.perf_counter() - t0)
    return best / N2


def chain(step_fn, x0):
    def make_run(n):
        @jax.jit
        def run(x):
            def body(c, _):
                s = step_fn(c)
                # tiny-but-NONZERO factor: `0*s` would be algebraically
                # folded, making the carry loop-invariant and hoistable
                # (see benchmarks/fa_tune.py timed_chain)
                eps = (1.0 + 1e-30 * s).astype(c.dtype)
                return c * eps, s

            c, outs = jax.lax.scan(body, x, None, length=n)
            return outs[-1] + 0.0 * jnp.float32(c.reshape(-1)[0])

        return lambda: run(x0)

    return make_run


def main():
    from horovod_tpu.models.transformer import TransformerLM
    import horovod_tpu as hvt

    os.environ.setdefault("HVT_FAST_RNG", "1")
    hvt.init()
    print(f"devices: {jax.devices()}  seq={SEQ} batch={BATCH}")

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, (BATCH, SEQ)), jnp.int32
    )
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, VOCAB, (BATCH, SEQ)), jnp.int32
    )

    def build(attn):
        m = TransformerLM(
            vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=LAYERS,
            compute_dtype=jnp.bfloat16, dropout=0.0,
        )
        if attn == "dense":
            import dataclasses

            m = dataclasses.replace(m, sharding=dataclasses.replace(m.sharding, attn="dense"))
        params = m.init(jax.random.PRNGKey(0), tokens, train=False)["params"]
        return m, params

    model, params = build("flash")

    fwd_flops = None

    # --- forward only ------------------------------------------------------
    def fwd_loss(params, toks):
        logits = model.apply({"params": params}, toks, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()

    x0 = jnp.float32(1.0)

    def fwd_step(c):
        return fwd_loss(params, (tokens + (1e-30 * c).astype(jnp.int32)) % VOCAB)

    s = slope_time(chain(fwd_step, x0))
    print(f"forward+loss: {s*1e3:.3f} ms/step")

    # --- fwd+bwd -----------------------------------------------------------
    gfn = jax.grad(fwd_loss)

    def bwd_step(c):
        g = gfn(params, (tokens + (1e-30 * c).astype(jnp.int32)) % VOCAB)
        return jax.tree.leaves(g)[0].astype(jnp.float32).sum()

    s = slope_time(chain(bwd_step, x0))
    print(f"forward+backward: {s*1e3:.3f} ms/step")

    # --- full train step (fwd+bwd+adamw) -----------------------------------
    tx = optax.adamw(3e-4)
    opt0 = tx.init(params)

    def make_full(n):
        @jax.jit
        def run(params, opt):
            def body(carry, _):
                p, o = carry
                g = gfn(p, tokens)
                up, o = tx.update(g, o, p)
                p = optax.apply_updates(p, up)
                return (p, o), jax.tree.leaves(g)[0].astype(jnp.float32).sum()

            (p, o), outs = jax.lax.scan(body, (params, opt), None, length=n)
            return outs[-1] + 0.0 * jax.tree.leaves(p)[0].astype(jnp.float32).sum()

        return lambda: run(params, opt0)

    s = slope_time(make_full)
    print(f"full step (fwd+bwd+adamw): {s*1e3:.3f} ms/step")

    # --- attention ablation: dense vs flash at this seq --------------------
    model_d, params_d = build("dense")

    def fwd_dense(c):
        toks = (tokens + (1e-30 * c).astype(jnp.int32)) % VOCAB
        logits = model_d.apply({"params": params_d}, toks, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()

    s = slope_time(chain(fwd_dense, x0))
    print(f"forward+loss (dense attn): {s*1e3:.3f} ms/step")

    # --- lm_head + CE alone ------------------------------------------------
    acts = jnp.ones((BATCH, SEQ, D), jnp.bfloat16) * 0.01
    w = params["lm_head"]["kernel"]

    def head_step(c):
        logits = (acts * c.astype(jnp.bfloat16)).reshape(-1, D) @ w.astype(jnp.bfloat16)
        logits = logits.astype(jnp.float32).reshape(BATCH, SEQ, VOCAB)
        return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()

    s = slope_time(chain(head_step, x0))
    print(f"lm_head matmul + CE (fwd only): {s*1e3:.3f} ms")

    # grad w.r.t. activations through head+CE
    def head_loss(a):
        logits = a.reshape(-1, D) @ w.astype(jnp.bfloat16)
        logits = logits.astype(jnp.float32).reshape(BATCH, SEQ, VOCAB)
        return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()

    ghead = jax.grad(head_loss)

    def head_bwd_step(c):
        return ghead(acts * c.astype(jnp.bfloat16)).astype(jnp.float32).sum()

    s = slope_time(chain(head_bwd_step, x0))
    print(f"lm_head + CE fwd+bwd: {s*1e3:.3f} ms")


if __name__ == "__main__":
    main()
