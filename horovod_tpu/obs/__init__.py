"""One pane of glass: typed metric registry + Prometheus exposition.

* `obs.core` — the declared metric catalog (`METRICS`) and the
  thread-safe instruments; undeclared names are refused (HVT009 checks
  call sites statically).
* `obs.prom` — text-format exposition (`render`) and its inverse
  (`parse_text`, the CI gate's reader).
* `obs.server` — the ``GET /metrics`` HTTP server and the opt-in
  trainer-side exporter (``HVT_METRICS_PORT``, ``POST /profile``).

Emission sites import the package and call ``obs.counter`` /
``obs.gauge`` / ``obs.histogram`` — never inside a jit/shard_map-traced
body (host effect; HVT009, same class as HVT003).
"""

from horovod_tpu.obs.core import (  # noqa: F401 — the public surface
    METRICS,
    MetricSpec,
    Registry,
    UnknownMetricError,
    counter,
    counter_set,
    default_registry,
    gauge,
    histogram,
    is_declared,
    register_collector,
    reset,
    spec,
)
