"""Typed telemetry registry — the single declaration point for every
exported metric series, the way `analysis/registry.py` is for ``HVT_*``
knobs.

The framework grew five disjoint slices of operational truth (restart
journal, supervisor ``/status``, elastic generation state, bench JSON
rows, serving ``/healthz``). This module unifies their *export surface*:
every series any process exposes over ``GET /metrics`` is declared here
as a `MetricSpec` (kind, help text, labels, histogram bucket edges), and
the instruments refuse undeclared names — a new series cannot ship
without a spec row, so the metric catalog (README "Observability") and
the exposition can't drift, exactly the HVT004 discipline for knobs.
The `hvt-lint` rule HVT009 enforces the same statically: an
``obs.counter/gauge/histogram`` call site naming an undeclared series is
a lint finding.

Deliberately dependency-free (stdlib only): the supervisor — which never
imports jax — and the linter both import this module.

Instruments are process-local and thread-safe (one registry-wide lock;
every operation under it is a dict lookup + float add). Three kinds:

* **counter** — monotonically increasing total (``_total`` suffix by
  convention). ``counter(name, inc)`` adds; collectors that re-derive a
  lifetime total from a durable source (the restart journal) use
  ``counter_set`` — the journal is the monotonic truth, the in-memory
  instrument just mirrors it.
* **gauge** — a value that goes up and down (``gauge(name, value)``).
* **histogram** — observations bucketed into the spec's FIXED edges
  (``histogram(name, value)``); exposition renders cumulative buckets,
  ``+Inf``, ``_sum`` and ``_count`` (prom.py owns the text format).

Registries: most processes use the module-level default (the
``obs.counter/gauge/histogram`` functions). Scrape-time aggregators (the
supervisor, which derives everything from the journal + coordinator per
request) build a fresh private `Registry` per scrape instead, so
concurrent scrapes and multiple supervisors in one test process never
race each other. The *declarations* are global either way — any registry
refuses an undeclared name.

``register_collector(fn)``: callbacks run at collect() time, just before
a scrape renders — the hook for values that live elsewhere (queue depth,
``data.stream.RETRY_STATS``) and are cheaper to read on demand than to
push on every change. Collector errors are swallowed per-collector: a
broken gauge must never take down the scrape surface.
"""

from __future__ import annotations

import dataclasses
import re
import threading

__all__ = [
    "MetricSpec", "METRICS", "UnknownMetricError", "Registry", "spec",
    "is_declared", "counter", "counter_set", "gauge", "histogram",
    "register_collector", "default_registry", "reset",
]

# Prometheus metric-name / label-name grammar.
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Shared latency edges (seconds), request-scale: 1 ms .. 60 s, log-ish.
_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
# Step-scale edges (seconds): training steps span ~1 ms (MNIST/CPU) to
# minutes (large accumulation on real pods).
_STEP_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0,
)


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One declared metric series."""

    name: str
    kind: str                 # "counter" | "gauge" | "histogram"
    help: str
    subsystem: str            # catalog grouping (README table order)
    labels: tuple = ()
    buckets: tuple | None = None   # histogram only: ascending upper edges


_SUBSYSTEM_ORDER = (
    "supervisor", "serving", "training", "data", "obs",
)


def _decl(specs: list[MetricSpec]) -> dict[str, MetricSpec]:
    table: dict[str, MetricSpec] = {}
    for s in specs:
        if s.name in table:
            raise ValueError(f"duplicate metric declaration {s.name}")
        if not _NAME_RE.match(s.name):
            raise ValueError(f"{s.name}: not a valid metric name")
        if s.kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"{s.name}: unknown kind {s.kind!r}")
        if s.subsystem not in _SUBSYSTEM_ORDER:
            raise ValueError(
                f"{s.name}: unknown subsystem {s.subsystem!r} — add it to "
                "_SUBSYSTEM_ORDER so the catalog ordering stays deterministic"
            )
        for lb in s.labels:
            if not _LABEL_RE.match(lb):
                raise ValueError(f"{s.name}: invalid label name {lb!r}")
        if s.kind == "histogram":
            if not s.buckets:
                raise ValueError(f"{s.name}: histograms need bucket edges")
            if list(s.buckets) != sorted(set(s.buckets)):
                raise ValueError(
                    f"{s.name}: bucket edges must be strictly increasing"
                )
        elif s.buckets is not None:
            raise ValueError(f"{s.name}: only histograms take buckets")
        if s.kind == "counter" and not s.name.endswith("_total"):
            # The promtool naming lint; enforced at declaration so the
            # exposition can't ship a non-conventional counter.
            raise ValueError(f"{s.name}: counters must end in _total")
        table[s.name] = s
    return table


METRICS: dict[str, MetricSpec] = _decl([
    # --- supervisor (launch/supervisor.py /metrics) -------------------------
    MetricSpec("hvt_restarts_total", "counter",
               "Lifetime restarts the supervisor journaled (fleet "
               "relaunches, or per-member replacements in elastic mode).",
               "supervisor"),
    MetricSpec("hvt_fleet_shrinks_total", "counter",
               "Elastic generations that settled SMALLER than their "
               "predecessor (clean departures absorbed in place).",
               "supervisor"),
    MetricSpec("hvt_fleet_grows_total", "counter",
               "Elastic generations that settled LARGER than their "
               "predecessor (replacements/joiners admitted).",
               "supervisor"),
    MetricSpec("hvt_supervisor_gave_up_total", "counter",
               "Times the supervisor journaled spending its no-progress "
               "restart budget (>0 means the job needed an operator).",
               "supervisor"),
    MetricSpec("hvt_elastic_generation", "gauge",
               "Current elastic membership generation (bumps on every "
               "join/leave/death).", "supervisor"),
    MetricSpec("hvt_fleet_size", "gauge",
               "Settled world size of the current generation.",
               "supervisor"),
    MetricSpec("hvt_fleet_live_members", "gauge",
               "Members currently live on the rendezvous coordinator.",
               "supervisor"),
    MetricSpec("hvt_member_heartbeat_age_seconds", "gauge",
               "Seconds since each live member's last TCP beat "
               "(coordinator clock).", "supervisor", labels=("member",)),
    MetricSpec("hvt_flight_dumps_total", "counter",
               "Flight-record collections the supervisor journaled on "
               "hang classifications (each = one hang whose per-rank "
               "collective submission records were quarantined for "
               "`hvt-sched replay`).", "supervisor"),
    MetricSpec("hvt_policy_actions_total", "counter",
               "Supervisor policy-engine decisions journaled as "
               "policy_* events (launch/policy.py), by action "
               "(warn/evict/promote/triage) and outcome — outcome "
               "'dry-run' means the decision was journaled without "
               "acting (HVT_POLICY=dry-run).", "supervisor",
               labels=("action", "outcome")),
    MetricSpec("hvt_restart_budget_remaining", "gauge",
               "Consecutive no-progress restarts left before the "
               "supervisor gives up (resets to max_restarts on progress).",
               "supervisor"),
    MetricSpec("hvt_fleet_step_ms", "gauge",
               "Fleet-level step-time summary computed at GET /fleet "
               "aggregation from the member exporters' "
               "hvt_step_phase_ms{phase=total}: the slowest and fastest "
               "rank's step time this scrape.", "supervisor",
               labels=("stat",)),
    MetricSpec("hvt_committed_epoch", "gauge",
               "Epoch of the best committed progress the supervisor can "
               "see (elastic commit marker or checkpoint manifest).",
               "supervisor"),
    MetricSpec("hvt_committed_step", "gauge",
               "Best committed optimizer step: cumulative when the "
               "checkpoint manifest carries the stream geometry "
               "(epoch x steps_per_epoch + step), the within-epoch step "
               "otherwise.", "supervisor"),
    # --- fleetd (launch/fleetd.py GET /fleetd + /metrics) -------------------
    MetricSpec("hvt_fleetd_jobs", "gauge",
               "Jobs under the fleet scheduler, by lifecycle state "
               "(pending/running/done/failed).", "supervisor",
               labels=("state",)),
    MetricSpec("hvt_fleetd_hosts", "gauge",
               "Pool hosts by state: up (schedulable) or quarantined "
               "(declared lost, cooling down).", "supervisor",
               labels=("state",)),
    MetricSpec("hvt_fleetd_preempts_total", "counter",
               "Preemption decisions journaled: a lower-priority elastic "
               "job shrunk (clean leave, zero budget spend) to free "
               "hosts for a higher-priority one.", "supervisor"),
    MetricSpec("hvt_fleetd_regrows_total", "counter",
               "Regrow grants journaled: freed hosts handed back to a "
               "shrunken job (POST /grow -> take_grows).", "supervisor"),
    MetricSpec("hvt_fleetd_host_lost_total", "counter",
               "Host-loss events journaled: every rank on the host died "
               "together, charged to the owning job ONCE, host "
               "quarantined.", "supervisor"),
    MetricSpec("hvt_fleetd_job_size", "gauge",
               "Host units currently allocated to each job.",
               "supervisor", labels=("job",)),
    MetricSpec("hvt_fleetd_job_restart_budget_remaining", "gauge",
               "Each job's OWN remaining no-progress restart budget "
               "(scraped from its supervisor; isolation means a peer's "
               "failures never move it).", "supervisor",
               labels=("job",)),
    # --- serving (launch/serve.py /metrics) ---------------------------------
    MetricSpec("hvt_serve_requests_total", "counter",
               "HTTP requests served, by route and status code.",
               "serving", labels=("route", "code")),
    MetricSpec("hvt_serve_queue_depth", "gauge",
               "Rows waiting in the coalescing device queue (sampled at "
               "scrape time).", "serving"),
    MetricSpec("hvt_serve_device_calls_total", "counter",
               "Compiled-program dispatches (the coalescing win: "
               "rows_total / device_calls_total ~ effective batch).",
               "serving"),
    MetricSpec("hvt_serve_rows_total", "counter",
               "Request rows pushed through the device.", "serving"),
    MetricSpec("hvt_serve_request_seconds", "histogram",
               "End-to-end request latency by route.", "serving",
               labels=("route",), buckets=_LATENCY_BUCKETS),
    MetricSpec("hvt_serve_ttft_seconds", "histogram",
               "Time to first token per generate request (streaming: "
               "first chunk flushed; one-shot: the whole call — prefill "
               "and decode are one dispatch there).", "serving",
               buckets=_LATENCY_BUCKETS),
    MetricSpec("hvt_serve_tpot_seconds", "histogram",
               "Time per output token per generate request (decode "
               "tail / generated tokens).", "serving",
               buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                        0.05, 0.1, 0.25, 0.5, 1.0)),
    # --- serving: continuous batching engine (serving/engine.py) ------------
    MetricSpec("hvt_serve_admitted_total", "counter",
               "Sequences the continuous-batching scheduler admitted "
               "into a decode slot (prefill spliced into the live "
               "batch).", "serving"),
    MetricSpec("hvt_serve_retired_total", "counter",
               "Sequences retired from the live batch (eos or "
               "generation budget) — their KV blocks returned the same "
               "tick.", "serving"),
    MetricSpec("hvt_serve_rejected_total", "counter",
               "Admissions refused with 429 because the bounded wait "
               "queue was full (the allocator saying no at the door "
               "instead of OOMing mid-decode).", "serving"),
    MetricSpec("hvt_serve_live_seqs", "gauge",
               "Sequences currently holding a decode slot (sampled at "
               "scrape time).", "serving"),
    MetricSpec("hvt_serve_kv_blocks_used", "gauge",
               "Paged-KV blocks reserved by live + waiting-admitted "
               "sequences.", "serving"),
    MetricSpec("hvt_serve_kv_blocks_free", "gauge",
               "Paged-KV blocks available for admission.", "serving"),
    # --- serving: replica fleet (serving/router.py, serving/fleet.py) -------
    MetricSpec("hvt_serve_replicas", "gauge",
               "Replicas currently admitting traffic at the router "
               "(draining and dead replicas excluded).", "serving"),
    MetricSpec("hvt_serve_replica_inflight", "gauge",
               "Requests in flight per replica (the router's "
               "least-loaded dispatch key; 0 is the drain barrier).",
               "serving", labels=("replica",)),
    MetricSpec("hvt_serve_router_retries_total", "counter",
               "Requests the router re-dispatched to another replica "
               "after a connect failure (before any response bytes — "
               "mid-stream failures surface to the client).", "serving"),
    MetricSpec("hvt_serve_swaps_total", "counter",
               "Zero-downtime weight swaps completed across the fleet "
               "(drain -> swap -> readmit, journaled per replica).",
               "serving"),
    # --- training (the HVT_METRICS_PORT trainer exporter) -------------------
    MetricSpec("hvt_step_phase_ms", "gauge",
               "Live per-step phase attribution in ms (labels: total / "
               "compute / comm / input), sampled every HVT_METRICS_EVERY "
               "optimizer steps with the same isolated-reduction-program "
               "attribution bench.py uses.", "training",
               labels=("phase",)),
    MetricSpec("hvt_step_seconds", "histogram",
               "Sampled mean optimizer-step wall time over each "
               "sampling window.", "training", buckets=_STEP_BUCKETS),
    MetricSpec("hvt_examples_per_sec", "gauge",
               "Global examples/second over the last sampling window.",
               "training"),
    MetricSpec("hvt_mfu", "gauge",
               "Live model-FLOPs utilization vs the resolved per-chip "
               "peak (XLA cost-model flops; custom-call kernels "
               "under-count — bench rows stay the calibrated source).",
               "training"),
    MetricSpec("hvt_peak_flops_per_chip", "gauge",
               "The per-chip peak FLOP/s the MFU gauge divides by "
               "(HVT_PEAK_FLOPS override, TPU table, or calibrated).",
               "training"),
    MetricSpec("hvt_accum_k", "gauge",
               "Gradient-accumulation factor K of the running trainer.",
               "training"),
    MetricSpec("hvt_optimizer_steps_total", "counter",
               "Optimizer steps completed by this process's fits.",
               "training"),
    MetricSpec("hvt_step_samples_total", "counter",
               "Times the step-phase sampler ran (one per "
               "HVT_METRICS_EVERY window).", "training"),
    MetricSpec("hvt_step_skew_ms", "gauge",
               "Cross-rank skew over the last sampled window: max - "
               "median of the fleet's per-step blocked times (host "
               "seconds in the step call + drain — the waiting ranks' "
               "block IS the straggler's lead, in both dispatch "
               "regimes). Published by the SkewProbe (HVT_SKEW_PROBE) "
               "on multi-process runs with the trainer exporter on.",
               "training"),
    MetricSpec("hvt_straggler_rank", "gauge",
               "Process rank the fleet waited on over the last sampled "
               "window (the rank with the SMALLEST blocked time; "
               "meaningful when hvt_step_skew_ms is materially > 0).",
               "training"),
    MetricSpec("hvt_barrier_wait_ms", "gauge",
               "THIS rank's per-step blocked time beyond the fleet "
               "minimum over the last sampled window, ms — its implicit "
               "wait for the slowest rank (stragglers read ~0 while "
               "everyone else pays).", "training"),
    # --- data ---------------------------------------------------------------
    MetricSpec("hvt_data_retries_total", "counter",
               "Bounded-retry outcomes of the data layer's transient-"
               "read discipline (data.stream.RETRY_STATS): "
               "outcome=retried counts absorbed faults, "
               "outcome=exhausted counts reads whose whole budget was "
               "spent (the degrade/fail-fast escalations).", "data",
               labels=("outcome",)),
    MetricSpec("hvt_data_batches_served_total", "counter",
               "Batches the hvt-data dispatcher streamed to clients, "
               "per admitted job.", "data", labels=("job",)),
    MetricSpec("hvt_data_admissions_total", "counter",
               "hvt-data (job, shard) admissions — spec-carrying hellos "
               "registered (and journaled) by the dispatcher.", "data",
               labels=("job",)),
    MetricSpec("hvt_data_cursor_refusals_total", "counter",
               "StreamCursor refusals the dispatcher sent over the wire "
               "(foreign format version, wrong engine kind, mismatched "
               "geometry) — pre-seeded to 0 at startup so a zero gate "
               "can distinguish 'none' from 'series absent'.", "data"),
    MetricSpec("hvt_data_jobs", "gauge",
               "Jobs currently admitted to this hvt-data dispatcher "
               "(journal-adopted jobs count).", "data"),
    MetricSpec("hvt_data_degraded_total", "counter",
               "Times this process's service client exhausted its retry "
               "budget and degraded to rank-local feeding from the same "
               "cursor (byte-identical fallback).", "data"),
    MetricSpec("hvt_data_reattach_total", "counter",
               "Times a degraded service client re-attached to the "
               "hvt-data dispatcher at an epoch boundary.", "data"),
    # --- obs (the export surface itself) ------------------------------------
    MetricSpec("hvt_scrapes_total", "counter",
               "GET /metrics requests this exporter answered.", "obs"),
    MetricSpec("hvt_trace_spans_dropped_total", "counter",
               "Trace spans lost to a dead span writer (HVT_TRACE_DIR "
               "unwritable/torn) — the writer fails once silently to "
               "protect training, this counter makes the loss visible.",
               "obs"),
])


class UnknownMetricError(KeyError):
    """A metric was emitted that is not declared in this registry."""

    def __init__(self, name: str):
        super().__init__(
            f"{name} is not a declared metric — add a MetricSpec row to "
            "horovod_tpu/obs/core.py (kind, help, subsystem, labels, "
            "buckets) so the exposition catalog stays the single source "
            "of truth (hvt-lint HVT009 checks this statically)"
        )


def spec(name: str) -> MetricSpec:
    try:
        return METRICS[name]
    except KeyError:
        raise UnknownMetricError(name) from None


def is_declared(name: str) -> bool:
    return name in METRICS


def _label_key(s: MetricSpec, labels: dict) -> tuple:
    if set(labels) != set(s.labels):
        raise ValueError(
            f"{s.name}: labels {sorted(labels)} do not match the declared "
            f"label set {sorted(s.labels)}"
        )
    return tuple(str(labels[lb]) for lb in s.labels)


class _Hist:
    """One histogram series: per-edge counts + sum + count."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_edges: int):
        self.counts = [0] * n_edges  # per-edge (non-cumulative) counts
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float, edges: tuple) -> None:
        for i, edge in enumerate(edges):
            if value <= edge:
                self.counts[i] += 1
                break
        self.sum += value
        self.count += 1


class Registry:
    """Process-local, thread-safe instrument store over the global
    declarations. See the module docstring for when to use a private
    instance vs the module-level default."""

    def __init__(self):
        self._lock = threading.Lock()
        # (name, label-values tuple) -> float | _Hist
        self._series: dict[tuple, object] = {}
        self._collectors: list = []

    # -- emission -----------------------------------------------------------

    def counter(self, name: str, inc: float = 1.0, **labels) -> None:
        s = spec(name)
        if s.kind != "counter":
            raise ValueError(f"{name} is a {s.kind}, not a counter")
        if inc < 0:
            raise ValueError(f"{name}: counters only go up (inc={inc})")
        key = (name, _label_key(s, labels))
        with self._lock:
            self._series[key] = float(self._series.get(key, 0.0)) + inc

    def counter_set(self, name: str, total: float, **labels) -> None:
        """Mirror a lifetime total whose monotonic source of truth lives
        elsewhere (the restart journal, ``RETRY_STATS``) — the collector
        idiom; never mix with `counter` on the same series."""
        s = spec(name)
        if s.kind != "counter":
            raise ValueError(f"{name} is a {s.kind}, not a counter")
        key = (name, _label_key(s, labels))
        with self._lock:
            self._series[key] = float(total)

    def gauge(self, name: str, value: float, **labels) -> None:
        s = spec(name)
        if s.kind != "gauge":
            raise ValueError(f"{name} is a {s.kind}, not a gauge")
        key = (name, _label_key(s, labels))
        with self._lock:
            self._series[key] = float(value)

    def histogram(self, name: str, value: float, **labels) -> None:
        s = spec(name)
        if s.kind != "histogram":
            raise ValueError(f"{name} is a {s.kind}, not a histogram")
        key = (name, _label_key(s, labels))
        with self._lock:
            h = self._series.get(key)
            if h is None:
                h = self._series[key] = _Hist(len(s.buckets))
            h.observe(float(value), s.buckets)

    # -- scrape side --------------------------------------------------------

    def register_collector(self, fn) -> None:
        """``fn(registry)`` runs at every `collect()`, just before a
        scrape renders — for values read on demand (queue depths, module
        counters). Exceptions are swallowed per collector. Registering
        the SAME callable again is a no-op, so long-lived emitters (the
        trainer exporter re-registers per fit) can re-assert their
        collector after a `reset()` without stacking duplicates."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def collect(self) -> list:
        """``[(spec, [(label_values, value_or_Hist), ...]), ...]`` in
        declaration order — the exposition's input (prom.render)."""
        for fn in list(self._collectors):
            try:
                fn(self)
            except Exception:
                pass  # a broken gauge must never take down the scrape
        with self._lock:
            items = list(self._series.items())
        by_name: dict[str, list] = {}
        for (name, lv), value in items:
            by_name.setdefault(name, []).append((lv, value))
        out = []
        for name, s in METRICS.items():
            if name in by_name:
                out.append((s, sorted(by_name[name], key=lambda kv: kv[0])))
        return out

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._collectors.clear()


_DEFAULT = Registry()


def default_registry() -> Registry:
    return _DEFAULT


def counter(name: str, inc: float = 1.0, **labels) -> None:
    _DEFAULT.counter(name, inc, **labels)


def counter_set(name: str, total: float, **labels) -> None:
    _DEFAULT.counter_set(name, total, **labels)


def gauge(name: str, value: float, **labels) -> None:
    _DEFAULT.gauge(name, value, **labels)


def histogram(name: str, value: float, **labels) -> None:
    _DEFAULT.histogram(name, value, **labels)


def register_collector(fn) -> None:
    _DEFAULT.register_collector(fn)


def reset() -> None:
    """Clear the default registry (tests)."""
    _DEFAULT.reset()
