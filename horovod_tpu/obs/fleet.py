"""Fleet metrics rollup — ONE Prometheus scrape target per job.

PR 13 put a ``/metrics`` exporter on every long-lived process: the
supervisor status server and one trainer exporter per rank
(``HVT_METRICS_PORT + local_rank``). Operationally that is N+1 scrape
targets per job whose ports depend on fleet size — exactly the config
sprawl a fleet scheduler (ROADMAP item 5) cannot hand to Prometheus.
This module is the join the supervisor's ``GET /fleet`` route serves:

* scrape each live member's trainer exporter (`scrape`);
* re-label every member series with ``rank`` (`merge_fleet` — text-level
  label injection, because the typed registry rightly refuses label sets
  that don't match a series' declaration, and the member series are
  *already* rendered expositions);
* compute fleet-level summary series the single panes can't see
  (``hvt_fleet_step_ms{stat="slowest"|"fastest"}`` from the members'
  ``hvt_step_phase_ms{phase="total"}``);
* splice it all into the supervisor's own exposition, one HELP/TYPE
  block per family, so the result is a single valid scrape body.

Deliberately stdlib-only (urllib + re): the supervisor never imports
jax.
"""

from __future__ import annotations

import re
import urllib.request

from horovod_tpu.obs import core, prom

# One exposition sample line: name, optional {labels}, value.
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$"
)
# Histogram child-series suffixes — their family is the base name.
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def scrape(url: str, timeout: float = 2.0) -> str | None:
    """One member exporter's exposition text, or None when the member
    is unreachable (dead, restarting, not yet bound) — a fleet scrape
    must degrade to the ranks it can see, never fail outright."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except (OSError, ValueError):
        return None


def inject_rank(line: str, rank) -> str:
    """Rewrite one sample line to carry ``rank="<rank>"`` alongside its
    existing labels."""
    m = _SAMPLE_RE.match(line)
    if not m:
        return line
    name, labels, value = m.groups()
    inner = labels[1:-1] if labels else ""
    pair = f'rank="{prom.escape_label_value(str(rank))}"'
    inner = f"{inner},{pair}" if inner else pair
    return f"{name}{{{inner}}} {value}"


def _family_of(name: str, families: dict) -> str:
    if name in families:
        return name
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix) and name[: -len(suffix)] in families:
            return name[: -len(suffix)]
    return name


def fleet_summary(members: dict) -> str:
    """The computed fleet series, rendered: slowest/fastest rank step
    time from the members' ``hvt_step_phase_ms{phase="total"}`` gauges.
    Empty when no member carries the series yet (samplers warm up)."""
    totals = []
    for text in members.values():
        try:
            values = prom.parse_text(text)
        except ValueError:
            continue  # a torn member scrape must not kill the rollup
        v = values.get('hvt_step_phase_ms{phase="total"}')
        if v is not None:
            totals.append(v)
    if not totals:
        return ""
    reg = core.Registry()
    reg.gauge("hvt_fleet_step_ms", max(totals), stat="slowest")
    reg.gauge("hvt_fleet_step_ms", min(totals), stat="fastest")
    return prom.render(reg)


def merge_fleet(supervisor_text: str, members: dict) -> str:
    """Splice the supervisor's exposition, each member's rank-labeled
    exposition, and the computed fleet summary into one valid scrape
    body. ``members`` maps rank (int or str) → that rank's exposition
    text; family HELP/TYPE blocks are emitted once (first writer wins —
    every emitter renders from the same declarations, so they agree)."""
    families: dict[str, dict] = {}
    order: list[str] = []

    def feed(text: str, rank=None) -> None:
        for line in text.splitlines():
            line = line.rstrip()
            if not line:
                continue
            if line.startswith(("# HELP ", "# TYPE ")):
                parts = line.split(" ", 3)
                if len(parts) < 3:
                    continue
                name = parts[2]
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = {
                        "help": None, "type": None, "samples": []
                    }
                    order.append(name)
                key = "help" if parts[1] == "HELP" else "type"
                if fam[key] is None:
                    fam[key] = line
            elif line.startswith("#"):
                continue
            else:
                m = _SAMPLE_RE.match(line)
                if not m:
                    continue
                name = _family_of(m.group(1), families)
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = {
                        "help": None, "type": None, "samples": []
                    }
                    order.append(name)
                fam["samples"].append(
                    inject_rank(line, rank) if rank is not None else line
                )

    feed(supervisor_text)
    for rank in sorted(members, key=str):
        feed(members[rank], rank=rank)
    summary = fleet_summary(members)
    if summary:
        feed(summary)
    lines: list[str] = []
    for name in order:
        fam = families[name]
        if not fam["samples"]:
            continue
        if fam["help"]:
            lines.append(fam["help"])
        if fam["type"]:
            lines.append(fam["type"])
        lines.extend(fam["samples"])
    return "\n".join(lines) + "\n" if lines else ""
