"""Prometheus text-format exposition over the typed registry (obs/core.py).

`render` turns a `Registry.collect()` into the text exposition format
(version 0.0.4 — the format every Prometheus/VictoriaMetrics/Grafana-agent
scraper speaks): ``# HELP``/``# TYPE`` per family, one sample line per
series, histograms as cumulative ``_bucket{le=...}`` series with the
``+Inf`` bucket, ``_sum`` and ``_count``. The invariants promtool lints —
HELP/TYPE present for every family, bucket counts monotonically
non-decreasing, ``+Inf`` == ``_count`` — hold by construction and are
asserted in tests/test_obs.py against golden output.

`parse_text` is the inverse the CI gate uses (`launch/job.py`
``metrics_checks:``): a minimal parser of the same format back into
``{series_name: value}`` so a supervisor's final scrape dump is gateable
with the existing ``lo..hi`` range grammar.
"""

from __future__ import annotations

import math

from horovod_tpu.obs import core

# The exposition content type every scrape endpoint must serve.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(value: float) -> str:
    """Sample-value formatting: integers render bare (promtool-friendly),
    specials use Prometheus spellings."""
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(text: str) -> str:
    return (
        text.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels_str(names: tuple, values: tuple, extra: tuple = ()) -> str:
    pairs = [
        f'{n}="{escape_label_value(v)}"' for n, v in zip(names, values)
    ] + [f'{n}="{escape_label_value(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render(registry: core.Registry | None = None) -> str:
    """The full ``GET /metrics`` body for ``registry`` (default: the
    module-level default registry)."""
    reg = registry if registry is not None else core.default_registry()
    lines: list[str] = []
    for spec, series in reg.collect():
        lines.append(f"# HELP {spec.name} {escape_help(spec.help)}")
        lines.append(f"# TYPE {spec.name} {spec.kind}")
        for label_values, value in series:
            if spec.kind == "histogram":
                cum = 0
                for edge, n in zip(spec.buckets, value.counts):
                    cum += n
                    lab = _labels_str(
                        spec.labels, label_values, extra=(("le", _fmt(edge)),)
                    )
                    lines.append(f"{spec.name}_bucket{lab} {cum}")
                lab = _labels_str(
                    spec.labels, label_values, extra=(("le", "+Inf"),)
                )
                lines.append(f"{spec.name}_bucket{lab} {value.count}")
                base = _labels_str(spec.labels, label_values)
                lines.append(f"{spec.name}_sum{base} {_fmt(value.sum)}")
                lines.append(f"{spec.name}_count{base} {value.count}")
            else:
                lab = _labels_str(spec.labels, label_values)
                lines.append(f"{spec.name}{lab} {_fmt(value)}")
    return "\n".join(lines) + "\n" if lines else ""


def write_http(handler, registry: core.Registry | None = None) -> None:
    """Render ``registry`` and write it as a complete HTTP 200 response
    on a ``BaseHTTPRequestHandler`` — the ONE implementation of the
    ``GET /metrics`` response shared by every mount point (the
    supervisor status server, the serving server, obs/server.py), so
    the content type and framing cannot drift between panes."""
    body = render(registry).encode()
    handler.send_response(200)
    handler.send_header("Content-Type", CONTENT_TYPE)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def parse_text(text: str) -> dict:
    """Parse a text exposition back into ``{series: value}``.

    Keys are the bare family name for unlabeled series and
    ``name{label="v",...}`` (exactly as rendered) for labeled ones; both
    spellings gate with `launch.job`'s ``metrics_checks:``. Comment and
    blank lines are skipped; a malformed line raises (a gate reading a
    torn dump must fail loudly, not pass vacuously)."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # Split at the LAST space: label values may contain escaped
        # spaces-free content, but be defensive about future timestamps.
        name, _, value = line.rpartition(" ")
        if not name:
            raise ValueError(f"malformed exposition line: {line!r}")
        out[name] = float(value)
    return out
