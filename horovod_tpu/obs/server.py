"""The metrics exporter HTTP server — ``GET /metrics`` for any process.

Two consumers:

* the **trainer-side exporter** (``HVT_METRICS_PORT``): every training
  process serves its own live step-phase gauges (`ensure_trainer_exporter`
  — the feeding paths call it once per process; port = base + local rank,
  so co-located processes don't collide). It additionally mounts
  ``POST /profile?seconds=N``: an on-demand `jax.profiler` capture of the
  next N seconds into ``HVT_TRACE_DIR`` (or ``HVT_PROFILE``), so a slow
  step can be drilled into without relaunching with profiling on — and
  ``POST /flightrecord``: an on-demand dump of this process's collective
  flight record (`horovod_tpu.flight`), the live-fleet entry into
  ``hvt-sched replay``.
* **any other long-lived process** wanting a standalone scrape port
  (`start_metrics_server` with an explicit registry). The supervisor and
  the serving server instead mount ``/metrics`` on their existing HTTP
  surfaces (launch/supervisor.py, launch/serve.py) — one pane of glass,
  no extra ports.

Binds loopback by default (`HVT_STATUS_HOST`), like the supervisor status
server: the routes are unauthenticated."""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from horovod_tpu.analysis import registry as knob_registry
from horovod_tpu.obs import core, prom


class _ProfileTrigger:
    """One in-flight on-demand profiler capture per process. jax.profiler
    supports a single active trace; concurrent POSTs get 409."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: str | None = None

    def start(self, seconds: float) -> str:
        out_root = (
            knob_registry.get_str("HVT_TRACE_DIR")
            or knob_registry.get_str("HVT_PROFILE")
        )
        if not out_root:
            raise ValueError(
                "on-demand profiling needs HVT_TRACE_DIR or HVT_PROFILE "
                "set — the capture has nowhere to land"
            )
        seconds = float(seconds)
        if not 0 < seconds <= 600:
            raise ValueError("seconds must be in (0, 600]")
        # Import BEFORE claiming the slot: a failed import after
        # `_active` is set would wedge the trigger in 409 forever.
        import jax

        with self._lock:
            if self._active is not None:
                raise RuntimeError(
                    f"a capture is already running ({self._active})"
                )
            out_dir = os.path.join(
                out_root, f"profile-{time.strftime('%Y%m%d-%H%M%S')}"
            )
            self._active = out_dir
        try:
            jax.profiler.start_trace(out_dir)
        except BaseException:
            with self._lock:
                self._active = None
            raise

        def stop():
            time.sleep(seconds)
            try:
                jax.profiler.stop_trace()
            finally:
                with self._lock:
                    self._active = None

        threading.Thread(target=stop, daemon=True).start()
        return out_dir


def start_metrics_server(port: int, host: str | None = None,
                         registry: core.Registry | None = None,
                         profile: bool = False):
    """Serve ``GET /metrics`` (+ ``GET /healthz``; ``POST /profile`` when
    ``profile=True``) for ``registry`` (default: the process default).
    Port 0 binds ephemerally — ``server.server_address[1]`` carries the
    real one. Returns the started server; callers own ``shutdown()``."""
    if host is None:
        host = knob_registry.get_str("HVT_STATUS_HOST")
    reg = registry if registry is not None else core.default_registry()
    trigger = _ProfileTrigger() if profile else None

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # scrapes are noise
            pass

        def _send(self, code: int, body: bytes, ctype: str):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, payload: dict):
            self._send(code, json.dumps(payload).encode(),
                       "application/json")

        def do_GET(self):
            try:
                path = urlparse(self.path).path
                if path == "/metrics":
                    reg.counter("hvt_scrapes_total")
                    prom.write_http(self, reg)
                elif path == "/healthz":
                    self._send_json(200, {"status": "ok"})
                else:
                    self._send_json(404, {"error": f"no route {path}"})
            except Exception as e:  # observability must never crash
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"})

        def do_POST(self):
            try:
                url = urlparse(self.path)
                if url.path == "/flightrecord":
                    # On-demand dump of this process's collective flight
                    # record (horovod_tpu.flight) — the live-fleet
                    # counterpart of the supervisor's hang collection:
                    # grab every rank's /flightrecord, then
                    # `hvt-sched replay` the directory.
                    from horovod_tpu import flight

                    rec = flight.RECORDER
                    if rec is None:
                        self._send_json(409, {
                            "error": "flight recorder is off — set "
                            "HVT_FLIGHT_RECORD to a directory and "
                            "relaunch",
                        })
                        return
                    self._send_json(200, {
                        "path": rec.dump(),
                        "records": rec.count,
                        "seq": rec.seq,
                    })
                    return
                if url.path != "/profile" or trigger is None:
                    self._send_json(404, {"error": f"no route {url.path}"})
                    return
                q = parse_qs(url.query)
                seconds = float(q.get("seconds", ["5"])[0])
                try:
                    out_dir = trigger.start(seconds)
                except RuntimeError as e:
                    self._send_json(409, {"error": str(e)})
                    return
                except ValueError as e:
                    self._send_json(400, {"error": str(e)})
                    return
                self._send_json(
                    200, {"profiling": out_dir, "seconds": seconds}
                )
            except Exception as e:
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"})

    server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def _retry_collector(reg) -> None:
    """Mirror the data layer's transient-read retry total at scrape
    time: the stream module owns the monotonic truth (``RETRY_STATS``),
    the scrape just reads it. A NAMED module-level function so
    re-registration dedupes by identity."""
    from horovod_tpu.data import stream as stream_lib

    reg.counter_set(
        "hvt_data_retries_total", stream_lib.RETRY_STATS["retried"],
        outcome="retried",
    )
    reg.counter_set(
        "hvt_data_retries_total", stream_lib.RETRY_STATS["exhausted"],
        outcome="exhausted",
    )


_trainer_exporter = None
_trainer_exporter_lock = threading.Lock()


def ensure_trainer_exporter():
    """Start this process's trainer-side exporter once, when
    ``HVT_METRICS_PORT`` is set (opt-in): port = base + local rank, so
    `hvt-launch run --nprocs N --metrics-port P` yields one scrapeable
    exporter per process at P..P+N-1. Returns the server (or None when
    the knob is unset). Idempotent; survives across fits — the exporter
    is a property of the process, not of one fit call."""
    global _trainer_exporter
    base = knob_registry.get_int("HVT_METRICS_PORT")
    if base is None:
        return None
    with _trainer_exporter_lock:
        # Re-registered on EVERY call (each fit), not just at server
        # start: `obs.reset()` clears collectors, and the once-per-
        # process server guard would otherwise leave the retries series
        # silently absent afterwards. Registration dedupes by callable
        # identity, so this never stacks. Same treatment for the span
        # writer's drop mirror (trace.py registers it at writer open /
        # on drops — this covers a reset in between).
        from horovod_tpu import trace as trace_lib

        core.register_collector(trace_lib._dropped_spans_collector)
        core.register_collector(_retry_collector)
        if _trainer_exporter is None:
            from horovod_tpu import runtime

            port = 0 if base == 0 else base + runtime.local_rank()
            _trainer_exporter = start_metrics_server(port, profile=True)
        return _trainer_exporter


def trainer_exporter():
    """The running trainer exporter, or None (tests reach the bound port
    through ``server.server_address``)."""
    return _trainer_exporter
