"""Cross-rank span timeline — the ``HOROVOD_TIMELINE`` parity layer
(arXiv:1802.05799 §"Horovod Timeline"), fleet-merged.

PR 13 gave every process a rank-tagged JSONL span stream under
``HVT_TRACE_DIR`` (`trace.span`) and PR 14 a per-rank collective flight
record — but nothing ever JOINED ranks: no merged timeline, no straggler
attribution. Communication-characterization studies of distributed
training (arXiv:1810.11112) show cross-rank *skew*, not mean step time,
is what predicts scaling loss, and skew is invisible in any one rank's
stream. This module is the join:

* `load_spans` / `load_flight` — read every ``spans-rank*-pid*.jsonl``
  (and, when present, ``flight-*.jsonl``) under one trace dir;
* `align` — put all ranks on ONE clock. Ranks are grouped by the host
  that stamped their spans (same host = same clock, offset 0 by
  construction); cross-host offsets are estimated from the shared
  per-step span boundaries as correlation anchors — every rank ends
  optimizer step k at the same TRUE time to within one collective, so
  the median of per-step end deltas against the reference host IS the
  clock offset, and the remaining spread (MAD) is the reported residual
  alignment error. Alignment REFUSES (`TimelineError`) when a host
  shares no common step anchors with the reference — merging unaligned
  clocks would fabricate skew.
* `chrome_trace` — one Chrome trace-event JSON (`chrome://tracing` /
  Perfetto): one ``pid`` per rank, ``tid`` per span depth, complete
  (``ph: "X"``) events carrying span attrs in ``args``; flight-recorded
  collective submissions become instant (``ph: "i"``) events keyed by
  seq on a dedicated lane, landing under their enclosing step span on
  the aligned clock.
* `skew` — per-step cross-rank analytics: end-margin straggler score,
  barrier-wait attribution (time between a rank's step end and the
  slowest rank's), duration spreads — and a named straggler with the
  evidence.

**What "slowest" means here.** A ``step`` span measures the host-side
call of the compiled step, and that call sits in one of two regimes:
*synchronous* (the call blocks through the collective — then every
rank's span ENDS at the barrier together, and the rank the fleet waited
on is the one that STARTED late and/or ran short while the others' spans
absorbed the wait), or *async-dispatch* (the call returns at enqueue —
then the straggler's whole cycle, start AND end, drifts late relative
to its peers). Measured on this framework (the 2-proc CPU acceptance
run): sync — a ``slow:50`` rank starts +50 ms late, ends ON the
barrier, and the victim rank's span is 50 ms LONGER. The signal robust
in BOTH regimes is the aligned step START margin — the straggler is the
late starter — with barrier wait estimated as (end gap) + (duration
beyond the fleet minimum), which collapses to the right quantity in
each regime. Duration spreads are reported alongside.

**The cross-host blind spot, stated honestly.** End-time attribution is
authoritative WITHIN a host (shared clock, zero alignment error).
ACROSS hosts the step anchors are the only clock witness, so a rank
that is *constantly* late by the same margin is indistinguishable from
a rank whose clock is behind by that margin — the alignment absorbs a
constant cross-host lateness into the offset, and only its VARIANCE
(the residual) and the duration spreads survive. The live `SkewProbe`
(training/trainer.py) has no such blind spot — its allgather is a true
cross-host rendezvous — which is the division of labor: spans for
per-step forensics and same-host attribution, the probe for live
cross-host skew.

Deliberately stdlib-only: the ``hvt-trace`` CLI and the supervisor both
import this module without jax.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import statistics

__all__ = [
    "TimelineError", "Alignment", "load_spans", "load_flight", "align",
    "chrome_trace", "phase_table", "phase_attribution", "render_report",
    "skew", "render_skew",
]

SPAN_FILE_RE = re.compile(r"^spans-rank(\d+)-pid(\d+)\.jsonl$")
FLIGHT_FILE_RE = re.compile(r"^flight-(.+)\.jsonl$")
# The flight lane's tid — far above any real span depth, so Perfetto
# renders collective submissions on their own track per rank.
FLIGHT_TID = 1000


class TimelineError(Exception):
    """A trace dir that cannot be merged: no span files, or a host whose
    spans share no step anchors with the reference clock."""


def load_spans(trace_dir: str) -> dict[int, list[dict]]:
    """``{rank: [span, ...]}`` from every ``spans-rank*-pid*.jsonl``
    under ``trace_dir``, each rank's spans sorted by start time. A rank
    restarted by the supervisor leaves one file per pid — all are
    loaded (the ``pid`` field stays on each record). Torn trailing
    lines (a process killed mid-write) are skipped, not fatal: spans
    are evidence, and the evidence of a crash is exactly when they
    matter."""
    by_rank: dict[int, list[dict]] = {}
    if not os.path.isdir(trace_dir):
        raise TimelineError(f"{trace_dir} is not a directory")
    for name in sorted(os.listdir(trace_dir)):
        m = SPAN_FILE_RE.match(name)
        if not m:
            continue
        rank = int(m.group(1))
        with open(os.path.join(trace_dir, name)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line
                if not isinstance(rec, dict) or "ts" not in rec:
                    continue
                by_rank.setdefault(rank, []).append(rec)
    if not by_rank:
        raise TimelineError(
            f"no spans-rank*-pid*.jsonl files under {trace_dir} — was the "
            "run launched with HVT_TRACE_DIR set?"
        )
    for spans in by_rank.values():
        spans.sort(key=lambda s: s.get("ts", 0.0))
    return by_rank


def load_flight(trace_dir: str) -> dict[int, list[dict]]:
    """Flight-recorder JSONLs (``flight-<member>.jsonl``, PR 14) living
    beside the span files, keyed to a rank when the member label carries
    one (``rank3``, ``m3``); unmappable labels are skipped — the
    timeline can only place a submission lane under a rank it has spans
    for. Returns ``{}`` when none exist (flight records are optional
    garnish on the timeline)."""
    out: dict[int, list[dict]] = {}
    if not os.path.isdir(trace_dir):
        return out
    for name in sorted(os.listdir(trace_dir)):
        m = FLIGHT_FILE_RE.match(name)
        if not m:
            continue
        label = m.group(1)
        lm = re.fullmatch(r"(?:rank|m)(\d+)", label)
        if not lm:
            continue
        rank = int(lm.group(1))
        recs = []
        with open(os.path.join(trace_dir, name)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "seq" in rec and "t" in rec:
                    recs.append(rec)
        if recs:
            recs.sort(key=lambda r: r["seq"])
            out[rank] = recs
    return out


def _span_host(span: dict, rank: int) -> str:
    # Pre-host span files (PR 13) get a per-rank pseudo-host: without a
    # shared-clock witness each rank must be aligned independently.
    return str(span.get("host") or f"rank{rank}")


def _step_table(spans: list[dict]) -> dict[tuple, tuple]:
    """``{(epoch, step): (start, end, dur_s)}`` from the rank's ``step``
    spans. Duplicate keys (a restarted epoch re-training the same steps)
    keep the LATEST occurrence — the run that actually completed."""
    table: dict[tuple, tuple] = {}
    for s in spans:
        if s.get("name") != "step":
            continue
        if "epoch" not in s or "step" not in s:
            continue
        try:
            key = (int(s["epoch"]), int(s["step"]))
            start = float(s["ts"])
            dur = float(s.get("dur_s", 0.0))
        except (TypeError, ValueError):
            continue
        if key not in table or start >= table[key][0]:
            table[key] = (start, start + dur, dur)
    return table


def _step_anchors(spans: list[dict]) -> dict[tuple, float]:
    """``{(epoch, step): end time}`` — the clock-correlation anchors
    (step ENDS: in the synchronous-dispatch regime they sit exactly on
    the cross-rank barrier; in the async regime they inherit the same
    offset as starts)."""
    return {k: v[1] for k, v in _step_table(spans).items()}


@dataclasses.dataclass
class Alignment:
    """Per-rank clock offsets onto the reference host's clock.

    ``offsets[rank]`` is ADDED to that rank's timestamps; ranks on the
    reference host carry 0.0 exactly, ranks sharing any other host carry
    that host's single estimated offset. ``residual_ms[host]`` is the
    median absolute deviation of the host's anchor deltas after
    alignment — the honest error bar on every cross-host comparison
    (same-host comparisons share a clock and carry no alignment error).
    """

    ref_rank: int
    ref_host: str
    offsets: dict[int, float]
    residual_ms: dict[str, float]
    anchor_counts: dict[str, int]
    hosts: dict[int, str]

    @property
    def max_residual_ms(self) -> float:
        return max(self.residual_ms.values(), default=0.0)


def align(by_rank: dict[int, list[dict]]) -> Alignment:
    """Estimate per-rank clock offsets from shared step anchors.

    Reference clock: the host of the lowest rank. Every other host's
    offset is the median over its ranks' common-step end deltas against
    the reference rank's ends; refuses with `TimelineError` when a host
    shares no common steps with the reference (nothing correlates the
    clocks — emitting a merged timeline anyway would fabricate order).
    """
    ranks = sorted(by_rank)
    ref_rank = ranks[0]
    hosts = {
        r: _span_host(by_rank[r][0], r) if by_rank[r] else f"rank{r}"
        for r in ranks
    }
    ref_host = hosts[ref_rank]
    ref_anchors = _step_anchors(by_rank[ref_rank])
    offsets: dict[int, float] = {}
    residual_ms: dict[str, float] = {ref_host: 0.0}
    anchor_counts: dict[str, int] = {}
    host_offset: dict[str, float] = {ref_host: 0.0}
    by_host: dict[str, list[int]] = {}
    for r in ranks:
        by_host.setdefault(hosts[r], []).append(r)
    anchor_counts[ref_host] = len(ref_anchors)
    for host, members in by_host.items():
        if host == ref_host:
            continue
        deltas: list[float] = []
        for r in members:
            anchors = _step_anchors(by_rank[r])
            common = set(anchors) & set(ref_anchors)
            deltas.extend(ref_anchors[k] - anchors[k] for k in common)
        anchor_counts[host] = len(deltas)
        if not deltas:
            raise TimelineError(
                f"cannot align host {host!r} (ranks "
                f"{members}): no step spans in common with the reference "
                f"rank {ref_rank} ({ref_host!r}) — the clocks have no "
                "correlation anchor. Re-run the jobs together (same "
                "HVT_TRACE_DIR, overlapping steps) or merge per host."
            )
        off = statistics.median(deltas)
        host_offset[host] = off
        residual_ms[host] = (
            statistics.median(abs(d - off) for d in deltas) * 1e3
        )
    for r in ranks:
        offsets[r] = host_offset[hosts[r]]
    return Alignment(
        ref_rank=ref_rank, ref_host=ref_host, offsets=offsets,
        residual_ms=residual_ms, anchor_counts=anchor_counts, hosts=hosts,
    )


# --- Chrome trace-event export ----------------------------------------------


def chrome_trace(
    by_rank: dict[int, list[dict]],
    alignment: Alignment | None = None,
    flight: dict[int, list[dict]] | None = None,
) -> dict:
    """The Chrome trace-event JSON object (``chrome://tracing`` /
    Perfetto "JSON" format): ``pid`` = rank, ``tid`` = span depth,
    complete events with span attrs in ``args``; flight submissions as
    instant events on the `FLIGHT_TID` lane. Timestamps are aligned to
    the reference clock and rebased so the earliest event sits at 0 µs.
    """
    alignment = alignment if alignment is not None else align(by_rank)
    flight = flight or {}
    core = {"name", "ts", "dur_s", "rank", "pid", "id", "parent", "depth",
            "host"}
    t0 = min(
        float(s["ts"]) + alignment.offsets[r]
        for r, spans in by_rank.items() for s in spans
    )
    events: list[dict] = []
    for rank in sorted(by_rank):
        events.append({
            "ph": "M", "pid": rank, "tid": 0, "name": "process_name",
            "args": {"name": f"rank {rank} ({alignment.hosts[rank]})"},
        })
        events.append({
            "ph": "M", "pid": rank, "tid": 0, "name": "process_sort_index",
            "args": {"sort_index": rank},
        })
        off = alignment.offsets[rank]
        for s in by_rank[rank]:
            args = {k: v for k, v in s.items() if k not in core}
            args["span_id"] = s.get("id")
            if s.get("parent") is not None:
                args["parent_id"] = s.get("parent")
            events.append({
                "ph": "X",
                "pid": rank,
                "tid": int(s.get("depth", 0)),
                "ts": (float(s["ts"]) + off - t0) * 1e6,
                "dur": float(s.get("dur_s", 0.0)) * 1e6,
                "name": str(s.get("name", "?")),
                "cat": "span",
                "args": args,
            })
        if rank in flight:
            events.append({
                "ph": "M", "pid": rank, "tid": FLIGHT_TID,
                "name": "thread_name",
                "args": {"name": "collective submissions"},
            })
            for rec in flight[rank]:
                args = {k: v for k, v in rec.items() if k != "t"}
                events.append({
                    "ph": "i",
                    "s": "t",
                    "pid": rank,
                    "tid": FLIGHT_TID,
                    "ts": (float(rec["t"]) + off - t0) * 1e6,
                    "name": f"{rec.get('kind', '?')}#{rec['seq']}",
                    "cat": "collective",
                    "args": args,
                })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "hvt-trace",
            "ref_rank": alignment.ref_rank,
            "clock_offsets_s": {
                str(r): alignment.offsets[r] for r in sorted(by_rank)
            },
            "alignment_residual_ms": dict(alignment.residual_ms),
        },
    }


# --- per-phase report --------------------------------------------------------


def phase_table(by_rank: dict[int, list[dict]]) -> dict[str, dict[int, dict]]:
    """``{span name: {rank: {count, total_s, mean_ms, max_ms}}}`` —
    the `hvt-trace report` payload, name-major so one row compares a
    phase across the fleet."""
    table: dict[str, dict[int, dict]] = {}
    for rank, spans in by_rank.items():
        for s in spans:
            name = str(s.get("name", "?"))
            cell = table.setdefault(name, {}).setdefault(
                rank, {"count": 0, "total_s": 0.0, "max_ms": 0.0}
            )
            dur = float(s.get("dur_s", 0.0))
            cell["count"] += 1
            cell["total_s"] += dur
            cell["max_ms"] = max(cell["max_ms"], dur * 1e3)
    for cells in table.values():
        for cell in cells.values():
            cell["mean_ms"] = cell["total_s"] * 1e3 / max(1, cell["count"])
    return table


def phase_attribution(trace_dir: str) -> dict[str, dict]:
    """Fleet-collapsed per-phase attribution — the `hvt-tune` evidence
    loader: ``{span name: {count, mean_ms, max_ms}}`` where ``mean_ms``
    is the MEDIAN of per-rank means (one slow rank cannot move the
    fleet's attribution) and ``count`` sums occurrences across ranks.
    Returns {} when the dir holds no span files."""
    try:
        table = phase_table(load_spans(trace_dir))
    except (TimelineError, OSError):
        return {}
    out: dict[str, dict] = {}
    for name, cells in table.items():
        means = sorted(c["mean_ms"] for c in cells.values())
        out[name] = {
            "count": sum(c["count"] for c in cells.values()),
            "mean_ms": means[len(means) // 2],
            "max_ms": max(c["max_ms"] for c in cells.values()),
        }
    return out


def render_report(by_rank: dict[int, list[dict]]) -> str:
    ranks = sorted(by_rank)
    table = phase_table(by_rank)
    lines = ["phase              " + "".join(f"rank{r:<12}" for r in ranks)]
    order = sorted(
        table,
        key=lambda n: -max(c["total_s"] for c in table[n].values()),
    )
    for name in order:
        cells = []
        for r in ranks:
            c = table[name].get(r)
            cells.append(
                f"{c['mean_ms']:8.2f}ms x{c['count']:<5}" if c
                else " " * 16
            )
        lines.append(f"{name:<19}" + "".join(cells))
    lines.append(
        "(mean duration x count per rank, phases ordered by total time)"
    )
    return "\n".join(lines)


# --- skew analytics ----------------------------------------------------------


def skew(
    by_rank: dict[int, list[dict]],
    alignment: Alignment | None = None,
    threshold_pct: float = 5.0,
) -> dict:
    """Per-step cross-rank skew over the common steps of all ranks.

    For each (epoch, step) present on EVERY rank, on the aligned clock:

    * **start margin** — each rank's step START minus the fleet median
      start: the regime-robust straggler signal (module docstring — in
      the synchronous-dispatch regime ends sit on the barrier together
      and only the starts discriminate; in the async regime starts and
      ends drift late together).
    * **straggler score** — the fraction of common steps a rank is the
      LAST to start by more than ``threshold_pct`` of the fleet's
      median step period (floored at 1 ms so sub-ms CI steps don't
      flag on scheduler noise).
    * **barrier wait** — per rank, the mean of (latest end − own end)
      + (own duration − fleet-min duration): the time the rank spent
      beyond the fleet's fastest cycle, i.e. waiting. Collapses to the
      end gap in the async regime and to the duration gap in the sync
      regime; the straggler's is ~0 while everyone else pays — the
      attribution evidence.
    * **duration spread** — max − median of per-rank mean durations for
      ``step`` (and ``reduction`` when sampled).

    The named ``straggler`` requires a majority score (> 0.5); below
    that the verdict is None ("no consistent straggler") — one noisy
    step must not name a culprit.
    """
    alignment = alignment if alignment is not None else align(by_rank)
    ranks = sorted(by_rank)
    tables = {r: _step_table(by_rank[r]) for r in ranks}
    common = sorted(set.intersection(*(set(tables[r]) for r in ranks)))
    if not common:
        raise TimelineError(
            "no (epoch, step) step spans common to every rank — skew "
            "needs at least one step the whole fleet trained"
        )
    off = alignment.offsets
    starts = {
        r: [tables[r][k][0] + off[r] for k in common] for r in ranks
    }
    ends = {
        r: [tables[r][k][1] + off[r] for k in common] for r in ranks
    }
    durs = {r: [tables[r][k][2] for k in common] for r in ranks}
    # Fleet step period: median spacing of the fleet-max end times —
    # the threshold's denominator (durations can be dispatch-thin).
    fleet_end = [max(ends[r][i] for r in ranks) for i in range(len(common))]
    period = (
        statistics.median(
            fleet_end[i + 1] - fleet_end[i]
            for i in range(len(fleet_end) - 1)
        ) if len(fleet_end) > 1 else 0.0
    )
    tau = max(threshold_pct / 100.0 * period, 1e-3)
    per_rank: dict[int, dict] = {
        r: {"straggler_steps": 0, "barrier_wait_s": 0.0, "margin_s": []}
        for r in ranks
    }
    spread_ms: list[float] = []
    for i in range(len(common)):
        step_starts = {r: starts[r][i] for r in ranks}
        med = statistics.median(step_starts.values())
        latest = max(step_starts.values())
        last_rank = max(step_starts, key=lambda r: (step_starts[r], r))
        latest_end = max(ends[r][i] for r in ranks)
        min_dur = min(durs[r][i] for r in ranks)
        spread_ms.append((latest - med) * 1e3)
        for r in ranks:
            per_rank[r]["barrier_wait_s"] += (
                (latest_end - ends[r][i]) + (durs[r][i] - min_dur)
            )
            per_rank[r]["margin_s"].append(step_starts[r] - med)
        if latest - med > tau:
            per_rank[last_rank]["straggler_steps"] += 1
    n = len(common)
    dur_means = {r: statistics.mean(durs[r]) * 1e3 for r in ranks}
    table = phase_table(by_rank)
    red_means = {
        r: c["mean_ms"] for r, c in table.get("reduction", {}).items()
    }
    out_ranks = {}
    for r in ranks:
        margins = per_rank[r]["margin_s"]
        out_ranks[r] = {
            "straggler_score": per_rank[r]["straggler_steps"] / n,
            "barrier_wait_ms_mean": per_rank[r]["barrier_wait_s"] / n * 1e3,
            "start_margin_ms_median": statistics.median(margins) * 1e3,
            "step_dur_ms_mean": dur_means[r],
        }
    best = max(ranks, key=lambda r: out_ranks[r]["straggler_score"])
    # Majority score AND a minimum sample: at n < 3 common steps the
    # period (and so the threshold) is meaningless and a single jittery
    # step would name a culprit with 100% confidence — the documented
    # "one noisy step must not name a culprit" invariant.
    straggler = (
        best
        if n >= 3 and out_ranks[best]["straggler_score"] > 0.5
        else None
    )

    def _dur_spread(means: dict) -> float:
        if len(means) < 2:
            return 0.0
        vals = sorted(means.values())
        return vals[-1] - statistics.median(vals)

    report = {
        "ranks": ranks,
        "common_steps": n,
        "threshold_ms": tau * 1e3,
        "step_period_ms": period * 1e3,
        "alignment_residual_ms": dict(alignment.residual_ms),
        "skew_ms_mean": statistics.mean(spread_ms),
        "skew_ms_max": max(spread_ms),
        "dur_spread_ms": {
            "step": _dur_spread(dur_means),
            "reduction": _dur_spread(red_means),
        },
        "per_rank": out_ranks,
        "straggler": straggler,
    }
    if straggler is not None:
        waiters = [r for r in ranks if r != straggler]
        wait = statistics.mean(
            out_ranks[r]["barrier_wait_ms_mean"] for r in waiters
        ) if waiters else 0.0
        report["evidence"] = (
            f"rank {straggler} was the last to start "
            f"{out_ranks[straggler]['straggler_score']:.0%} of {n} common "
            f"steps (median start margin "
            f"{out_ranks[straggler]['start_margin_ms_median']:+.1f} ms vs "
            f"fleet median); the other ranks waited "
            f"{wait:.1f} ms per step at the barrier while rank "
            f"{straggler} waited "
            f"{out_ranks[straggler]['barrier_wait_ms_mean']:.1f} ms"
        )
    elif n < 3:
        report["evidence"] = (
            f"only {n} common step(s) — too few to name a straggler "
            "(one noisy step must not name a culprit); collect a longer "
            "trace"
        )
    else:
        report["evidence"] = (
            f"no rank lagged the fleet's step starts in a majority of "
            f"{n} common steps (best score "
            f"{out_ranks[best]['straggler_score']:.0%} by rank {best}) — "
            "no consistent straggler"
        )
    return report


def render_skew(report: dict) -> str:
    lines = [
        f"common steps: {report['common_steps']}   "
        f"step period: {report['step_period_ms']:.2f} ms   "
        f"threshold: {report['threshold_ms']:.2f} ms",
        f"cross-rank skew (max start - median start): "
        f"mean {report['skew_ms_mean']:.2f} ms, "
        f"max {report['skew_ms_max']:.2f} ms",
        f"duration spread (max - median of per-rank means): "
        f"step {report['dur_spread_ms']['step']:.2f} ms, "
        f"reduction {report['dur_spread_ms']['reduction']:.2f} ms",
        "rank   straggler-score   barrier-wait(ms)   start-margin(ms)  "
        "step-dur(ms)",
    ]
    for r in report["ranks"]:
        c = report["per_rank"][r]
        lines.append(
            f"{r:<7}"
            + f"{c['straggler_score']:.0%}".ljust(18)
            + f"{c['barrier_wait_ms_mean']:.2f}".ljust(19)
            + f"{c['start_margin_ms_median']:+.2f}".ljust(18)
            + f"{c['step_dur_ms_mean']:.2f}"
        )
    if report["straggler"] is not None:
        lines.append(f"STRAGGLER: rank {report['straggler']}")
    lines.append(report["evidence"])
    res = report.get("alignment_residual_ms") or {}
    worst = max(res.values(), default=0.0)
    if worst:
        lines.append(
            f"(clock-alignment residual up to {worst:.2f} ms — cross-host "
            "comparisons carry that error bar)"
        )
    return "\n".join(lines)
