"""Observability: platform metric sink + primary-process logging (§5.5).

The reference pushes scalars to its hosting platform via
``gradient_utils.metrics.init(sync_tensorboard=True)`` (mnist_keras.py:22-23)
and gates console/TB output on rank 0. Here the platform is pluggable: a
`MetricsSink` interface with a JSONL file default, and a module-level
``init()`` shim mirroring the reference's call shape so entry scripts read
the same.
"""

from __future__ import annotations

import json
import os
import time
from typing import Protocol

from horovod_tpu import runtime
from horovod_tpu.analysis import registry


class MetricsSink(Protocol):
    def push(self, name: str, value: float, step: int | None = None) -> None: ...
    def close(self) -> None: ...


class NullSink:
    def push(self, name, value, step=None):
        pass

    def close(self):
        pass


class JsonlSink:
    """Appends ``{"name", "value", "step", "wall_time"}`` lines; the CI gate
    (`horovod_tpu.launch.ci_gate`) consumes this stream the way the Gradient
    workflow consumes ``tensorflow:loss`` (config.yaml:8-11)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._fh = open(path, "a")

    def push(self, name, value, step=None):
        self._fh.write(
            json.dumps(
                {"name": name, "value": float(value), "step": step, "wall_time": time.time()}
            )
            + "\n"
        )
        self._fh.flush()

    def close(self):
        self._fh.close()


_sink: MetricsSink | None = None
_configured_path: str | None = None
_buffered: list[tuple[str, float, int | None]] = []
_sync_tensorboard = False


def init(sync_tensorboard: bool = False, path: str | None = None) -> None:
    """Parity shim for ``gradient_utils.metrics.init`` (mnist_keras.py:23).

    ``sync_tensorboard=True`` mirrors the reference's behavior: scalars the
    TensorBoard-role logger (`callbacks.ScalarLogger`) records at epoch
    granularity are ALSO pushed to this platform sink, so the CI gate sees
    them without an explicit push callback.

    Sink creation is deferred: the reference calls ``metrics.init`` *before*
    ``hvd.init()`` (mnist_keras.py:22-30), and deciding the primary process
    must not touch the JAX backend before `runtime.init` has configured
    `jax.distributed`. Pushes that arrive before `runtime.init` are buffered
    and flushed on the first post-init push."""
    global _sink, _configured_path, _sync_tensorboard
    _sink = None
    _sync_tensorboard = bool(sync_tensorboard)
    _configured_path = path or os.path.join(
        registry.get_str("HVT_METRICS_DIR")
        or os.environ.get("PS_MODEL_PATH", "./models"),
        "metrics.jsonl",
    )


def sync_tensorboard_enabled() -> bool:
    return _sync_tensorboard


def _can_decide_primary() -> bool:
    """Whether asking `jax.process_index()` is safe/meaningful now.

    True once `runtime.init` ran, or once the JAX backend is already up for
    any other reason (e.g. a bare script that trains without ever calling
    ``hvt.init()`` — the backend exists by the time it pushes metrics, and
    querying it can no longer break a later `jax.distributed.initialize`
    because there won't be one)."""
    if runtime.is_initialized():
        return True
    try:
        from jax._src import xla_bridge

        return xla_bridge.backends_are_initialized()
    except Exception:
        # Fail closed: keep buffering until runtime.init. Deciding now would
        # initialize the backend before jax.distributed is configured — the
        # exact hazard the deferred-sink design exists to prevent. Buffered
        # pushes flush on the first post-init push, so nothing is lost.
        return False


def _resolve() -> MetricsSink | None:
    """The active sink, or None while the single-writer identity is still
    unknowable (§5.2) — before both `runtime.init` and first backend use."""
    global _sink
    if _sink is None:
        if _configured_path is not None:
            if not _can_decide_primary():
                return None
            # Primary process only; others get the NullSink.
            _sink = JsonlSink(_configured_path) if runtime.is_primary() else NullSink()
        else:
            _sink = NullSink()
    return _sink


def push(name: str, value: float, step: int | None = None) -> None:
    sink = _resolve()
    if sink is None:
        _buffered.append((name, float(value), step))
        return
    while _buffered:
        sink.push(*_buffered.pop(0))
    sink.push(name, value, step)


def set_sink(sink: MetricsSink) -> None:
    global _sink, _configured_path
    _sink = sink
    _configured_path = None
