"""Flash attention — pallas TPU kernel for the local attention hot path.

The single hottest op of the transformer family, implemented blockwise so
the [Tq, Tk] score matrix never touches HBM: each grid step streams one K/V
block through VMEM, folds it into an online-softmax accumulator (running
max / normalizer / unnormalized output, the same recurrence
`ops.attention.ring_attention` uses across chips — this kernel is the
within-chip counterpart), and writes the normalized output once per Q block.
O(T) memory instead of O(T²), matmuls on the MXU in the input dtype,
statistics in float32.

Backward is a custom VJP with the standard two-kernel recomputation scheme
(dq swept over K blocks, dK/dV swept over Q blocks) using the saved
logsumexp, so residual memory is O(T) as well.

`flash_attention` is shape-checked and falls back to the dense reference
(`ops.attention.dense_attention`) when the kernel's tiling constraints don't
hold; `interpret=True` (auto on CPU) runs the same kernel in the pallas
interpreter, which is how the unit tests validate it off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from horovod_tpu.ops.attention import check_window, dense_attention

_BIG_NEG = -1e30
# 1024-square tiles won the measured block sweep on v5e (benchmarks/
# fa_tune.py): vs 512² they are 1.23x at T=1024 and 1.27-1.4x at T=8192
# (fwd and fwd+bwd), because each K/V block amortizes the per-block
# online-softmax statistics (max/renormalize) over 4x the scores. The
# [bq, bk] f32 score tile is 4 MB — fine for VMEM at D ≤ 128; for wider
# heads `flash_attention` drops to 512 to keep the working set bounded.
# Tuned for v5e-class VMEM (16 MiB): on a smaller-VMEM TPU generation an
# oversized tile fails LOUDLY at Mosaic compile time (not silent wrong
# results) — pass block_q/block_k=512 there.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


# Segment-id operand layout (Mosaic-friendly, no in-kernel transposes):
# q ids ride the SUBLANE axis as [B, Tq, LANES] (value broadcast across the
# 128 lanes), kv ids ride the LANE axis as [B, SUBLANES, Tk] — so the
# [bq, bk] equality mask is a lane-tile of the q block against row 0 of the
# k block, both already in their natural in-register orientation.
_SEG_LANES = 128
_SEG_SUBLANES = 8


def _causal_mask(iq, ik, bq, bk, offset, window=None):
    """[bq, bk] 0/1 mask for global rows iq*bq+r+offset ≥ cols ik*bk+c.

    ``offset = Tk - Tq`` aligns the sequences at the END (the standard
    cross-attention/decode convention, matching `_dense_with_lse`): query i
    sees keys j ≤ i + Tk - Tq. Zero for self-attention. ``window`` further
    restricts to the sliding band row − col < window (Mistral-style local
    attention: each query sees its ``window`` most recent keys, itself
    included)."""
    rows = iq * bq + offset + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = ik * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    keep = rows >= cols
    if window is not None:
        keep &= cols > rows - window
    return keep.astype(jnp.float32)


def _tile_mask(iq, ik, causal, segmented, bq, bk, offset, window,
               qs_ref, ks_ref, sinks=0, sink_sel=None):
    """(needed, mask): the block-skip predicate and the [bq, bk] 0/1 mask
    (None when unmasked). ``needed`` is False when the whole tile is
    provably masked — above the causal diagonal, below the sliding-window
    band, or (segment early-out) the q block's id range cannot intersect
    the k block's (a NECESSARY condition for any equality match, so the
    skip is sound for arbitrary id layouts, and tight for the contiguous
    runs packing produces).

    ``sinks``/``sink_sel``: global+local attention. A SINK tile (sink_sel
    True — a traced scalar when one grid handles both kinds, or the
    literal True for a sink-only kernel) masks to cols < sinks AND below
    the band — strictly disjoint from band tiles, so a (row, col) pair
    visible through both the band and the sink region is never counted
    twice."""
    needed = True
    mask = None
    if causal:
        band_needed = ik * bk <= iq * bq + bq - 1 + offset
        if window is not None:
            # The tile's newest key vs the tile's oldest query's horizon:
            # every (row, col) has row − col ≥ (iq*bq + offset) − (ik*bk +
            # bk − 1); when even that gap ≥ window the whole tile is stale.
            band_needed &= ik * bk + bk - 1 > iq * bq + offset - window
        if sinks and sink_sel is not None:
            rows = iq * bq + offset + lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0
            )
            cols = ik * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            band_keep = (rows >= cols) & (cols > rows - window)
            sink_keep = (
                (rows >= cols) & (cols < sinks) & (cols <= rows - window)
            )
            # A q block whose rows are all inside the window needs no
            # sink tile — the band tiles already cover block 0.
            sink_needed = iq * bq + bq - 1 + offset >= window
            if sink_sel is True:
                needed = sink_needed
                mask = sink_keep.astype(jnp.float32)
            else:
                needed = (sink_sel & sink_needed) | (~sink_sel & band_needed)
                # f32 select: Mosaic cannot legalize a vector select on i1.
                mask = jnp.where(
                    sink_sel,
                    sink_keep.astype(jnp.float32),
                    band_keep.astype(jnp.float32),
                )
        else:
            needed = band_needed
            mask = _causal_mask(iq, ik, bq, bk, offset, window)
    if segmented:
        qs = qs_ref[0]  # [bq, LANES]
        ks = ks_ref[0, 0:1, :]  # [1, bk]
        q_ids = jnp.tile(qs, (1, bk // _SEG_LANES))  # [bq, bk]
        smask = (q_ids == ks).astype(jnp.float32)
        overlap = (jnp.min(ks) <= jnp.max(qs)) & (jnp.max(ks) >= jnp.min(qs))
        needed = overlap if needed is True else (needed & overlap)
        mask = smask if mask is None else mask * smask
    return needed, mask


def _band_lo_k(iq, bq, bk, offset, window):
    """First k block holding any in-band column for q block ``iq`` (the
    oldest visible key of the block's first row), clamped to 0. Floor
    division handles a negative numerator (band starting before key 0)."""
    return jnp.maximum(0, (iq * bq + offset - (window - 1)) // bk)


def _band_lo_q(ik, bq, bk, offset, window):
    """First q block holding any row that sees k block ``ik`` (rows r with
    0 ≤ r + offset − c < window for some c in the block), clamped to 0."""
    return jnp.maximum(0, (ik * bk - offset) // bq)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, segmented,
                bq, bk, offset, window, banded, nk, sinks=0):
    if segmented:
        qs_ref, ks_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
        qs_ref = ks_ref = None
    iq, jj = pl.program_id(2), pl.program_id(3)
    nj = pl.num_programs(3)
    # Banded (sliding-window) grids enumerate ONLY the k blocks near the
    # band: grid coordinate jj walks lo(iq) .. lo(iq)+nj−1 — O(T·window)
    # tiles (and, crucially, O(T·window) K/V DMA: a predicated-off tile in
    # a full grid still streams its block; a tile the grid never names
    # does not). The top-clipped DMA duplicates mask off via `needed`.
    # With sinks, tile jj==0 is the pinned SINK tile (k block 0) and the
    # band walks jj−1.
    sink_sel = None
    if banded and sinks:
        sink_sel = jj == 0
        ik = jnp.where(
            sink_sel, 0, _band_lo_k(iq, bq, bk, offset, window) + jj - 1
        )
    elif banded:
        ik = _band_lo_k(iq, bq, bk, offset, window) + jj
    else:
        ik = jj

    @pl.when(jj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _BIG_NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Block skip: a K block strictly above the causal diagonal — or with no
    # possible segment match — contributes nothing; predicate the whole
    # update away (half the FLOPs for causal; one matmul per co-resident
    # segment pair for packed sequences).
    needed, mask = _tile_mask(
        iq, ik, causal, segmented, bq, bk, offset, window, qs_ref, ks_ref,
        sinks=sinks, sink_sel=sink_sel,
    )
    if banded:
        needed &= ik <= nk - 1  # clipped-DMA duplicates beyond the last block

    @pl.when(needed)
    def _():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if mask is not None:
            s = s + (1.0 - mask) * _BIG_NEG

        m_prev = m_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = p * mask  # exact zeros on masked lanes
        l_ref[:, 0:1] = l_ref[:, 0:1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, 0:1] = m_new

    @pl.when(jj == nj - 1)
    def _():
        l = l_ref[:, 0:1]
        # A row every key is masked away from (a padding segment with no kv
        # tokens, or causal rows before the first key when Tk < Tq) has
        # l == 0: emit 0 output and a -inf-like lse so any downstream
        # online-softmax merge weights it to zero — never NaN.
        empty = l == 0.0
        l_safe = jnp.where(empty, 1.0, l)
        o_ref[0, 0, :, :] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = jnp.where(
            empty, _BIG_NEG, m_ref[:, 0:1] + jnp.log(l_safe)
        )


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   scale, causal, segmented, bq, bk, offset, window, banded,
                   nk, sinks=0):
    if segmented:
        qs_ref, ks_ref, dq_ref, acc_ref = rest
    else:
        dq_ref, acc_ref = rest
        qs_ref = ks_ref = None
    iq, jj = pl.program_id(2), pl.program_id(3)
    nj = pl.num_programs(3)
    sink_sel = None
    if banded and sinks:
        sink_sel = jj == 0
        ik = jnp.where(
            sink_sel, 0, _band_lo_k(iq, bq, bk, offset, window) + jj - 1
        )
    elif banded:
        ik = _band_lo_k(iq, bq, bk, offset, window) + jj
    else:
        ik = jj

    @pl.when(jj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    needed, mask = _tile_mask(
        iq, ik, causal, segmented, bq, bk, offset, window, qs_ref, ks_ref,
        sinks=sinks, sink_sel=sink_sel,
    )
    if banded:
        needed &= ik <= nk - 1

    @pl.when(needed)
    def _():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if mask is not None:
            # Mask BEFORE exp (as the forward does): a large masked score
            # would overflow exp to inf, and the TPU's inf*0 is NaN — the
            # post-hoc `p * mask` alone is only safe in interpret mode.
            s = s + (1.0 - mask) * _BIG_NEG
        p = jnp.exp(s - lse)
        if mask is not None:
            p = p * mask
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        acc_ref[:] = acc_ref[:] + jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(jj == nj - 1)
    def _():
        dq_ref[0, 0, :, :] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    scale, causal, segmented, bq, bk, offset, window, banded,
                    nq, sinks=0, sink_only=False):
    if segmented:
        qs_ref, ks_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
        qs_ref = ks_ref = None
    ik, jj = pl.program_id(2), pl.program_id(3)
    nj = pl.num_programs(3)
    iq = _band_lo_q(ik, bq, bk, offset, window) + jj if banded else jj

    @pl.when(jj == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    needed, mask = _tile_mask(
        iq, ik, causal, segmented, bq, bk, offset, window, qs_ref, ks_ref,
        sinks=sinks, sink_sel=True if sink_only else None,
    )
    if banded:
        needed &= iq <= nq - 1

    @pl.when(needed)
    def _():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if mask is not None:
            s = s + (1.0 - mask) * _BIG_NEG  # pre-exp: see _bwd_dq_kernel
        p = jnp.exp(s - lse)
        if mask is not None:
            p = p * mask
        # dV += Pᵀ · dO
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        # dK += dSᵀ · Q
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(jj == nj - 1)
    def _():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


# Grid-to-T-block selectors: the grid is (b, h, anchor, swept); a tensor's
# T coordinate is either the anchored axis, the swept axis, or — for banded
# (sliding-window) grids — a band around the anchor: lo(anchor) + swept,
# clipped for the DMA (the kernels predicate the clipped duplicates off).
def _anchor(i, j):
    return i


def _sweep(i, j):
    return j


def _sweep_banded(lo_fn, n_total):
    return lambda i, j: jnp.clip(lo_fn(i) + j, 0, n_total - 1)


def _block_spec(d, bt, tsel):
    """BlockSpec for [B,H,T,D] arrays: one (1, 1, bt, D) tile per (b, h)
    grid point — the (bt, D) tile sits in the trailing dims as the TPU
    lowering requires. ``tsel(i, j)`` maps the grid's (anchor, swept)
    coordinates to this tensor's T-block index."""
    return pl.BlockSpec(
        (1, 1, bt, d), lambda ib, ih, i, j: (ib, ih, tsel(i, j), 0)
    )


def _stat_spec(bq, tsel):
    """[B,H,T,1] per-row statistics (lse / delta)."""
    return pl.BlockSpec(
        (1, 1, bq, 1), lambda ib, ih, i, j: (ib, ih, tsel(i, j), 0)
    )


def _seg_q_spec(bq, tsel):
    """[B, Tq, LANES] q segment ids (no head dim — shared across heads)."""
    return pl.BlockSpec(
        (1, bq, _SEG_LANES), lambda ib, ih, i, j: (ib, tsel(i, j), 0)
    )


def _seg_kv_spec(bk, tsel):
    """[B, SUBLANES, Tk] kv segment ids."""
    return pl.BlockSpec(
        (1, _SEG_SUBLANES, bk), lambda ib, ih, i, j: (ib, 0, tsel(i, j))
    )


def _seg_operands(q_seg, kv_seg, tq, tk):
    """Lift [B, Tq]/[B, Tk] ids into the kernel's register-oriented layouts
    (see _SEG_LANES note). int32; values are opaque labels."""
    qs = lax.broadcast_in_dim(
        q_seg.astype(jnp.int32), (q_seg.shape[0], tq, _SEG_LANES), (0, 1)
    )
    ks = lax.broadcast_in_dim(
        kv_seg.astype(jnp.int32), (kv_seg.shape[0], _SEG_SUBLANES, tk), (0, 2)
    )
    return qs, ks


def _band_sweep_k(bq, bk, off, window, sinks, nk):
    """(swept-axis size, k-block selector) for a banded [+ pinned sink
    tile] sweep — shared by the forward and backward grids so they cannot
    disagree on which k block a grid step reads."""
    nb = min(nk, (bq + window - 2) // bk + 2) + (1 if sinks else 0)
    lo = lambda i: _band_lo_k(i, bq, bk, off, window)  # noqa: E731
    if sinks:
        ksel = lambda i, j: jnp.where(  # noqa: E731
            j == 0, 0, jnp.clip(lo(i) + j - 1, 0, nk - 1)
        )
    else:
        ksel = _sweep_banded(lo, nk)
    return nb, ksel


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11)
)
def _flash(q, k, v, q_seg, kv_seg, causal, window, sinks, q_offset, bq, bk,
           interpret):
    out, _ = _flash_fwd_impl(
        q, k, v, q_seg, kv_seg, causal, window, sinks, q_offset, bq, bk,
        interpret,
    )
    return out


def _flash_fwd_impl(q, k, v, q_seg, kv_seg, causal, window, sinks, q_offset,
                    bq, bk, interpret):
    # Kernel layout is [B, H, T, D] so the (T-block, D) tile occupies the
    # trailing dims; callers pass [B, T, H, D]. K/V carry their own Tk
    # (cross-attention); causality aligns the sequence ENDS via offset.
    qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
    b, h, tq, d = qt.shape
    tk = kt.shape[2]
    segmented = q_seg is not None
    scale = d ** -0.5
    off = tk - tq if q_offset is None else q_offset
    nq, nk = tq // bq, tk // bk
    banded = window is not None
    if banded:
        # Sliding window: the swept grid axis walks only the ≤ nb k blocks
        # that can intersect q block i's band (span bq + window − 1 cols,
        # any alignment) — O(T·window) tiles AND K/V DMA instead of O(T²).
        # Sinks prepend one pinned tile (k block 0) to every sweep.
        nb, ksel = _band_sweep_k(bq, bk, off, window, sinks, nk)
    else:
        nb, ksel = nk, _sweep
    grid = (b, h, nq, nb)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, segmented=segmented,
        bq=bq, bk=bk, offset=off, window=window, banded=banded, nk=nk,
        sinks=sinks,
    )
    in_specs = [
        _block_spec(d, bq, _anchor),
        _block_spec(d, bk, ksel),
        _block_spec(d, bk, ksel),
    ]
    operands = [qt, kt, vt]
    if segmented:
        in_specs += [_seg_q_spec(bq, _anchor), _seg_kv_spec(bk, ksel)]
        operands += list(_seg_operands(q_seg, kv_seg, tq, tk))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            _block_spec(d, bq, _anchor),
            _stat_spec(bq, _anchor),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return jnp.transpose(out, (0, 2, 1, 3)), lse


def _flash_fwd(q, k, v, q_seg, kv_seg, causal, window, sinks, q_offset, bq,
               bk, interpret):
    out, lse = _flash_fwd_impl(
        q, k, v, q_seg, kv_seg, causal, window, sinks, q_offset, bq, bk,
        interpret,
    )
    return out, (q, k, v, q_seg, kv_seg, out, lse)


def _flash_bwd(causal, window, sinks, q_offset, bq, bk, interpret, res, g):
    return _flash_bwd_core(
        causal, window, sinks, q_offset, bq, bk, interpret, res, g, None
    )


def _flash_bwd_core(causal, window, sinks, q_offset, bq, bk, interpret, res,
                    g, g_lse):
    """Shared backward: the lse cotangent (from `flash_attention_with_lse`
    consumers like the ring merge) folds into the per-row jacobian term —
    with s → p = exp(s−lse), o = p·v:  ds = p ⊙ (dp − (δ − dlse)) where
    δ_i = Σ_d dO·O, because ∂lse/∂s = p. So the kernels run unchanged with
    an adjusted δ."""
    q, k, v, q_seg, kv_seg, out, lse = res
    qt, kt, vt, gt = (
        jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v, g)
    )
    b, h, tq, d = qt.shape
    tk = kt.shape[2]
    segmented = q_seg is not None
    scale = d ** -0.5
    off = tk - tq if q_offset is None else q_offset
    nq, nk = tq // bq, tk // bk
    banded = window is not None
    if banded:
        nb, ksel = _band_sweep_k(bq, bk, off, window, sinks, nk)
        nbq = min(nq, (bk + window - 2) // bq + 2)
        qsel = _sweep_banded(
            lambda i: _band_lo_q(i, bq, bk, off, window), nq
        )
    else:
        nb, ksel = nk, _sweep
        nbq, qsel = nq, _sweep
    # delta_i = Σ_d dO·O — the softmax-jacobian row term, cheap outside.
    delta = jnp.einsum(
        "bthd,bthd->bht", g.astype(jnp.float32), out.astype(jnp.float32)
    )[..., None]
    if g_lse is not None:
        # g_lse arrives in the caller-facing [B, T, H] layout.
        delta = delta - jnp.transpose(g_lse, (0, 2, 1))[..., None]
    seg_ops = list(_seg_operands(q_seg, kv_seg, tq, tk)) if segmented else []

    dq_in_specs = [
        _block_spec(d, bq, _anchor),
        _block_spec(d, bk, ksel),
        _block_spec(d, bk, ksel),
        _block_spec(d, bq, _anchor),
        _stat_spec(bq, _anchor),
        _stat_spec(bq, _anchor),
    ]
    if segmented:
        dq_in_specs += [
            _seg_q_spec(bq, _anchor), _seg_kv_spec(bk, ksel)
        ]
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, segmented=segmented,
            bq=bq, bk=bk, offset=off, window=window, banded=banded, nk=nk,
            sinks=sinks,
        ),
        grid=(b, h, nq, nb),
        in_specs=dq_in_specs,
        out_specs=_block_spec(d, bq, _anchor),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, gt, lse, delta, *seg_ops)

    dkv_in_specs = [
        _block_spec(d, bq, qsel),
        _block_spec(d, bk, _anchor),
        _block_spec(d, bk, _anchor),
        _block_spec(d, bq, qsel),
        _stat_spec(bq, qsel),
        _stat_spec(bq, qsel),
    ]
    if segmented:
        dkv_in_specs += [
            _seg_q_spec(bq, qsel), _seg_kv_spec(bk, _anchor)
        ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, segmented=segmented,
            bq=bq, bk=bk, offset=off, window=window, banded=banded, nq=nq,
        ),
        grid=(b, h, nk, nbq),
        in_specs=dkv_in_specs,
        out_specs=[
            _block_spec(d, bk, _anchor),
            _block_spec(d, bk, _anchor),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kt.shape, k.dtype),
            jax.ShapeDtypeStruct(vt.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, gt, lse, delta, *seg_ops)
    if banded and sinks:
        # Sink contributions to dK/dV of k block 0: every q block sees the
        # sink columns, so this pass sweeps ALL nq q blocks for the one
        # anchored block — a separate call keeps the band pass's swept axis
        # at nbq instead of forcing the whole rectangle to nq.
        sink_in_specs = [
            _block_spec(d, bq, _sweep),
            _block_spec(d, bk, _anchor),
            _block_spec(d, bk, _anchor),
            _block_spec(d, bq, _sweep),
            _stat_spec(bq, _sweep),
            _stat_spec(bq, _sweep),
        ]
        if segmented:
            sink_in_specs += [_seg_q_spec(bq, _sweep), _seg_kv_spec(bk, _anchor)]
        dk0, dv0 = pl.pallas_call(
            functools.partial(
                _bwd_dkv_kernel, scale=scale, causal=causal,
                segmented=segmented, bq=bq, bk=bk, offset=off, window=window,
                banded=False, nq=nq, sinks=sinks, sink_only=True,
            ),
            grid=(b, h, 1, nq),
            in_specs=sink_in_specs,
            out_specs=[
                _block_spec(d, bk, _anchor),
                _block_spec(d, bk, _anchor),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, bk, d), k.dtype),
                jax.ShapeDtypeStruct((b, h, bk, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
            interpret=interpret,
        )(qt, kt[:, :, :bk], vt[:, :, :bk], gt, lse, delta, *seg_ops)
        dk = dk.at[:, :, :bk].add(dk0)
        dv = dv.at[:, :, :bk].add(dv0)
    back = lambda x: jnp.transpose(x, (0, 2, 1, 3))  # noqa: E731
    # Integer segment-id operands take no gradient (None cotangent).
    return back(dq), back(dk), back(dv), None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash_lse(q, k, v, q_seg, kv_seg, causal, window, sinks, q_offset, bq,
               bk, interpret):
    """Kernel entry that also RETURNS the per-row logsumexp — the statistic
    a cross-chip online-softmax merge needs (ring attention: each hop's
    (out, lse) pair is exactly one step of the recurrence)."""
    out, lse = _flash_fwd_impl(
        q, k, v, q_seg, kv_seg, causal, window, sinks, q_offset, bq, bk,
        interpret,
    )
    return out, jnp.transpose(lse[..., 0], (0, 2, 1))  # [B,H,T,1]→[B,T,H]


def _flash_lse_fwd(q, k, v, q_seg, kv_seg, causal, window, sinks, q_offset,
                   bq, bk, interpret):
    out, lse = _flash_fwd_impl(
        q, k, v, q_seg, kv_seg, causal, window, sinks, q_offset, bq, bk,
        interpret,
    )
    return (
        (out, jnp.transpose(lse[..., 0], (0, 2, 1))),
        (q, k, v, q_seg, kv_seg, out, lse),
    )


def _flash_lse_bwd(causal, window, sinks, q_offset, bq, bk, interpret, res,
                   cotangents):
    g, g_lse = cotangents
    return _flash_bwd_core(
        causal, window, sinks, q_offset, bq, bk, interpret, res, g, g_lse
    )


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _dense_with_lse(q, k, v, *, causal: bool, q_segment_ids=None,
                    kv_segment_ids=None, window=None, q_offset=None,
                    sinks=0):
    """Dense (out, lse) fallback, numerically matching the kernel's
    conventions: f32 statistics, fully-masked rows get lse ≈ _BIG_NEG and
    zero output (so a merge weights them to zero), natively differentiable.
    Also the segment/window-mask REFERENCE the kernel parity tests compare
    to. ``window``/``q_offset`` as in `flash_attention`."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    masked = causal or q_segment_ids is not None
    keep = None
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        off = tk - tq if q_offset is None else q_offset
        rows = lax.broadcasted_iota(jnp.int32, (tq, tk), 0) + off
        cols = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        keep = rows >= cols  # [Tq, Tk], broadcasts over [B, H]
        if window is not None:
            band = cols > rows - window
            if sinks:
                band |= cols < sinks
            keep &= band
    if q_segment_ids is not None:
        seg = (
            q_segment_ids[:, None, :, None] == kv_segment_ids[:, None, None, :]
        )  # [B, 1, Tq, Tk]
        keep = seg if keep is None else (keep & seg)
    if masked:
        s = jnp.where(keep, s, _BIG_NEG)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    if masked:
        # Exact zeros so a fully-masked row yields l == 0 (not tk) and the
        # empty-row convention below matches the kernel's.
        p = jnp.where(keep, p, 0.0)
    l = p.sum(axis=-1, keepdims=True)
    empty = l == 0.0
    l_safe = jnp.where(empty, 1.0, l)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", (p / l_safe).astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
    lse = jnp.where(empty, _BIG_NEG, m + jnp.log(l_safe))[..., 0]  # [B,H,Tq]
    return out, jnp.transpose(lse, (0, 2, 1))  # [B,Tq,H]


def _check_segment_shapes(q, k, q_segment_ids, kv_segment_ids):
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError(
            "pass q_segment_ids and kv_segment_ids together (for packed "
            "self-attention they are the same array)"
        )
    if q_segment_ids is None:
        return
    if q_segment_ids.shape != (q.shape[0], q.shape[1]):
        raise ValueError(
            f"q_segment_ids must be [B, Tq] = {(q.shape[0], q.shape[1])}, "
            f"got {q_segment_ids.shape}"
        )
    if kv_segment_ids.shape != (k.shape[0], k.shape[1]):
        raise ValueError(
            f"kv_segment_ids must be [B, Tk] = {(k.shape[0], k.shape[1])}, "
            f"got {kv_segment_ids.shape}"
        )


def flash_attention_with_lse(
    q, k, v, *,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    q_segment_ids=None,
    kv_segment_ids=None,
    window: int | None = None,
    q_offset: int | None = None,
    interpret: bool | None = None,
):
    """[B,Tq,H,D] attention returning ``(out, lse)`` with ``lse`` [B,Tq,H] —
    the building block for cross-chip softmax merges (ring attention).
    Same kernel/fallback/interpret policy as `flash_attention`; gradients
    flow through BOTH outputs (the lse cotangent folds into the kernel
    backward's δ term). ``window``/``q_offset`` as in `flash_attention`."""
    _check_segment_shapes(q, k, q_segment_ids, kv_segment_ids)
    check_window(window, causal)
    segmented = q_segment_ids is not None
    block_q, block_k = pick_blocks(
        q.shape[1], q.shape[-1], q.dtype, block_q, block_k, t_k=k.shape[1],
        segmented=segmented, windowed=window is not None,
    )
    if not supported(
        q.shape, block_q, block_k, k_shape=k.shape, dtype=q.dtype,
        segmented=segmented,
    ):
        return _dense_with_lse(
            q, k, v, causal=causal,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            window=window, q_offset=q_offset,
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_lse(
        q, k, v, q_segment_ids, kv_segment_ids, causal, window, 0, q_offset,
        block_q, block_k, interpret,
    )


def _sublane(dtype) -> int:
    """Second-to-last-dim tile granule for the TPU vector layout: f32 packs
    8 sublanes, 16-bit types 16, 8-bit types 32."""
    itemsize = jnp.dtype(dtype).itemsize
    return {4: 8, 2: 16, 1: 32}.get(itemsize, 8)


def supported(q_shape, bq=DEFAULT_BLOCK_Q, bk=DEFAULT_BLOCK_K,
              k_shape=None, dtype=jnp.float32, segmented=False) -> bool:
    """Whether the kernel's tiling holds for [B,Tq,H,D] q and [B,Tk,H,D] k/v.

    Beyond divisibility (q blocks against Tq, k blocks against K/V's own Tk —
    cross-attention runs the kernel on a rectangular nq×nk grid), the blocks
    must be sublane-aligned for the dtype (an unaligned tile fails Mosaic
    compilation on real TPU instead of falling back), and segment-id masking
    needs lane-aligned K blocks (the q-id tile is repeated in _SEG_LANES
    units across the K axis).

    This checks ONE given block config; it is not a will-the-kernel-run
    predicate for `flash_attention`, which first degrades the config via
    `pick_blocks` — probe with ``supported(shape, *pick_blocks(...))``.
    """
    b, t, h, d = q_shape
    tk = k_shape[1] if k_shape is not None else t
    granule = _sublane(dtype)
    if segmented and bk % _SEG_LANES:
        return False
    return (
        t % bq == 0 and tk % bk == 0
        and bq % granule == 0 and bk % granule == 0
        and d <= 256
    )


def pick_blocks(t: int, d: int, dtype, bq: int = DEFAULT_BLOCK_Q,
                bk: int = DEFAULT_BLOCK_K, t_k: int | None = None,
                segmented: bool = False,
                windowed: bool = False) -> tuple[int, int]:
    """Largest workable (block_q, block_k) ≤ the requested sizes for a
    [*, t, *, d] attention call (``t_k`` = K/V's own length for
    cross-attention; default self-attention): clamp for wide heads (a 1024²
    f32 score tile + wide q/k/v blocks would crowd VMEM), clamp to T, then
    halve until the block divides its T — so e.g. T=1536 runs 512² tiles
    instead of regressing to the dense fallback just because
    1536 % 1024 != 0."""
    t_k = t if t_k is None else t_k
    if d > 128 or max(t, t_k) >= 32768:
        # Wide heads: a 1024² f32 score tile + wide q/k/v blocks would
        # crowd VMEM. Very long grids overflow v5e's 16 MB scoped-VMEM
        # budget *in context*: the bare kernel compiles at 1024² up to
        # T=32k, but inside a remat'd training step XLA co-schedules
        # neighboring fusions into the same scoped budget and the
        # allocation grows slowly with T (measured: 16.26M at T=32k,
        # 16.76M at T=131k vs the 16.00M limit — both fail, while T=8k
        # fits). 512² tiles leave ~3/4 of the score-tile footprint as
        # headroom and measured within a few % of 1024² in the block sweep.
        bq, bk = min(bq, 512), min(bk, 512)
    if segmented or windowed:
        # Extra in-kernel operands push 1024² past v5e's 16 MB VMEM stack:
        # the double-buffered segment-id tiles cost ~0.8 MB, and the band
        # mask's [bq, bk] i32 iotas a few hundred KB (measured 16.30M vs
        # the 16M limit at seq 32768). 512² fits with headroom, measured
        # within a few % of 1024² in the block sweep — and for windows a
        # smaller K block also tightens the block-skip granularity.
        bq, bk = min(bq, 512), min(bk, 512)
    bq, bk = min(bq, t), min(bk, t_k)
    # Degrade no further than 128: below that the kernel's tiny score tiles
    # underfill the MXU and the dense fallback is faster — leaving a
    # non-dividing block here makes `supported` reject and fall back.
    # (Explicitly-passed smaller blocks are honored, not degraded-to; the
    # `bq // 2 >= floor` guard keeps non-power-of-two explicit blocks from
    # halving THROUGH the floor, e.g. 384 → 192 stops rather than → 96.)
    floor = max(_sublane(dtype), 128)
    while t % bq and bq // 2 >= floor:
        bq //= 2
    while t_k % bk and bk // 2 >= floor:
        bk //= 2
    return bq, bk


def flash_attention(
    q, k, v, *,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    q_segment_ids=None,
    kv_segment_ids=None,
    window: int | None = None,
    sinks: int = 0,
    q_offset: int | None = None,
    interpret: bool | None = None,
):
    """[B,Tq,H,D] attention via the pallas kernel; dense fallback when the
    tiling doesn't hold. ``interpret=None`` auto-selects the pallas
    interpreter off-TPU so tests/CPU paths run the same kernel code.

    ``q_segment_ids``/``kv_segment_ids`` ([B,Tq]/[B,Tk] ints) restrict
    attention to equal-id pairs — the packed-sequence pretraining mask
    (multiple documents per row, none attending across its neighbors), with
    block-level early-out so disjoint tile pairs cost no FLOPs. K/V may
    carry their own length Tk ≠ Tq (cross-attention); with ``causal`` the
    sequences align at their ENDS (query i sees keys j ≤ i + Tk − Tq).

    ``window`` (sliding-window attention, Mistral-style: each query sees
    only its ``window`` most recent keys, itself included — requires
    ``causal``) masks the band row − col < window AND block-skips tiles
    entirely outside it, so FLOPs scale with T·window instead of T²/2.
    ``q_offset`` overrides the q↔k alignment: query row i sits at key
    position i + q_offset (default Tk − Tq, the end-aligned convention);
    ring attention uses it to place a remote K/V block's hop distance into
    the causal/window arithmetic.

    ``sinks`` (global+local / StreamingLLM mask; requires ``window``)
    re-admits the first ``sinks`` key positions beyond the band: the grid
    prepends one pinned tile (k block 0) per q block, masked disjointly
    from the band, and the backward adds a sink-only dK/dV pass over that
    block — overall cost stays O(T·(window + sinks))."""
    _check_segment_shapes(q, k, q_segment_ids, kv_segment_ids)
    check_window(window, causal)
    if sinks < 0:
        raise ValueError(f"sinks must be >= 0, got {sinks}")
    if window is None:
        sinks = 0  # full causal attention already sees every sink
    segmented = q_segment_ids is not None
    block_q, block_k = pick_blocks(
        q.shape[1], q.shape[-1], q.dtype, block_q, block_k, t_k=k.shape[1],
        segmented=segmented, windowed=window is not None,
    )
    kernel_ok = supported(
        q.shape, block_q, block_k, k_shape=k.shape, dtype=q.dtype,
        segmented=segmented,
    ) and (sinks == 0 or (sinks <= block_k and q_offset is None))
    if not kernel_ok:
        if segmented or k.shape[1] != q.shape[1] or window is not None \
                or q_offset is not None:
            out, _ = _dense_with_lse(
                q, k, v, causal=causal,
                q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
                window=window, q_offset=q_offset, sinks=sinks,
            )
            return out
        return dense_attention(q, k, v, causal=causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(
        q, k, v, q_segment_ids, kv_segment_ids, causal, window, sinks,
        q_offset, block_q, block_k, interpret,
    )
