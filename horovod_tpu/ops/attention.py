"""Attention: dense reference, ring (sequence-parallel), Ulysses (head-swap).

Long-context is first-class in this framework: sequences too long for one
chip's HBM are sharded along the mesh's ``seq`` axis and attention runs as a
collective. Two standard schemes, both expressed with XLA collectives so the
compiler overlaps communication with compute:

* **Ring attention** (Liu et al., arXiv:2310.01889): K/V shards rotate around
  the ``seq`` ring via `lax.ppermute` while each device accumulates its
  queries' attention with an online (streaming) softmax — full attention,
  O(T/n) memory per chip, n-1 hops riding neighbor ICI links.
* **Ulysses** (Jacobs et al., arXiv:2309.14509): `lax.all_to_all` re-shards
  seq ↔ heads so each device holds the full sequence for H/n heads, runs
  ordinary attention locally, and swaps back. One collective pair per layer,
  needs heads % seq_parallelism == 0.

All functions take ``[batch, seq, heads, head_dim]`` and return the same.
`ring_attention`/`ulysses_attention` must be called **inside** `shard_map`
with the sequence dimension sharded over ``axis_name`` (see
`models/transformer.py` for the placement); with an axis of size 1 they
degrade to exactly `dense_attention` — the reference's "no-launcher
degradation" principle (README.md:49-52) applied to sequence parallelism.
"""

from __future__ import annotations

import jax

from horovod_tpu import compat
import jax.numpy as jnp
from jax import lax

# Finite stand-in for -inf: keeps fully-masked softmax rows at p == 0 via
# explicit mask multiplication without generating NaNs from inf - inf.
_BIG_NEG = -1e30


def check_window(window, causal) -> None:
    """Validate a sliding-window request (shared by every attention impl:
    dense, ring, Ulysses, and the flash kernel)."""
    if window is None:
        return
    if not causal:
        raise ValueError(
            "window (sliding-window attention) requires causal=True — the "
            "band is defined as each query's `window` most recent keys"
        )
    if window < 1:
        raise ValueError(f"window must be a positive int, got {window}")


def _scores(q, k, scale):
    """[B,Tq,H,D] x [B,Tk,H,D] -> [B,H,Tq,Tk] logits on the MXU."""
    return jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale


def dense_attention(q, k, v, *, causal: bool = True, q_segment_ids=None,
                    kv_segment_ids=None, window: int | None = None,
                    sinks: int = 0):
    """Reference full-materialization attention (numerics ground truth).

    float32 softmax regardless of input dtype — bf16 logits lose too much for
    long sequences; the matmuls still run in the inputs' dtype on the MXU.
    ``q_segment_ids``/``kv_segment_ids`` ([B,Tq]/[B,Tk]) restrict attention
    to equal-id pairs (packed sequences) — the reference semantics the flash
    kernel's segment masking is tested against. ``window`` (requires
    ``causal``) further restricts each query to its ``window`` most recent
    keys (the sliding-window band the flash kernel block-skips); ``sinks``
    re-admits the first ``sinks`` key positions beyond the band — the
    global+local (StreamingLLM / Longformer-style) mask."""
    check_window(window, causal)
    if sinks < 0:
        raise ValueError(f"sinks must be >= 0, got {sinks}")
    scale = q.shape[-1] ** -0.5
    s = _scores(q, k, scale)
    keep = None
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        q_pos = lax.broadcasted_iota(jnp.int32, (tq, tk), 0) + (tk - tq)
        k_pos = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        keep = (q_pos >= k_pos)[None, None]
        if window is not None:
            band = k_pos > q_pos - window
            if sinks:
                band |= k_pos < sinks
            keep &= band[None, None]
    if q_segment_ids is not None:
        seg = q_segment_ids[:, None, :, None] == kv_segment_ids[:, None, None, :]
        keep = seg if keep is None else keep & seg
    if keep is not None:
        s = jnp.where(keep, s, _BIG_NEG)
    p = jax.nn.softmax(s, axis=-1)
    if keep is not None:
        # Exact zeros: a FULLY-masked row (a q segment with no kv tokens, or
        # causal rows before the first key when Tk < Tq) would otherwise get
        # softmax's uniform 1/Tk and average ALL values — a cross-segment
        # leak. Zeroing matches the flash kernel's empty-row convention
        # (zero output); already-zero lanes are unaffected.
        p = jnp.where(keep, p, 0.0)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def ring_attention(q, k, v, *, axis_name: str = "seq", causal: bool = True,
                   window: int | None = None):
    """Exact blockwise attention over a sequence-sharded ring.

    Inside `shard_map`: q/k/v are this device's ``[B, T/n, H, D]`` shard of
    the global sequence. Each of the n ring steps attends the local queries
    to one K/V block, folds the result into an online softmax accumulator
    (running max m, normalizer l, unnormalized output o), and rotates the
    K/V block to the next neighbor — `lax.ppermute`, which XLA lowers to
    neighbor ICI sends that overlap with the attention matmuls of the
    current block. `lax.scan` (not fori_loop) so reverse-mode AD works and
    the backward pass replays the ring.

    ``window`` (requires ``causal``): sliding-window band over GLOBAL
    positions — queries see their ``window`` most recent keys across shard
    boundaries; hops carrying only stale keys contribute zero (their lanes
    mask away; the flash-ring variant additionally skips their FLOPs).
    """
    check_window(window, causal)
    n = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    scale = d ** -0.5

    q_pos = my * t_local + lax.broadcasted_iota(jnp.int32, (t_local, 1), 0)[:, 0]

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        # Which global block we currently hold: blocks travel "rightward"
        # (r → r+1), so after i hops we hold the block born at my - i.
        j = (my - i) % n
        k_pos = j * t_local + lax.broadcasted_iota(jnp.int32, (t_local, 1), 0)[:, 0]

        s = _scores(q, k_blk, scale)  # [B,H,Tq,Tk] float32
        if causal:
            keep = q_pos[:, None] >= k_pos[None, :]
            if window is not None:
                keep &= k_pos[None, :] > q_pos[:, None] - window
            mask = keep.astype(s.dtype)
        else:
            mask = jnp.ones((t_local, t_local), s.dtype)
        s = s + (1.0 - mask) * _BIG_NEG

        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)  # finite: both are ≥ _BIG_NEG
        p = jnp.exp(s - m_new[..., None]) * mask  # zero masked lanes exactly
        l_new = l * alpha + p.sum(axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        o_new = o * alpha[..., None] + pv

        perm = [(r, (r + 1) % n) for r in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_blk, v_blk), None

    o0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local), _BIG_NEG, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    (o, _, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n)
    )
    out = o / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,Tq,H,D]


def _merge_lse(o, m, l, o_c, lse_c):
    """Fold one block's (out, lse) contribution into the running
    (unnormalized out, max, normalizer) accumulator — the logsumexp
    recurrence every ring variant shares."""
    m_new = jnp.maximum(m, lse_c)
    alpha = jnp.exp(m - m_new)
    w = jnp.exp(lse_c - m_new)
    return (
        o * alpha[..., None] + o_c.astype(jnp.float32) * w[..., None],
        m_new,
        l * alpha + w,
    )


def ring_cross_attention(q, k, v, *, axis_name: str = "seq",
                         q_segment_ids=None, kv_segment_ids=None):
    """Non-causal CROSS-attention over a sequence-sharded ring — the
    seq2seq decoder's cross-attention under sequence parallelism.

    Inside `shard_map`: ``q`` is this device's ``[B, Tq/n, H, D]`` shard of
    the decoder tokens, ``k``/``v`` the ``[B, Tk/n, H, D]`` shard of the
    encoder memory (Tq and Tk are independent). Each of the n hops runs
    the flash kernel's non-causal Tk≠Tq grids against one memory block and
    folds the result in by the logsumexp recurrence while the block
    rotates to the neighbor — identical structure to
    `ring_flash_attention`, minus the causal machinery (every query sees
    every key, so every hop is a full block).

    ``q_segment_ids`` stays local with the queries; ``kv_segment_ids``
    rotates with its K/V block (the source-side padding mask). A query
    with NO matching key anywhere (an all-pad source row) gets exactly
    zero output — the kernel's empty-row convention, preserved through
    the merge by the safe final divide."""
    from horovod_tpu.ops.flash_attention import flash_attention_with_lse

    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError(
            "q_segment_ids and kv_segment_ids come as a pair (the "
            "source-side padding mask needs both sides labelled)"
        )
    n = compat.axis_size(axis_name)
    b, tq, h, d = q.shape

    def hop(k_blk, v_blk, ks_blk):
        kw = (
            dict(q_segment_ids=q_segment_ids, kv_segment_ids=ks_blk)
            if q_segment_ids is not None
            else {}
        )
        return flash_attention_with_lse(q, k_blk, v_blk, causal=False, **kw)

    def step(carry, _):
        o, m, l, k_blk, v_blk, ks_blk = carry
        o_j, lse_j = hop(k_blk, v_blk, ks_blk)
        o, m, l = _merge_lse(o, m, l, o_j, lse_j)
        perm = [(r, (r + 1) % n) for r in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if ks_blk is not None:
            ks_blk = lax.ppermute(ks_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk, ks_blk), None

    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    m0 = jnp.full((b, tq, h), _BIG_NEG, jnp.float32)
    l0 = jnp.zeros((b, tq, h), jnp.float32)
    (o, _, l, _, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v, kv_segment_ids), jnp.arange(n)
    )
    # A query with no visible key anywhere (all-pad source row) ends with
    # o exactly 0 — each empty hop contributes (o_c=0, lse=-BIG), and while
    # m stays at -BIG the merge adds w=1 to l per hop, so l ends at n, NOT
    # 0. The zero output therefore comes from o, and the max() below only
    # guards the true-zero-l case that the recurrence never produces.
    return (o / jnp.maximum(l[..., None], 1e-30)).astype(q.dtype)


def ring_flash_attention(q, k, v, *, axis_name: str = "seq", causal: bool = True,
                         segment_ids=None, window: int | None = None,
                         sinks: int = 0):
    """Ring attention whose per-hop block attention is the pallas flash
    kernel — the within-chip and cross-chip halves of the SAME online
    softmax: each hop computes its block's ``(out, lse)`` in O(T/n) memory
    on the MXU (`flash_attention_with_lse`), and the hop results merge by
    the standard logsumexp recurrence. Versus `ring_attention` (dense
    per-hop scores) this never materializes a [T/n, T/n] f32 score matrix
    in HBM and skips — not just masks — the above-diagonal hops via
    `lax.cond`, so a causal ring does ~half the block work.

    Same contract as `ring_attention`: call inside `shard_map` with
    ``[B, T/n, H, D]`` sequence shards; n == 1 degrades to exactly the
    local flash/dense path.

    ``segment_ids`` ([B, T/n], this device's shard of the packed-sequence
    ids) restricts attention to equal-id pairs: the kv ids rotate around the
    ring with their K/V blocks, and within each hop the kernel's block-level
    early-out prunes segment-disjoint tiles — so a packed ring pays ICI for
    every hop but FLOPs only where documents actually overlap. Every token
    belongs to its own segment and (causal) sees at least itself, so the
    merge normalizer never vanishes.

    ``window`` (requires ``causal``): sliding-window band over GLOBAL
    positions. Each hop runs the kernel with ``q_offset = hop_distance ×
    T/n`` so the band arithmetic sees true positions — hops entirely
    outside the window become static skip branches (zero kernel calls, via
    `lax.switch` over the hop distance), and a partially-covered hop
    block-skips its stale tiles in-kernel. The ring itself still makes all
    n − 1 ppermute hops (a collective must be uniform across the axis), so
    a window prunes FLOPs, not ICI traffic.

    ``sinks`` (global+local; requires ``window``): the first ``sinks``
    GLOBAL positions stay visible beyond the band. They live in global
    block 0, which visits every device once per rotation — the hop holding
    it (`j == 0`, a `lax.cond`) adds a small dense (out, lse) contribution
    over just the sink columns, masked disjointly from the band, merged by
    the same logsumexp recurrence as every other hop. Needs
    ``sinks ≤ T/n`` (the sink region must fit the first shard)."""
    from horovod_tpu.ops.flash_attention import flash_attention_with_lse

    check_window(window, causal)
    if sinks:
        if sinks < 0:
            raise ValueError(f"sinks must be >= 0, got {sinks}")
        if window is None:
            raise ValueError(
                "sinks need window set (full causal already sees them)"
            )
    n = compat.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    if sinks > t_local:
        raise ValueError(
            f"sinks ({sinks}) must fit one sequence shard (T/n = {t_local})"
        )

    def seg_kw(ks_blk):
        return (
            dict(q_segment_ids=segment_ids, kv_segment_ids=ks_blk)
            if segment_ids is not None
            else {}
        )

    def skip(*_):
        # Contributes nothing: lse = -BIG weights it to zero in the merge
        # without running any attention.
        return (
            jnp.zeros((b, t_local, h, d), q.dtype),
            jnp.full((b, t_local, h), _BIG_NEG, jnp.float32),
        )

    def hop_contrib(i, j, k_blk, v_blk, ks_blk):
        """(out, lse) of my queries against the block born at rank j,
        held here on hop i."""

        def diag(_):
            return flash_attention_with_lse(
                q, k_blk, v_blk, causal=True, window=window, **seg_kw(ks_blk)
            )

        def full(_):
            return flash_attention_with_lse(
                q, k_blk, v_blk, causal=False, **seg_kw(ks_blk)
            )

        if not causal:
            return full(None)
        if window is not None:
            # Hop distance d = my − j (mod n) equals the scan index i for
            # past blocks; wrapped hops (i > my, future blocks) route to the
            # extra skip branch. Each past distance gets its own STATIC
            # q_offset = d·T/n so the kernel's band arithmetic is global —
            # and distances whose newest key is already stale collapse to
            # skip at trace time (no kernel call compiled at all).
            def past(dist):
                if dist * t_local - (t_local - 1) >= window:
                    return skip  # even (row 0, col T/n−1) is out of band

                def branch(_):
                    return flash_attention_with_lse(
                        q, k_blk, v_blk, causal=True, window=window,
                        q_offset=dist * t_local, **seg_kw(ks_blk)
                    )

                return branch

            branches = [diag if dist == 0 else past(dist) for dist in range(n)]
            return lax.switch(jnp.where(i <= my, i, n), branches + [skip], None)
        return lax.cond(
            j == my, diag, lambda x: lax.cond(j < my, full, skip, x), None
        )

    def sink_contrib(k_blk, v_blk, ks_blk):
        """(out, lse) of my queries against the sink columns of global
        block 0 (currently held here): cols < sinks AND below the band —
        disjoint from every band tile, so nothing is counted twice. Dense
        [T/n, sinks] scores: the sink region is small by design."""
        kb = k_blk[:, :sinks]
        vb = v_blk[:, :sinks]
        s_ = _scores(q, kb, d ** -0.5)
        rows = (my * t_local + jnp.arange(t_local))[:, None]  # global q pos
        cols = jnp.arange(sinks)[None, :]
        keep = cols <= rows - window  # below the band (and causal: col<row)
        if ks_blk is not None:
            keep = keep[None] & (
                segment_ids[:, :, None] == ks_blk[:, None, :sinks]
            )
            keep = keep[:, None]  # [B, 1, Tq, S]
        else:
            keep = keep[None, None]  # [1, 1, Tq, S]
        s_ = jnp.where(keep, s_, _BIG_NEG)
        mx = s_.max(axis=-1, keepdims=True)
        p = jnp.exp(s_ - mx)
        p = jnp.where(keep, p, 0.0)
        lsum = p.sum(axis=-1, keepdims=True)
        empty = lsum == 0.0
        l_safe = jnp.where(empty, 1.0, lsum)
        o_ = jnp.einsum(
            "bhqk,bkhd->bqhd", (p / l_safe).astype(vb.dtype), vb,
            preferred_element_type=jnp.float32,
        ).astype(q.dtype)
        lse_ = jnp.where(empty, _BIG_NEG, mx + jnp.log(l_safe))[..., 0]
        return o_, jnp.transpose(lse_, (0, 2, 1))  # [B, Tq, H]

    merge = _merge_lse

    def step(carry, i):
        o, m, l, k_blk, v_blk, ks_blk = carry
        j = (my - i) % n  # the block born at rank j is here after i hops
        o_j, lse_j = hop_contrib(i, j, k_blk, v_blk, ks_blk)
        o, m, l = merge(o, m, l, o_j, lse_j)
        if sinks:
            o_s, lse_s = lax.cond(
                j == 0,
                lambda _: sink_contrib(k_blk, v_blk, ks_blk),
                skip,
                None,
            )
            o, m, l = merge(o, m, l, o_s, lse_s)
        perm = [(r, (r + 1) % n) for r in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if ks_blk is not None:
            ks_blk = lax.ppermute(ks_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk, ks_blk), None

    o0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    m0 = jnp.full((b, t_local, h), _BIG_NEG, jnp.float32)
    l0 = jnp.zeros((b, t_local, h), jnp.float32)
    (o, _, l, _, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v, segment_ids), jnp.arange(n)
    )
    return (o / l[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = "seq", causal: bool = True,
                      segment_ids=None, window: int | None = None,
                      sinks: int = 0):
    """All-to-all sequence parallelism: swap seq-sharding for head-sharding,
    attend over the full sequence locally, swap back.

    Inside `shard_map` with ``[B, T/n, H, D]`` shards; requires ``H % n == 0``.
    Two `lax.all_to_all` pairs per call — cheaper than a ring when n is small
    and heads are plentiful; the full-sequence [T] intermediate bounds the
    max context per chip (ring has no such bound).

    The local full-sequence attention runs the pallas flash kernel when its
    tiling holds (O(T) memory — without it, the [T, T] score matrix would
    cancel most of what head-swapping buys at long context), with the dense
    path as fallback exactly like `flash_attention` itself."""
    n = compat.axis_size(axis_name)
    if q.shape[2] % n != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the seq axis ({n})"
        )

    def to_heads(x):  # [B,T/n,H,D] -> [B,T,H/n,D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):  # [B,T,H/n,D] -> [B,T/n,H,D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    from horovod_tpu.ops.flash_attention import flash_attention

    seg_kw = {}
    if segment_ids is not None:
        # Per-token ids ([B, T/n] shard) have no head axis to swap; after the
        # head-swap every device attends over the FULL sequence, so it needs
        # the full ids — one [B, T] int gather, negligible next to K/V.
        full_ids = lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)
        seg_kw = dict(q_segment_ids=full_ids, kv_segment_ids=full_ids)
    out = flash_attention(
        to_heads(q), to_heads(k), to_heads(v), causal=causal, window=window,
        sinks=sinks, **seg_kw
    )
    return to_seq(out)
