"""TPU compute ops: attention implementations (dense / ring / Ulysses) and
pallas kernels for the hot paths."""

from horovod_tpu.ops.attention import (  # noqa: F401
    dense_attention,
    ring_attention,
    ulysses_attention,
)
