"""TPU compute ops: attention implementations (dense / ring / ring-flash /
Ulysses) and pallas kernels for the hot paths."""

from horovod_tpu.ops.attention import (  # noqa: F401
    dense_attention,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)

# NOTE: the flash kernel lives in `horovod_tpu.ops.flash_attention` (module);
# it is deliberately NOT re-exported here — a function named like its own
# submodule would shadow the module attribute on the package.
