"""Fused (chunked) linear + softmax cross-entropy for large-vocab LM heads.

The standard LM loss path materializes ``[B, T, vocab]`` logits twice — once
in the forward pass and once as the backward cotangent — and at long context
those two arrays dominate HBM (BASELINE.md context-envelope rows: at seq
131k they are the OOM driver the ``logits_dtype=bf16`` knob only halves).
This op computes ``cross_entropy(h @ W, labels)`` without ever building the
full logits array: a `lax.scan` over row-chunks computes each chunk's
``[C, vocab]`` logits tile on the fly — forward for the logsumexp, again in
the backward for the softmax — so peak extra memory is
O(chunk · vocab) instead of O(B · T · vocab), trading one extra head matmul
(recompute) for the two big arrays. The per-chunk matmuls stay MXU-shaped
(``[C, D] @ [D, V]`` with f32 accumulation), so the recompute rides the
systolic array rather than fighting it.

This is the moral equivalent of the "fused linear cross-entropy" kernels in
GPU land, expressed TPU-natively: `lax.scan` + `jax.custom_vjp` and XLA's
own matmul/reduction fusion, no hand-written kernel needed — the tile sizes
are large enough that XLA's codegen is already at the op-size ceiling.

Capability context: the reference's loss is a Keras one-liner on 10-class
MNIST (`/root/reference/tensorflow2_keras_mnist.py:62-65`) where none of
this matters; this op exists for the framework's long-context flagship,
where the head is the memory-binding layer.

Used by ``TransformerLM(fused_head_chunks=n)`` + ``Trainer(loss='module')``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _chunk_logits(hc, w, compute_dtype):
    """One chunk's logits tile ``[C, V]`` with f32 MXU accumulation."""
    return lax.dot(
        hc.astype(compute_dtype),
        w.astype(compute_dtype),
        precision=None,
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(h, w, labels, n_chunks: int = 8):
    """Per-token CE loss of ``h @ w`` against integer ``labels``, chunked.

    Args:
      h: ``[..., D]`` final hidden states (any leading shape; typically
        ``[B, T, D]``), f32 or bf16.
      w: ``[D, V]`` head kernel (the LM head's ``lm_head/kernel`` param).
      labels: integer ``[...]`` matching ``h``'s leading shape.
      n_chunks: static number of row-chunks the flattened ``B·T`` rows are
        scanned in; peak logits memory is ``ceil(B·T / n_chunks) · V`` floats
        (per forward or backward scan step).

    Returns:
      ``(loss, correct)`` — per-token f32 loss ``lse - logit[label]`` and a
      per-token f32 correctness indicator (``argmax == label``), both with
      ``labels``'s shape. ``correct`` carries no gradient (argmax is
      piecewise constant).
    """
    loss, correct, _ = _fwd(h, w, labels, n_chunks)
    return loss, correct


def _split(x, n_chunks):
    """Flatten leading dims and pad rows to a multiple of n_chunks.

    Returns (chunked ``[n_chunks, C, ...]``, n_valid_rows).
    """
    n = x.shape[0]
    c = -(-n // n_chunks)  # ceil
    pad = n_chunks * c - n
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0
        )
    return x.reshape((n_chunks, c) + x.shape[1:]), n


def _fwd(h, w, labels, n_chunks):
    lead = labels.shape
    compute_dtype = h.dtype
    hf = h.reshape(-1, h.shape[-1])
    lf = labels.reshape(-1).astype(jnp.int32)
    hc, n = _split(hf, n_chunks)
    lc, _ = _split(lf, n_chunks)

    def body(_, chunk):
        hck, lck = chunk
        logits = _chunk_logits(hck, w, compute_dtype)  # [C, V] f32
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, lck[:, None], axis=-1)[:, 0]
        correct = (jnp.argmax(logits, axis=-1) == lck).astype(jnp.float32)
        return None, (lse - ll, correct)

    _, (loss_c, corr_c) = lax.scan(body, None, (hc, lc))
    loss = loss_c.reshape(-1)[:n].reshape(lead)
    correct = corr_c.reshape(-1)[:n].reshape(lead)
    return loss, correct, (h, w, labels)


def _fwd_vjp(h, w, labels, n_chunks):
    loss, correct, res = _fwd(h, w, labels, n_chunks)
    return (loss, correct), res


def _bwd_vjp(n_chunks, res, cts):
    h, w, labels = res
    g_loss, _ = cts  # `correct` is piecewise constant — cotangent discarded
    compute_dtype = h.dtype
    hf = h.reshape(-1, h.shape[-1])
    lf = labels.reshape(-1).astype(jnp.int32)
    gf = g_loss.reshape(-1).astype(jnp.float32)
    hc, n = _split(hf, n_chunks)
    lc, _ = _split(lf, n_chunks)
    gc, _ = _split(gf, n_chunks)  # padded rows get g == 0 → no contribution

    v = w.shape[-1]

    def body(dw_acc, chunk):
        hck, lck, gck = chunk
        logits = _chunk_logits(hck, w, compute_dtype)  # recompute [C, V] f32
        p = jax.nn.softmax(logits, axis=-1)
        # d logits = (softmax - onehot(label)) · g  — the CE gradient.
        d = (p - jax.nn.one_hot(lck, v, dtype=jnp.float32)) * gck[:, None]
        dh_ck = lax.dot(
            d.astype(compute_dtype), w.astype(compute_dtype).T,
            preferred_element_type=jnp.float32,
        )
        dw_acc = dw_acc + lax.dot(
            hck.astype(compute_dtype).T, d.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        return dw_acc, dh_ck.astype(h.dtype)

    dw, dh_c = lax.scan(
        body, jnp.zeros(w.shape, jnp.float32), (hc, lc, gc)
    )
    dh = dh_c.reshape(-1, h.shape[-1])[:n].reshape(h.shape)
    return dh, dw.astype(w.dtype), None


fused_linear_cross_entropy.defvjp(_fwd_vjp, _bwd_vjp)
