"""Deterministic test/chaos utilities (no production code imports these
by default — `faults` activates only through the HVT_FAULT env contract)."""
