"""Deterministic fault injection — the reproducible chaos knob the
reference stack lacks entirely (SURVEY.md §5.3: "No fault injection
anywhere").

Contract: ``HVT_FAULT=rank:epoch[.step]:kind`` makes exactly one rank
misbehave at a chosen point in training, via a callback `fit()`
auto-installs (so any example/entry script is injectable unmodified).
Kinds:

* ``kill``  — SIGKILL self: the hard crash / OOM-killer / node-loss shape.
  Peers block in the next collective; the launcher's fail-stop grace window
  then reaps them (`launcher.Fleet.wait`).
* ``exitN`` — ``os._exit(N)`` (e.g. ``exit1``, ``exit143``): a crash with a
  chosen exit code, bypassing teardown the way a real abort does. ``exit143``
  exercises the supervisor's preemption classification.
* ``hang``  — stop making progress while staying alive: the wedged-collective
  failure mode (arXiv:1810.11112) that produces no exit code and is only
  detectable via stale heartbeats.
* ``slow:MS`` — a per-step host-side sleep of MS milliseconds on one rank,
  every batch end from the target epoch ON (``1:0:slow:50`` = rank 1,
  epoch 0 onward, +50 ms/step): the STRAGGLER shape — one rank pacing the
  whole fleet through its collectives while producing no error, no stale
  heartbeat, and (thanks to async dispatch) not even a longer step span of
  its own. The deterministic ground truth for skew detection: ``hvt-trace
  skew`` must name the rank, the live `SkewProbe` must point
  ``hvt_straggler_rank`` at it. Unlike every other kind this fault is
  RECURRING (a straggler is a rate, not an event) — stamps don't apply.
* ``reorder`` — swap the last two flight-recorded collective submissions'
  payloads in THIS rank's record (`flight.FlightRecorder.swap_last_two`),
  then wedge exactly like ``hang``: the deterministic reproduction of the
  mismatched-submission-order deadlock class (arXiv:1802.05799 — the bug
  Horovod's coordinator exists to prevent). The supervisor classifies the
  hang and auto-collects every member's flight record; ``hvt-sched
  replay`` must then name this rank, the swapped seq, and the op — the
  acceptance run for the recorder. Requires ``HVT_FLIGHT_RECORD`` (the
  swap is a no-op with the recorder off; the wedge still fires).
* ``netdrop:MS`` — a client-side DATA-PLANE fault: the hvt-data service
  client (`data.client.ServiceClient`) drops its dispatcher connection
  and delays the reconnect by MS milliseconds before EVERY service fetch
  DURING the target epoch on the target rank — a bounded data-plane
  brownout. A short window is absorbed by the `read_with_retries`
  budget; a window longer than the budget forces the graceful-degrade
  arc (fall back to rank-local feeding from the same cursor, re-attach
  at the next epoch boundary) deterministically. Fired by the data
  plane, not this callback (`data_fault_ms`); window-bounded by
  construction, so stamps are not needed (honoured if set).
* ``dataslow:MS`` — the dispatcher-side twin: the hvt-data dispatcher
  (`data.service`) delays every batch response to the target rank's
  shard by MS milliseconds from the target epoch ON (a slow data
  service is a rate, like ``slow:MS``) — the data-plane straggler
  shape, visible as input-phase time on the fed ranks. Also fired by
  the data plane via `data_fault_ms`.
* ``leave`` — clean SIGTERM-style self-removal: the planned-departure shape
  (scheduler preemption honored gracefully, elastic shrink testing). Under
  an elastic launch (``HVT_ELASTIC_COORDINATOR`` set) it only RECORDS leave
  intent (`request_leave`); the elastic callback then executes the
  departure at the epoch boundary — coordinator notified, synchronized
  teardown, exit 143 — so survivors shrink instead of aborting. Outside
  elastic mode it degrades to a SIGTERM to self: with
  `PreemptionCheckpointCallback` installed that is the graceful save-and-
  stop path, without it the process dies of SIGTERM and the supervisor
  classifies a preemption.
* ``hostdown`` — whole-HOST failure: SIGKILL every rank sharing the
  firing rank's host in one stroke (peers first, self last), so a fleet
  supervisor sees the co-resident deaths land together — the node-loss
  shape `hvt-launch fleet` must reclassify as ONE ``host_lost`` event
  (charged once, host quarantined) instead of N independent crashes.
  Host membership comes from a pid registry: when the launcher exports
  ``HVT_FAULT_HOST_PIDS`` (a per-host directory — the fleet scheduler
  points every rank it places on host H at ``<dir>/H``), each rank's
  fault callback registers its pid there at epoch begin, and the firing
  rank kills every registered pid that is still alive (stale files from
  exited members are skipped and swept). Without the registry the kind
  degrades to a self-SIGKILL — a one-rank host going down.
* ``corrupt`` — damage the newest checkpoint file/shard under
  ``PS_MODEL_PATH`` (truncate to half, bit-flip the first surviving byte
  — both without touching its ``.sha256`` sidecar), then SIGKILL self: the
  writer-killed-mid-fsync / bit-rot shape. Drives the corruption-recovery
  path deterministically: the relaunched run must detect the digest
  mismatch and resume from the previous complete checkpoint instead of
  crashing on (or silently loading) garbage. An optional target picks the
  victim instead of the newest file: ``corrupt@epoch3`` hits epoch 3's
  checkpoint artifact (testing fallback across a HISTORY of checkpoints,
  not just the head), ``corrupt@shard1`` hits shard file 1 of the newest
  sharded checkpoint (one process's shard rots, the others stay clean),
  and ``corrupt@epoch3/shard1`` combines both.

The fault fires at the first ``on_batch_end`` of the target epoch — mid-epoch
by construction (after the epoch's checkpoint boundary, before the next), so
kill-and-resume tests lose partial-epoch work exactly like a real fault.

**Step filter**: ``rank:epoch.step:kind`` (e.g. ``2:1.3:leave``) defers the
fault to the chosen OPTIMIZER step's ``on_batch_end`` instead of the
epoch's first batch — chaos tests can then target a precise mid-epoch
point (the step-granular recovery paths: sub-epoch commits, mid-epoch
rescale, ``initial_step`` resume). The trigger is "``step`` steps done or
more" (``>=``), so ``steps_per_execution`` chunks that stride past the
target still fire at the next boundary — but a run RESUMED at or past the
target step (``fit(initial_step=)`` from the trainer's recorded resume
point) does not re-fire: the fault already fired in the run being
resumed. Without ``.step`` the behavior is unchanged: first batch end of
the epoch (epoch-filtered faults still need ``HVT_FAULT_STAMP`` to stay
one-shot across relaunches that resume INTO the target epoch).

One-shot faults: set ``HVT_FAULT_STAMP=<path>`` and the callback touches the
stamp file just before firing and never fires while it exists — across
process *relaunches*, which is what makes "inject once, assert exactly one
supervised restart" deterministic. Without a stamp the fault fires every
launch: the deterministic crash loop that must exhaust the supervisor's
no-progress budget.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time

from horovod_tpu import runtime
from horovod_tpu.analysis import registry
from horovod_tpu.training.callbacks import Callback

ENV_FAULT = "HVT_FAULT"
ENV_FAULT_STAMP = "HVT_FAULT_STAMP"
ENV_FAULT_HOST_PIDS = "HVT_FAULT_HOST_PIDS"

KINDS = ("kill", "hang", "leave", "corrupt", "reorder", "hostdown")
# plus exitN, corrupt@<target> (parse_plan / corrupt_target), slow:MS
# (slow_ms), and the data-plane kinds netdrop:MS / dataslow:MS
# (netdrop_ms / dataslow_ms, fired via data_fault_ms)

# Process-wide leave intent (the `leave` fault kind under an elastic
# launch). The elastic epoch-end agreement consumes it; tests reset it.
_leave_requested = False


def request_leave() -> None:
    """Record that this process should leave the fleet at the next elastic
    commit boundary (consumed by `elastic.ElasticStateCallback`)."""
    global _leave_requested
    _leave_requested = True


def leave_requested() -> bool:
    return _leave_requested


def reset_leave() -> None:
    global _leave_requested
    _leave_requested = False


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One planned fault: ``rank`` fires ``kind`` mid-epoch ``epoch`` —
    at its first batch end, or at optimizer step ``step`` (1-based count
    of completed steps) when the ``epoch.step`` form was used."""

    rank: int
    epoch: int
    kind: str
    step: int | None = None

    @property
    def exit_code(self) -> int | None:
        if self.kind.startswith("exit"):
            return int(self.kind[4:])
        return None

    @property
    def slow_ms(self) -> float | None:
        """The per-step sleep of a ``slow:MS`` plan, or None."""
        if self.kind.startswith("slow:"):
            return float(self.kind[5:])
        return None

    @property
    def netdrop_ms(self) -> float | None:
        """The reconnect delay of a ``netdrop:MS`` plan, or None."""
        if self.kind.startswith("netdrop:"):
            return float(self.kind[8:])
        return None

    @property
    def dataslow_ms(self) -> float | None:
        """The per-response delay of a ``dataslow:MS`` plan, or None."""
        if self.kind.startswith("dataslow:"):
            return float(self.kind[9:])
        return None


def parse_plan(spec: str) -> FaultPlan:
    """Parse ``rank:epoch[.step]:kind`` (kind: ``kill`` | ``hang`` |
    ``exitN`` | ``leave`` | ``corrupt[@target]`` | ``slow:MS`` — the
    last carries its own colon, so the kind field is everything past
    the second separator)."""
    parts = spec.split(":", 2)
    if len(parts) != 3 or not parts[2]:
        raise ValueError(
            f"HVT_FAULT must be rank:epoch[.step]:kind, got {spec!r}"
        )
    rank_s, epoch_s, kind = parts
    step = None
    if "." in epoch_s:
        epoch_s, step_s = epoch_s.split(".", 1)
        try:
            step = int(step_s)
        except ValueError:
            raise ValueError(
                f"HVT_FAULT step must be an integer, got {spec!r}"
            ) from None
        if step < 1:
            raise ValueError(
                f"HVT_FAULT step is a 1-based completed-step count, "
                f"got {spec!r}"
            )
    try:
        rank, epoch = int(rank_s), int(epoch_s)
    except ValueError:
        raise ValueError(
            f"HVT_FAULT rank/epoch must be integers, got {spec!r}"
        ) from None
    if kind not in KINDS:
        if kind.startswith("exit"):
            try:
                int(kind[4:])
            except ValueError:
                raise ValueError(
                    f"HVT_FAULT exit kind needs an integer code "
                    f"(exit1, exit143, ...), got {kind!r}"
                ) from None
        elif kind.startswith("corrupt@"):
            corrupt_target(kind)  # validates; raises on a bad target
        elif kind.startswith(("slow:", "netdrop:", "dataslow:")):
            prefix, ms_s = kind.split(":", 1)
            try:
                ms = float(ms_s)
            except ValueError:
                raise ValueError(
                    f"HVT_FAULT {prefix} kind needs a millisecond count "
                    f"({prefix}:50), got {kind!r}"
                ) from None
            if ms <= 0:
                raise ValueError(
                    f"HVT_FAULT {prefix}:MS needs MS > 0, got {kind!r}"
                )
        else:
            raise ValueError(
                f"HVT_FAULT kind must be kill, hang, leave, reorder, "
                f"hostdown, corrupt[@epochN][/shardM], slow:MS, "
                f"netdrop:MS, dataslow:MS or exitN, got {kind!r}"
            )
    return FaultPlan(rank=rank, epoch=epoch, kind=kind, step=step)


def data_fault_ms(kind: str, *, epoch: int,
                  rank: int | None = None) -> float | None:
    """The active ``HVT_FAULT`` plan's data-plane delay (ms) applying at
    this position, or None — how the hvt-data client (``netdrop``) and
    dispatcher (``dataslow``) consult the fault plan, since the trainer
    callback cannot reach into the data plane's sockets.

    ``netdrop`` fires for every service fetch DURING the target epoch
    (``==`` — a bounded brownout window, so degrade → local → re-attach
    is deterministic); a set ``HVT_FAULT_STAMP`` makes it one-shot
    instead (touched before the first fire, never fires while it
    exists). ``dataslow`` fires from the target epoch ON (``>=`` — a
    slow dispatcher is a rate, like ``slow:MS``; stamps don't apply).
    ``rank`` is matched against the plan's rank when given (the client
    passes its shard index). Parsed fresh per call, so a test's
    monkeypatched env is honoured; an unset or unparseable plan is
    simply no fault."""
    if kind not in ("netdrop", "dataslow"):
        raise ValueError(
            f"data_fault_ms kind must be netdrop or dataslow, got {kind!r}"
        )
    spec = registry.get_str(ENV_FAULT)
    if not spec:
        return None
    try:
        plan = parse_plan(spec)
    except ValueError:
        return None
    if rank is not None and plan.rank != rank:
        return None
    if kind == "netdrop":
        ms = plan.netdrop_ms
        if ms is None or epoch != plan.epoch:
            return None
        stamp = registry.get_str(ENV_FAULT_STAMP)
        if stamp:
            if os.path.exists(stamp):
                return None  # one-shot spent in an earlier launch
            d = os.path.dirname(stamp)
            if d:
                os.makedirs(d, exist_ok=True)
            # Empty stamp touch: existence IS the payload.
            open(stamp, "w").close()  # hvt: noqa[HVT005]
        return ms
    ms = plan.dataslow_ms
    if ms is None or epoch < plan.epoch:
        return None
    return ms


def register_host_pid(pid_dir: str, pid: int | None = None) -> str:
    """Record ``pid`` (default: this process) as resident on the host the
    ``pid_dir`` stands for — one empty file named after the pid, existence
    is the payload. Called by every rank's fault callback when the
    launcher exports ``HVT_FAULT_HOST_PIDS``; the ``hostdown`` kind reads
    the directory back to find its co-resident victims. Registration
    sweeps entries whose processes are gone, so a respawned member's
    stale predecessor can never be 'killed' again (pid-reuse hygiene)."""
    pid = os.getpid() if pid is None else pid
    os.makedirs(pid_dir, exist_ok=True)
    for name in os.listdir(pid_dir):
        if not name.isdigit():
            continue
        try:
            os.kill(int(name), 0)
        except ProcessLookupError:
            try:
                os.remove(os.path.join(pid_dir, name))
            except OSError:
                pass
        except PermissionError:
            pass  # alive, not ours to probe — keep it
    path = os.path.join(pid_dir, str(pid))
    # Empty marker touch: the filename IS the record, nothing to tear.
    open(path, "w").close()  # hvt: noqa[HVT005]
    return path


def host_pids(pid_dir: str) -> list[int]:
    """Every pid registered in a host's pid directory, sorted."""
    try:
        names = os.listdir(pid_dir)
    except OSError:
        return []
    return sorted(int(n) for n in names if n.isdigit())


def corrupt_target(kind: str) -> tuple:
    """Parse a ``corrupt`` kind's optional target: ``corrupt`` →
    ``(None, None)`` (the newest payload), ``corrupt@epoch3`` → ``(3,
    None)``, ``corrupt@shard1`` → ``(None, 1)``, ``corrupt@epoch3/shard1``
    → ``(3, 1)``."""
    if kind == "corrupt":
        return None, None
    target = kind[len("corrupt@"):]
    epoch = shard = None
    for part in target.split("/"):
        if part.startswith("epoch") and part[5:].isdigit():
            epoch = int(part[5:])
        elif part.startswith("shard") and part[5:].isdigit():
            shard = int(part[5:])
        else:
            raise ValueError(
                f"HVT_FAULT corrupt target must be epochN, shardM or "
                f"epochN/shardM, got {target!r}"
            )
    return epoch, shard


def newest_checkpoint_file(
    model_dir: str, epoch: int | None = None, shard: int | None = None
) -> str | None:
    """Newest checkpoint payload file under ``model_dir`` (recursive, so
    shard files inside ``*.shards/`` dirs count), by mtime. Digest
    sidecars are excluded — the ``corrupt`` fault damages payloads, not
    the record of what they should have been (corrupting the record would
    also trigger recovery, but proves less).

    ``epoch`` restricts candidates to that epoch's checkpoint artifact
    (single file or shards dir); ``shard`` restricts to ``shard-{shard}``
    files of sharded checkpoints (single-file checkpoints then never
    match). Both None = the newest payload anywhere, the classic fault."""
    from horovod_tpu import checkpoint

    newest = None
    for root, _, files in os.walk(model_dir):
        base = os.path.basename(root)
        in_shards_dir = base.endswith(checkpoint.SHARDED_SUFFIX)
        dir_m = checkpoint.CHECKPOINT_RE.search(base) if in_shards_dir else None
        for name in files:
            # Skip digest sidecars AND atomic-write temp files: corrupting
            # an in-flight '...tmp.<pid>.<seq>' would be overwritten by
            # its own os.replace (silent no-op for the fault).
            if name.endswith(checkpoint.DIGEST_SUFFIX) or ".tmp." in name:
                continue
            is_shard_file = in_shards_dir and name.startswith("shard-")
            m = checkpoint.CHECKPOINT_RE.search(name)
            if not m and not is_shard_file:
                continue
            file_epoch = (
                int(dir_m.group(1)) if is_shard_file and dir_m
                else (int(m.group(1)) if m else None)
            )
            if epoch is not None and file_epoch != epoch:
                continue
            if shard is not None and not (
                is_shard_file and name.startswith(f"shard-{shard}.")
            ):
                continue
            full = os.path.join(root, name)
            try:
                key = (os.stat(full).st_mtime_ns, full)
            except OSError:
                continue
            if newest is None or key > newest[0]:
                newest = (key, full)
    return newest[1] if newest else None


def corrupt_file(path: str) -> None:
    """Deterministically damage a file in place: truncate to half its
    size, then flip every bit of the first remaining byte. The ``.sha256``
    sidecar (if any) is left untouched, so integrity verification MUST now
    fail for the file."""
    size = os.path.getsize(path)
    # Deliberate corruption — tearing the file is this function's JOB.
    with open(path, "r+b") as f:  # hvt: noqa[HVT005]
        f.truncate(max(size // 2, 1))
        f.seek(0)
        first = f.read(1) or b"\0"
        f.seek(0)
        f.write(bytes([first[0] ^ 0xFF]))


class FaultInjectionCallback(Callback):
    """Fires the planned fault at the first batch end of the target epoch on
    the target rank. Installed automatically by ``fit()`` when ``HVT_FAULT``
    is set (`callbacks.env_callbacks`); constructible directly for in-process
    tests."""

    def __init__(self, plan: FaultPlan, stamp: str | None = None):
        self.plan = plan
        self.stamp = stamp
        self._epoch: int | None = None

    @classmethod
    def from_env(cls) -> "FaultInjectionCallback":
        spec = registry.get_str(ENV_FAULT)
        if spec is None:
            raise ValueError(
                f"{ENV_FAULT} is not set — from_env() needs a "
                "rank:epoch[.step]:kind fault plan"
            )
        return cls(
            parse_plan(spec),
            stamp=registry.get_str(ENV_FAULT_STAMP),
        )

    def on_epoch_begin(self, epoch: int, logs=None):
        self._epoch = epoch
        pid_dir = registry.get_str(ENV_FAULT_HOST_PIDS)
        if pid_dir:
            # EVERY rank (not just the fault's target) keeps its host
            # residency registered — the `hostdown` stroke needs the
            # victims' pids, and a registry refreshed per epoch also
            # covers members respawned onto the host mid-run.
            try:
                register_host_pid(pid_dir)
            except OSError:
                pass  # chaos bookkeeping must never fail training

    def on_batch_end(self, batch: int, logs=None):
        if (
            self.plan.netdrop_ms is not None
            or self.plan.dataslow_ms is not None
        ):
            # Data-plane kinds: fired by the hvt-data client/dispatcher
            # (`data_fault_ms`), not by the trainer callback — the
            # callback cannot reach into the data plane's sockets.
            return
        if self.plan.slow_ms is not None:
            # The straggler fault is RECURRING: every batch end from the
            # target epoch on, this rank drags its feet by MS — stamps
            # and step filters don't apply (a straggler is a rate).
            if (
                self._epoch is not None
                and self._epoch >= self.plan.epoch
                and runtime.rank() == self.plan.rank
            ):
                time.sleep(self.plan.slow_ms / 1e3)
            return
        if self._epoch != self.plan.epoch:
            return
        if runtime.rank() != self.plan.rank:
            return
        if self.plan.step is not None:
            if batch + 1 < self.plan.step:
                # Step-filtered plan: hold fire until the chosen optimizer
                # step completes (>= so steps_per_execution strides that
                # jump past the target still fire at the next boundary).
                return
            if (
                self.trainer is not None
                and getattr(self.trainer, "_resume_epoch", 0)
                == self.plan.epoch
                and getattr(self.trainer, "_resume_step", 0)
                >= self.plan.step
            ):
                # The fit RESUMED at or past the target step: the fault
                # already fired in the run being resumed (that is why a
                # resume point past it exists), so do not re-fire — the
                # stamp-free form of the one-shot contract for resumed
                # step-granular runs.
                return
        if self.stamp and os.path.exists(self.stamp):
            return  # already fired in a previous launch — one-shot spent
        if self.stamp:
            d = os.path.dirname(self.stamp)
            if d:
                os.makedirs(d, exist_ok=True)
            # Empty stamp touch: existence IS the payload, nothing to tear.
            open(self.stamp, "w").close()  # hvt: noqa[HVT005]
        self._fire()

    def _fire(self):  # pragma: no cover — ends or wedges the process
        at = f"epoch {self.plan.epoch}" + (
            f" step {self.plan.step}" if self.plan.step is not None else ""
        )
        print(
            f"FaultInjection: rank {self.plan.rank} firing "
            f"{self.plan.kind!r} at {at}",
            flush=True,
        )
        if self.plan.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif self.plan.kind == "hostdown":
            # Whole-host stroke: SIGKILL every co-resident rank first,
            # self last, so the supervisor's next poll sees the host's
            # deaths together (the one-`host_lost` classification window).
            pid_dir = registry.get_str(ENV_FAULT_HOST_PIDS)
            me = os.getpid()
            host = registry.get_str("HVT_FLEET_HOST")
            for pid in (host_pids(pid_dir) if pid_dir else []):
                if pid == me:
                    continue
                try:
                    os.kill(pid, signal.SIGKILL)
                    print(
                        f"FaultInjection: hostdown"
                        f"{f' ({host})' if host else ''} killed "
                        f"co-resident pid {pid}",
                        flush=True,
                    )
                except (ProcessLookupError, PermissionError):
                    continue  # stale registration — already gone
            os.kill(me, signal.SIGKILL)
        elif self.plan.kind == "hang":
            self._wedge()
        elif self.plan.kind == "reorder":
            # Seed a real submission-order divergence in THIS rank's
            # flight record, then wedge: the supervisor's hang path
            # collects every member's record and `hvt-sched replay`
            # names this rank/seq/op (the recorder acceptance fault).
            from horovod_tpu import flight

            if flight.RECORDER is not None:
                flight.RECORDER.swap_last_two()
            self._wedge()
        elif self.plan.kind == "leave":
            if registry.get_str(runtime.ENV_ELASTIC_COORDINATOR):
                # Elastic launch: record intent; the elastic callback
                # executes the clean departure at the epoch boundary.
                request_leave()
            else:
                os.kill(os.getpid(), signal.SIGTERM)
        elif self.plan.kind.startswith("corrupt"):
            epoch, shard = corrupt_target(self.plan.kind)
            target = newest_checkpoint_file(
                os.environ.get("PS_MODEL_PATH", "./models"),
                epoch=epoch, shard=shard,
            )
            if target is not None:
                print(f"FaultInjection: corrupting {target}", flush=True)
                corrupt_file(target)
            os.kill(os.getpid(), signal.SIGKILL)
        else:
            os._exit(self.plan.exit_code)

    @staticmethod
    def _wedge():  # pragma: no cover — never returns
        """Stay alive, make no progress, touch no heartbeat — only a
        stale-heartbeat supervisor can reap this. A Python-level sleep,
        so the SIGTERM flight-dump handler still runs when the
        supervisor's hang teardown arrives."""
        while True:
            time.sleep(3600)
