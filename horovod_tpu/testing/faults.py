"""Deterministic fault injection — the reproducible chaos knob the
reference stack lacks entirely (SURVEY.md §5.3: "No fault injection
anywhere").

Contract: ``HVT_FAULT=rank:epoch:kind`` makes exactly one rank misbehave at
a chosen point in training, via a callback `fit()` auto-installs (so any
example/entry script is injectable unmodified). Kinds:

* ``kill``  — SIGKILL self: the hard crash / OOM-killer / node-loss shape.
  Peers block in the next collective; the launcher's fail-stop grace window
  then reaps them (`launcher.Fleet.wait`).
* ``exitN`` — ``os._exit(N)`` (e.g. ``exit1``, ``exit143``): a crash with a
  chosen exit code, bypassing teardown the way a real abort does. ``exit143``
  exercises the supervisor's preemption classification.
* ``hang``  — stop making progress while staying alive: the wedged-collective
  failure mode (arXiv:1810.11112) that produces no exit code and is only
  detectable via stale heartbeats.

The fault fires at the first ``on_batch_end`` of the target epoch — mid-epoch
by construction (after the epoch's checkpoint boundary, before the next), so
kill-and-resume tests lose partial-epoch work exactly like a real fault.

One-shot faults: set ``HVT_FAULT_STAMP=<path>`` and the callback touches the
stamp file just before firing and never fires while it exists — across
process *relaunches*, which is what makes "inject once, assert exactly one
supervised restart" deterministic. Without a stamp the fault fires every
launch: the deterministic crash loop that must exhaust the supervisor's
no-progress budget.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time

from horovod_tpu import runtime
from horovod_tpu.training.callbacks import Callback

ENV_FAULT = "HVT_FAULT"
ENV_FAULT_STAMP = "HVT_FAULT_STAMP"

KINDS = ("kill", "hang")  # plus exitN, validated in parse_plan


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One planned fault: ``rank`` fires ``kind`` mid-epoch ``epoch``."""

    rank: int
    epoch: int
    kind: str

    @property
    def exit_code(self) -> int | None:
        if self.kind.startswith("exit"):
            return int(self.kind[4:])
        return None


def parse_plan(spec: str) -> FaultPlan:
    """Parse ``rank:epoch:kind`` (kind: ``kill`` | ``hang`` | ``exitN``)."""
    parts = spec.split(":")
    if len(parts) != 3:
        raise ValueError(
            f"HVT_FAULT must be rank:epoch:kind, got {spec!r}"
        )
    rank_s, epoch_s, kind = parts
    try:
        rank, epoch = int(rank_s), int(epoch_s)
    except ValueError:
        raise ValueError(
            f"HVT_FAULT rank/epoch must be integers, got {spec!r}"
        ) from None
    if kind not in KINDS:
        if kind.startswith("exit"):
            try:
                int(kind[4:])
            except ValueError:
                raise ValueError(
                    f"HVT_FAULT exit kind needs an integer code "
                    f"(exit1, exit143, ...), got {kind!r}"
                ) from None
        else:
            raise ValueError(
                f"HVT_FAULT kind must be kill, hang or exitN, got {kind!r}"
            )
    return FaultPlan(rank=rank, epoch=epoch, kind=kind)


class FaultInjectionCallback(Callback):
    """Fires the planned fault at the first batch end of the target epoch on
    the target rank. Installed automatically by ``fit()`` when ``HVT_FAULT``
    is set (`callbacks.env_callbacks`); constructible directly for in-process
    tests."""

    def __init__(self, plan: FaultPlan, stamp: str | None = None):
        self.plan = plan
        self.stamp = stamp
        self._epoch: int | None = None

    @classmethod
    def from_env(cls) -> "FaultInjectionCallback":
        return cls(
            parse_plan(os.environ[ENV_FAULT]),
            stamp=os.environ.get(ENV_FAULT_STAMP) or None,
        )

    def on_epoch_begin(self, epoch: int, logs=None):
        self._epoch = epoch

    def on_batch_end(self, batch: int, logs=None):
        if self._epoch != self.plan.epoch:
            return
        if runtime.rank() != self.plan.rank:
            return
        if self.stamp and os.path.exists(self.stamp):
            return  # already fired in a previous launch — one-shot spent
        if self.stamp:
            d = os.path.dirname(self.stamp)
            if d:
                os.makedirs(d, exist_ok=True)
            open(self.stamp, "w").close()
        self._fire()

    def _fire(self):  # pragma: no cover — ends or wedges the process
        print(
            f"FaultInjection: rank {self.plan.rank} firing "
            f"{self.plan.kind!r} at epoch {self.plan.epoch}",
            flush=True,
        )
        if self.plan.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif self.plan.kind == "hang":
            # Stay alive, make no progress, touch no heartbeat — only a
            # stale-heartbeat supervisor can reap this.
            while True:
                time.sleep(3600)
        else:
            os._exit(self.plan.exit_code)
