"""Poisoned-persistent-XLA-cache detection for the test harness.

The suite shares one persistent XLA compilation cache (tests/.jax_cache,
conftest.py) because it is compile-dominated — but a subprocess test
that SIGKILLs/os._exit()s a training child can tear a cache write, and
on this jax floor a torn entry later either fails DESERIALIZATION
loudly, or — far worse — deserializes into a silently WRONG executable
(observed twice: an EMA shadow off by exactly the decay factor, PR 5 and
PR 8). The wrongness mode looks like a phantom numeric mismatch and has
cost two sessions real time; the fix is always the same:
``rm -rf tests/.jax_cache`` and re-run.

Two guards, both wired into conftest:

* `scan_cache_dir` at session start — a zero-byte or stale ``.tmp``
  entry is definitionally torn (the atomic-rename never completed);
  conftest deletes them and says so, before they can poison a test.
* `poisoned_cache_advice` at failure time — when a test fails with a
  deserialization-shaped error (`DESERIALIZATION_SIGNATURES`), the
  report grows an actionable section naming the cache dir and the
  ``rm -rf`` command instead of leaving the operator to chase phantoms.

Numeric wrongness without a deserialization error cannot be detected
here (the executable runs; it is just wrong) — that is why the advice
also triggers on the *assertion shapes* the poisoned cache historically
produced only when the persistent cache is actually enabled, and why it
is phrased as a first-thing-to-try hint, not a diagnosis.
"""

from __future__ import annotations

import os
import re

# Error text that indicates a torn cache entry failed to deserialize —
# the LOUD poisoning mode. Matched case-insensitively against the
# failure repr.
DESERIALIZATION_SIGNATURES = (
    r"failed to deserialize",
    r"deserializ\w+ (?:error|failure|failed)",
    r"error loading program from (?:the )?compilation cache",
    r"compilation cache (?:entry|read|load)\w* (?:is )?(?:corrupt|invalid|failed)",
    r"xla runtime error.*deserial",
    r"invalid (?:serialized|flatbuffer)",
    r"zlib\.error",
    r"data loss:",
)

_SIGNATURE_RE = re.compile(
    "|".join(f"(?:{s})" for s in DESERIALIZATION_SIGNATURES),
    re.IGNORECASE,
)


def cache_dir_from_env(environ=None) -> str | None:
    """The persistent cache directory in effect, or None when disabled
    (the conftest contract: JAX_ENABLE_COMPILATION_CACHE=0 wins)."""
    env = os.environ if environ is None else environ
    if env.get("JAX_ENABLE_COMPILATION_CACHE") == "0":
        return None
    return env.get("JAX_COMPILATION_CACHE_DIR") or None


def poisoned_cache_advice(failure_text: str,
                          cache_dir: str | None) -> str | None:
    """An actionable hint when `failure_text` looks like the documented
    poisoned-cache failure mode and a persistent cache is in play."""
    if not cache_dir:
        return None
    if not _SIGNATURE_RE.search(failure_text):
        return None
    return (
        "This failure matches the torn persistent-XLA-cache signature "
        "(a SIGKILLed child can tear a cache write; the entry later "
        "fails to deserialize — or worse, deserializes into a silently "
        "wrong executable that shows up as a phantom numeric mismatch; "
        "see tests/conftest.py and CHANGES.md PR 5/PR 8 notes).\n"
        f"First thing to try:  rm -rf {cache_dir}  and re-run.\n"
        "If it persists with a cold cache, it is a real failure."
    )


def scan_cache_dir(cache_dir: str | None) -> list[str]:
    """Paths of definitionally-torn entries in the persistent cache:
    zero-byte files and orphaned temp files from interrupted writes.
    Safe to delete (the cache is keyed content; jax recompiles)."""
    if not cache_dir or not os.path.isdir(cache_dir):
        return []
    torn = []
    for dirpath, _, filenames in os.walk(cache_dir):
        for name in filenames:
            path = os.path.join(dirpath, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            if size == 0 or ".tmp" in name:
                torn.append(path)
    return sorted(torn)


def remove_torn_entries(cache_dir: str | None) -> list[str]:
    """Delete what `scan_cache_dir` found; returns the removed paths."""
    removed = []
    for path in scan_cache_dir(cache_dir):
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    return removed
