"""Launcher / cluster orchestration — the L5+L6 replacement (SURVEY.md §1).

The reference launches with ``mpirun --hostfile /generated/hostfile`` under a
platform that provisions workers running ``sleep infinity`` (README.md:57,
distributed-keras-sample.yaml:1-11) and gates CI on a metric range
(config.yaml:8-11). TPU-native, that becomes:

* `launcher.run_local` — N processes on this host (the "Docker-local mpirun"
  test mode, README.md:53-58), coordinator address auto-assigned.
* `launcher.run_hosts` — one process per host over ssh with env propagation
  (the ``mpirun -x`` role), coordinator = first host.
* `ci_gate` — aggregate a metric stream and assert a target range (the
  Gradient workflow's ``checks`` block).
* `job` — YAML job specs binding the two together (the `.ps_project` role).
* `supervisor` — fail-*restart* around either launcher: crash/preemption/
  hang classification, heartbeat hang detection, progress-aware restart
  budget, JSONL restart journal (``run``/``pod`` ``--max-restarts``
  ``--backoff`` ``--heartbeat-timeout``; the job spec's ``restart:`` block).

CLI:  python -m horovod_tpu.launch run --nprocs 4 -- python train.py
      python -m horovod_tpu.launch run --nprocs 4 --max-restarts 3 \\
          --heartbeat-timeout 300 -- python train.py
      python -m horovod_tpu.launch pod --hostfile hosts.txt -- python train.py
      python -m horovod_tpu.launch gate --metrics m.jsonl --check loss=0.0..0.3
      python -m horovod_tpu.launch job launch/jobs/mnist-ci.yaml
"""

from horovod_tpu.launch.launcher import (
    Fleet,
    run_hosts,
    run_local,
    start_hosts,
    start_local,
)
from horovod_tpu.launch.ci_gate import check_metrics, parse_target
from horovod_tpu.launch.supervisor import (
    RestartPolicy,
    supervise,
    supervise_hosts,
    supervise_local,
)

__all__ = [
    "Fleet",
    "run_local",
    "run_hosts",
    "start_local",
    "start_hosts",
    "check_metrics",
    "parse_target",
    "RestartPolicy",
    "supervise",
    "supervise_local",
    "supervise_hosts",
]
