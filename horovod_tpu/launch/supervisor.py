"""Restart supervisor — fail-*restart* semantics around the fail-stop
launchers (SURVEY.md §5.3, the layer the reference leaves to a human).

The reference's fault model ends at fail-stop: any rank failure kills the
MPI job (`launcher.Fleet.wait`) and recovery is *manual* — an operator
reruns the command and `restore_latest_and_broadcast` resumes from the
newest checkpoint (`tests/test_resume_e2e.py` proves that leg). This module
closes the loop: `supervise()` relaunches the whole fleet automatically,
with three properties the manual loop lacks:

* **Failure classification.** Exit 143 / SIGTERM is a *preemption* (the
  gang-scheduler reclaiming the slice — the convention
  `PreemptionCheckpointCallback(exit_code=143)` emits); anything else
  nonzero is a *crash*; a fleet the supervisor itself had to kill for
  stale heartbeats is a *hang*.
* **Progress-aware restart budget.** The budget decrements only when a
  launch made *no progress* (the newest checkpoint under ``model_dir``
  unchanged since the previous launch). A transient fault that keeps
  losing different epochs restarts indefinitely; a deterministic crash
  loop — same fault, same epoch, every launch — burns through
  ``max_restarts`` and exits with the original exit code. Backoff is
  exponential between no-progress restarts and resets on progress.
* **Hang detection.** A rank wedged in a collective produces no exit code
  at all (the classic NCCL/ICI failure mode, arXiv:1810.11112). Each rank
  touches ``<heartbeat_dir>/rank-<i>`` from a trainer callback
  (`callbacks.HeartbeatCallback`, auto-installed by ``fit()`` when the
  supervisor exports ``HVT_HEARTBEAT_DIR``); when the *newest* heartbeat
  is older than ``heartbeat_timeout`` the supervisor kills the fleet and
  relaunches it. Size the timeout above worst-case step + compile time —
  the first beat lands at train begin, before the first step compiles.
  On multi-host (pod) launches hang detection needs ``heartbeat_dir`` on
  a filesystem shared with every host, and teardown reaches only the
  local ssh clients — see `supervise_hosts` for the orphan caveats and
  the coordinator-port rotation that keeps relaunches viable anyway.

Every restart decision is appended to a JSONL log whose records are
metric-shaped (``{"name": "restarts", "value": <total so far>, ...}``)
precisely so the existing CI gate reads it unchanged:

    hvt-launch gate --metrics restarts.jsonl --check restarts=1..1 \
        --aggregate count

Deterministic chaos for testing lives in `horovod_tpu.testing.faults`
(``HVT_FAULT=rank:epoch:kind``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import signal
import tempfile
import time

from horovod_tpu.analysis import registry
from horovod_tpu.launch import launcher
from horovod_tpu.launch import policy as policy_lib
from horovod_tpu.obs import core as obs_core
from horovod_tpu.obs import fleet as obs_fleet
from horovod_tpu.obs import prom as obs_prom
from horovod_tpu.runtime import ENV_HEARTBEAT_DIR

# Any file named like a checkpoint artifact counts as progress: single-file
# epochs (checkpoint-3.msgpack), sharded dirs (checkpoint-3.sharded/...),
# EMA shadows. Matched against the basename, extension-agnostic like
# checkpoint.latest_checkpoint.
_CHECKPOINT_RE = re.compile(r"checkpoint-(\d+)")


@dataclasses.dataclass
class RestartPolicy:
    """Knobs for `supervise` (CLI: --max-restarts/--backoff/
    --heartbeat-timeout; YAML: the job's ``restart:`` block).

    ``max_restarts`` bounds *consecutive no-progress* restarts, not total
    restarts — see the module docstring. ``heartbeat_timeout=None``
    disables hang detection; size it above the longest legitimate
    beat-free span (worst-case compile + step on the streamed fit path,
    worst-case EPOCH on the device-cached path where batch callbacks fire
    once per epoch, plus any post-fit export/eval work).
    ``startup_timeout`` separately bounds time-to-FIRST-beat, so a fleet
    that wedges before training (stuck ``jax.distributed.initialize``, an
    orphan holding the coordinator port) is also caught; default
    ``None`` = 10 × ``heartbeat_timeout`` (imports + distributed init +
    build trace all precede the first beat)."""

    max_restarts: int = 3
    backoff: float = 1.0
    backoff_factor: float = 2.0
    backoff_max: float = 60.0
    heartbeat_timeout: float | None = None
    startup_timeout: float | None = None
    grace_seconds: float = 30.0
    # Consecutive no-progress OOM-KILL restarts (`classify` kind
    # "oom-kill": exit 137 / SIGKILL, the host OOM killer's signature)
    # before giving up — None shares `max_restarts`. An OOM loop is
    # near-deterministic (the same footprint re-exceeds the same host
    # limit every relaunch), so a tighter budget stops it burning the
    # full restart budget on faults a relaunch can never fix.
    oom_kill_budget: int | None = None

    @classmethod
    def from_mapping(cls, mapping) -> "RestartPolicy":
        """Build a policy from a partial dict — the single constructor both
        front-ends (CLI flags, the YAML ``restart:`` block) funnel through,
        so a new knob can't land in one and silently no-op in the other.
        Unknown keys are rejected loudly. ``None`` values mean 'keep the
        default' (unset CLI flags)."""
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(mapping) - fields
        if unknown:
            raise ValueError(
                f"unknown restart policy keys {sorted(unknown)}; "
                f"valid: {sorted(fields)}"
            )
        policy = cls()
        for key, value in mapping.items():
            if value is None:
                continue
            setattr(
                policy, key,
                int(value) if key in ("max_restarts", "oom_kill_budget")
                else float(value),
            )
        return policy


def classify(exit_code: int, hang: bool = False) -> str:
    """Map a fleet outcome to a restart-log kind.

    143 (= 128 + SIGTERM, the `PreemptionCheckpointCallback` convention) and
    a raw SIGTERM death both read as the scheduler reclaiming the slice.
    137 (= 128 + SIGKILL) and a raw SIGKILL death read as the host OOM
    killer — the one external kill a scheduler never sends politely — and
    get their own kind (and, via ``RestartPolicy.oom_kill_budget``, their
    own restart budget) rather than lumping in with generic crashes."""
    if hang:
        return "hang"
    if exit_code in (143, -signal.SIGTERM):
        return "preemption"
    if exit_code in (137, -signal.SIGKILL):
        return "oom-kill"
    return "crash"


def shell_code(exit_code: int) -> int:
    """Popen returncodes are negative for signal deaths; shells speak
    128+sig. Positive codes pass through untouched (the acceptance contract:
    a deterministic ``exit 7`` loop exits the supervisor with 7)."""
    if exit_code > 0:
        return exit_code
    if exit_code < 0:
        return 128 - exit_code
    return 0


def newest_checkpoint_marker(model_dir: str | None):
    """Identity of the newest checkpoint-like file under ``model_dir``
    (recursive — single-file checkpoints and sharded-dir shard files alike),
    as a comparable ``(path, mtime_ns, size)`` tuple; None when there are
    none. Two calls comparing unequal == progress was made in between."""
    if not model_dir or not os.path.isdir(model_dir):
        return None
    newest = None
    for root, _, files in os.walk(model_dir):
        for name in files:
            if not _CHECKPOINT_RE.search(name) and not _CHECKPOINT_RE.search(
                os.path.basename(root)
            ):
                continue
            full = os.path.join(root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue  # racing a writer's atomic rename
            key = (st.st_mtime_ns, full)
            if newest is None or key > newest[0]:
                newest = (key, (full, st.st_mtime_ns, st.st_size))
    return newest[1] if newest else None


def _reset_heartbeats(heartbeat_dir: str) -> None:
    """Clear stale beats before a (re)launch — a leftover rank file from the
    previous attempt would read as instantly-stale and kill the new fleet
    before it trains a step."""
    os.makedirs(heartbeat_dir, exist_ok=True)
    for name in os.listdir(heartbeat_dir):
        if name.startswith("rank-"):
            try:
                os.remove(os.path.join(heartbeat_dir, name))
            except OSError:
                pass


def newest_beat(heartbeat_dir: str) -> float | None:
    """Wall-clock mtime of the freshest ``rank-*`` beat, None if none."""
    try:
        names = os.listdir(heartbeat_dir)
    except OSError:
        return None
    newest = None
    for name in names:
        if not name.startswith("rank-"):
            continue
        try:
            mt = os.stat(os.path.join(heartbeat_dir, name)).st_mtime
        except OSError:
            continue
        newest = mt if newest is None else max(newest, mt)
    return newest


def heartbeats_stale(heartbeat_dir: str, timeout: float,
                     now=None) -> bool:
    """True when heartbeats exist but the newest is older than ``timeout``
    of wall-clock ``now``. Same-clock convenience check (single-host
    tooling, tests); the supervisor's own abort hook uses skew-immune
    change-detection instead (`_throttled_staleness_check`). No files yet
    = not stale here — time-to-FIRST-beat is bounded separately by the
    abort hook's startup timeout."""
    newest = newest_beat(heartbeat_dir)
    if newest is None:
        return False
    return (now if now is not None else time.time()) - newest > timeout


def _throttled_staleness_check(heartbeat_dir: str, timeout: float,
                               startup_timeout: float):
    """An abort hook for `Fleet.wait` that stats the heartbeat dir at a
    cadence proportional to the timeout (bounded to [0.5s, 5s]) rather than
    at the fleet's 10 Hz process-poll rate — a question with timeout-scale
    resolution must not generate constant metadata traffic on the
    NFS/GCS-fuse mounts multi-host hang detection runs over.

    Two hang shapes are bounded: beats that STOPPED and beats that never
    STARTED (no rank file within ``startup_timeout`` of the launch — a
    fleet wedged in distributed init produces no exit code and no beats,
    and would otherwise be supervised forever).

    Staleness is judged by whether the newest beat's mtime has CHANGED
    within ``timeout`` of the supervisor's own monotonic clock — never by
    comparing rank-written mtimes against the supervisor's wall clock.
    On multi-host (NFS/GCS-fuse) deployments the rank hosts' clocks can
    skew past the timeout in either direction; wall-clock comparison
    would then kill healthy fleets (or mask real hangs), while
    change-detection only requires the mtimes to be *distinct* across
    beats."""
    interval = max(0.5, min(5.0, timeout / 10.0))
    t0 = time.monotonic()
    state = {"next": 0.0, "stale": False, "beat": None, "changed_at": t0}

    def abort() -> bool:
        now = time.monotonic()
        if now >= state["next"]:
            state["next"] = now + interval
            beat = newest_beat(heartbeat_dir)
            if beat is None:
                state["stale"] = now - t0 > startup_timeout
            else:
                if beat != state["beat"]:
                    state["beat"] = beat
                    state["changed_at"] = now
                state["stale"] = now - state["changed_at"] > timeout
        return state["stale"]

    return abort


class RestartLog:
    """Append-only JSONL restart journal. Records double as CI-gate metrics:
    each carries ``name``/``value`` (value = total restarts so far), so
    ``ci_gate.check_metrics(log, 'restarts', (1, 1), how='count')`` asserts
    restart counts with no new machinery.

    **Rotation** (long-lived elastic fleets journal every beat-adjacent
    membership event for weeks): when the file exceeds ``max_lines`` or
    ``max_bytes`` it is renamed to ``<path>.1`` — replacing the previous
    predecessor, so at most two windows exist on disk — and appending
    continues in a fresh file. Readers (`fleet_status`,
    `ci_gate.read_metric`) read the ``.1`` predecessor first, so counts
    and settle state survive the rotation boundary. Defaults come from
    ``HVT_RESTART_LOG_MAX_LINES`` / ``HVT_RESTART_LOG_MAX_MB`` (100000
    lines / 64 MB; 0 disables that bound)."""

    def __init__(self, path: str | None, max_lines: int | None = None,
                 max_bytes: int | None = None,
                 extra: dict | None = None):
        self.path = path
        # Fields stamped onto EVERY record (e.g. ``job=`` for the per-job
        # journals of a fleet launch, so a merged/aggregated view stays
        # attributable). Per-write fields win on collision.
        self.extra = dict(extra or {})
        if max_lines is None:
            max_lines = registry.get_int("HVT_RESTART_LOG_MAX_LINES")
        if max_bytes is None:
            max_bytes = int(
                registry.get_float("HVT_RESTART_LOG_MAX_MB") * 1024 * 1024
            )
        self.max_lines = max_lines or None
        self.max_bytes = max_bytes or None
        self._lines: int | None = None  # counted lazily on first write

    def touch(self) -> None:
        """Ensure the journal exists even for a zero-restart run: the CI
        gate fails on a MISSING file for every aggregate, so 'ran
        supervised, zero restarts' (`restarts=0..0 --aggregate count`)
        must be distinguishable from 'never ran'."""
        if not self.path:
            return
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a"):
            pass

    def _maybe_rotate(self) -> None:
        if self.max_lines is None and self.max_bytes is None:
            return
        over_lines = (
            self.max_lines is not None
            and self._lines is not None
            and self._lines >= self.max_lines
        )
        over_bytes = False
        if not over_lines and self.max_bytes is not None:
            try:
                over_bytes = os.path.getsize(self.path) >= self.max_bytes
            except OSError:
                pass
        if over_lines or over_bytes:
            try:
                os.replace(self.path, self.path + ".1")
            except OSError:
                return  # rotation is best-effort; keep appending
            self._lines = 0

    def write(self, name: str, value: float, **fields) -> None:
        if not self.path:
            return
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        if self._lines is None:
            try:
                with open(self.path) as f:
                    self._lines = sum(1 for _ in f)
            except OSError:
                self._lines = 0
        record = {"name": name, "value": value, "wall_time": time.time(),
                  **self.extra, **fields}
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
        self._lines += 1
        self._maybe_rotate()


def resolve_flight_dir(env) -> str | None:
    """Where the fleet's flight records land, if recording is on: the
    job env's ``HVT_FLIGHT_RECORD`` overlay, falling back to the
    launcher's own environment (the registry accessor). None = recorder
    off — the hang path then collects nothing."""
    return (env or {}).get("HVT_FLIGHT_RECORD") or registry.get_str(
        "HVT_FLIGHT_RECORD"
    )


def collect_flight_records(flight_dir: str | None, log: "RestartLog",
                           attempt: int, **fields) -> list:
    """The hang-classification hook: quarantine-copy every member's
    flight record (`flight.collect` — the relaunch truncates the live
    files, so the copies are the post-mortem evidence `hvt-sched replay
    <dest>` examines) and journal ONE ``flight_dump`` event carrying the
    destination — the record `supervisor_metrics` counts into
    ``hvt_flight_dumps_total``. Best-effort: evidence collection must
    never change a restart decision."""
    if not flight_dir:
        return []
    from horovod_tpu import flight as flight_lib

    dest = os.path.join(flight_dir, f"hang-{attempt}")
    try:
        files = flight_lib.collect(flight_dir, dest)
    except OSError:
        return []
    if files:
        log.write(
            "flight_dump", float(len(files)), attempt=attempt, dir=dest,
            files=[os.path.basename(f) for f in files], **fields,
        )
    return files


def supervise(
    start,
    policy: RestartPolicy | None = None,
    *,
    model_dir: str | None = None,
    heartbeat_dir: str | None = None,
    log_path: str | None = None,
    status_port: int | None = None,
    flight_dir: str | None = None,
    fleet_ports=None,
    fleet_env: dict | None = None,
    policy_config: "policy_lib.PolicyConfig | None" = None,
    sleep=time.sleep,
    verbose: bool = True,
) -> int:
    """Launch-monitor-relaunch loop. ``start`` is a zero-arg callable
    returning a running `launcher.Fleet` (close over `start_local` /
    `start_hosts` with the env already carrying ``HVT_HEARTBEAT_DIR`` —
    `supervise_local` does this wiring). Returns 0 on fleet success, else
    the final failure's shell exit code once the no-progress budget is
    exhausted. ``status_port`` serves `start_status_server` from this
    supervisor for the run's duration (fleet status + journal over HTTP,
    no serving bundle required); ``fleet_ports`` additionally lights up
    its ``GET /fleet`` rollup (`member_metrics_ports`).

    ``policy_config`` (mode != off) runs the policy engine
    (`launch.policy`) alongside: straggler OBSERVATION over the fleet
    cache (whole-fleet mode has no per-member actuator, so the evict
    rung journals ``unsupported`` — or ``dry-run``) and hang auto-triage
    (the `hvt-sched replay` verdict journaled before every
    hang-relaunch decision)."""
    policy = policy or RestartPolicy()
    log = RestartLog(log_path)
    log.touch()
    # Shared with the status server's /metrics scrape (and the final
    # dump): the loop keeps "used" current so
    # hvt_restart_budget_remaining is live, not post-hoc.
    budget = {"max": policy.max_restarts, "used": 0}
    status_server = (
        start_status_server(status_port, log_path, budget=budget,
                            model_dir=model_dir, fleet_ports=fleet_ports,
                            env=fleet_env)
        if status_port is not None else None
    )
    marker = newest_checkpoint_marker(model_dir)
    total_restarts = 0  # lifetime count — what the log/gate report
    backoff = policy.backoff
    attempt = 0
    engine = (
        policy_lib.PolicyEngine(policy_config, log.write)
        if policy_config is not None and policy_config.active else None
    )

    try:
        return _supervise_loop(
            start, policy, log, model_dir, heartbeat_dir, sleep, verbose,
            marker, budget, total_restarts, backoff, attempt, flight_dir,
            engine=engine,
            members_fn=(
                (lambda: status_server.fleet_cache["members"])
                if status_server is not None else None
            ),
        )
    finally:
        dump_metrics(
            log_path, None, budget, model_dir,
            members=(
                status_server.fleet_cache["members"]
                if status_server is not None else None
            ),
        )
        if status_server is not None:
            status_server.shutdown()


def _supervise_loop(start, policy, log, model_dir, heartbeat_dir, sleep,
                    verbose, marker, budget, total_restarts, backoff,
                    attempt, flight_dir=None, engine=None,
                    members_fn=None) -> int:
    restarts_used = budget["used"]  # consecutive no-progress restarts
    oom_used = 0  # consecutive no-progress oom-kill restarts
    while True:
        attempt += 1
        abort = None
        if heartbeat_dir and policy.heartbeat_timeout is not None:
            _reset_heartbeats(heartbeat_dir)
            abort = _throttled_staleness_check(
                heartbeat_dir, policy.heartbeat_timeout,
                policy.startup_timeout
                if policy.startup_timeout is not None
                else 10.0 * policy.heartbeat_timeout,
            )
        if engine is not None and members_fn is not None:
            # Ride the fleet's abort-poll cadence for the engine's
            # observation tick (it throttles internally) — whole-fleet
            # mode gets the observe/warn/dry-run rungs without a thread.
            inner_abort = abort

            def abort(inner=inner_abort):
                engine.poll(members_fn())
                return inner() if inner is not None else False
        fleet = start()
        code = fleet.wait(policy.grace_seconds, abort=abort)
        if code == 0 and not fleet.aborted:
            if verbose and total_restarts:
                print(f"supervisor: fleet succeeded after "
                      f"{total_restarts} restart(s)")
            return 0

        kind = classify(code, hang=fleet.aborted)
        if kind == "hang":
            # The fleet's SIGTERM teardown already ran each member's
            # flight-dump handler (and write-through covers ranks
            # wedged in native collectives): quarantine the evidence
            # before the relaunch truncates the live files.
            files = collect_flight_records(
                flight_dir, log, attempt, kind=kind
            )
            if engine is not None and files:
                # Auto-triage the quarantined evidence: the replay
                # verdict lands in the journal BEFORE the restart
                # decision below.
                engine.on_hang(os.path.dirname(files[0]))
        new_marker = newest_checkpoint_marker(model_dir)
        progressed = model_dir is not None and new_marker != marker
        marker = new_marker
        if progressed:
            # Fresh checkpoint since launch: the fault is not a
            # deterministic loop — full budget and backoff again.
            restarts_used = 0
            oom_used = 0
            backoff = policy.backoff
        budget["used"] = restarts_used
        oom_exhausted = (
            kind == "oom-kill"
            and policy.oom_kill_budget is not None
            and oom_used >= policy.oom_kill_budget
        )
        if restarts_used >= policy.max_restarts or oom_exhausted:
            log.write(
                "supervisor_gave_up", 1.0, attempt=attempt, kind=kind,
                exit_code=code, restarts=total_restarts,
                **({"budget": "oom-kill"} if oom_exhausted else {}),
            )
            if verbose:
                spent = (
                    f"oom-kill budget ({policy.oom_kill_budget}) spent"
                    if oom_exhausted else
                    f"no progress in the last {restarts_used} restart(s)"
                )
                print(
                    f"supervisor: giving up after {total_restarts} "
                    f"restart(s) — attempt {attempt} {kind} "
                    f"(exit {code}), {spent}"
                )
            # `or 1`: a hang-killed rank that trapped SIGTERM and exited 0
            # must still surface as failure.
            return shell_code(code) or 1
        restarts_used += 1
        if kind == "oom-kill":
            oom_used += 1
        budget["used"] = restarts_used
        total_restarts += 1
        log.write(
            "restarts", float(total_restarts), attempt=attempt, kind=kind,
            exit_code=code, progressed=progressed, backoff_s=backoff,
        )
        if verbose:
            print(
                f"supervisor: attempt {attempt} {kind} (exit {code}, "
                f"{'progress' if progressed else 'no progress'}) — "
                f"restart {total_restarts} in {backoff:.1f}s"
            )
        sleep(backoff)
        backoff = min(backoff * policy.backoff_factor, policy.backoff_max)


def default_heartbeat_dir(model_dir: str | None) -> str:
    """``<model_dir>/hb`` when the job has a model dir (shared-filesystem
    deployments get multi-host hang detection for free), else a tmpdir."""
    if model_dir:
        return os.path.join(model_dir, "hb")
    return tempfile.mkdtemp(prefix="hvt-hb-")


def default_model_dir(env) -> str | None:
    """The progress-detection root: job env's PS_MODEL_PATH, falling back
    to the launcher's own environment."""
    return (env or {}).get("PS_MODEL_PATH") or os.environ.get("PS_MODEL_PATH")


def default_log_path(env) -> str | None:
    """Where the restart journal lands by default: beside the checkpoints.
    The SINGLE resolver — `run_job`'s stale-journal reset and the
    supervisor's writer must agree on the path or the reset silently
    guards the wrong file."""
    model_dir = default_model_dir(env)
    return os.path.join(model_dir, "restarts.jsonl") if model_dir else None


def _resolve_dirs(env, model_dir, heartbeat_dir, log_path, policy):
    """Shared CLI/YAML wiring: model dir from PS_MODEL_PATH, heartbeat dir
    exported to children, restart log defaulted beside the checkpoints."""
    env = dict(env or {})
    model_dir = model_dir or default_model_dir(env)
    if policy.heartbeat_timeout is not None:
        heartbeat_dir = heartbeat_dir or default_heartbeat_dir(model_dir)
        env[ENV_HEARTBEAT_DIR] = heartbeat_dir
    else:
        heartbeat_dir = None
    if log_path is None:
        log_path = default_log_path(env)
    return env, model_dir, heartbeat_dir, log_path


def supervise_local(
    nprocs: int,
    argv: list[str],
    env: dict[str, str] | None = None,
    policy: RestartPolicy | None = None,
    *,
    model_dir: str | None = None,
    heartbeat_dir: str | None = None,
    log_path: str | None = None,
    status_port: int | None = None,
    policy_config: "policy_lib.PolicyConfig | None" = None,
    tag_output: bool = True,
    sleep=time.sleep,
) -> int:
    """`launcher.start_local` under supervision (the ``hvt-launch run
    --max-restarts`` path)."""
    policy = policy or RestartPolicy()
    env, model_dir, heartbeat_dir, log_path = _resolve_dirs(
        env, model_dir, heartbeat_dir, log_path, policy
    )
    if policy_config is None:
        policy_config = policy_lib.PolicyConfig.from_env(env)
    return supervise(
        lambda: launcher.start_local(
            nprocs, argv, env=env, tag_output=tag_output
        ),
        policy,
        model_dir=model_dir,
        heartbeat_dir=heartbeat_dir,
        log_path=log_path,
        status_port=status_port,
        flight_dir=resolve_flight_dir(env),
        fleet_ports=member_metrics_ports(env, nprocs),
        fleet_env=env,
        policy_config=policy_config,
        sleep=sleep,
    )


@dataclasses.dataclass
class ElasticPolicy:
    """Knobs for `supervise_elastic` (CLI: ``--elastic --min-ranks/
    --max-ranks``; YAML: the job's ``elastic:`` block).

    The fleet shrinks to survivors on a clean departure (down to
    ``min_ranks``) and grows back as replacements join (up to
    ``max_ranks``). ``rendezvous_timeout`` bounds how long a rendezvous
    round waits for a member that will never arrive.

    ``commit_every`` (epochs) and ``commit_every_steps`` (optimizer steps
    within an epoch; 0 = epoch cadence only) set the members' elastic
    commit cadence: they travel to every member as ``HVT_COMMIT_EVERY`` /
    ``HVT_COMMIT_EVERY_STEPS``, which `ElasticStateCallback` reads as its
    defaults — so a job spec tunes the cadence without entry-script
    changes. Sub-epoch commits are always aligned to gradient-accumulation
    boundaries (the callback commits per optimizer step; see
    `ElasticStateCallback.commit_every_steps`).

    ``rescale_every_steps`` (optimizer steps; 0 = epoch boundaries only)
    sets the members' SUB-EPOCH membership-agreement cadence
    (``HVT_RESCALE_EVERY_STEPS`` → `ElasticStateCallback.
    rescale_every_steps`): joiners are admitted and clean leavers
    released within N optimizer steps instead of an epoch, with
    survivors resuming at the committed step (``initial_step``). Pair
    with ``commit_every_steps`` so the boundary always has a fresh
    sub-epoch commit to resume from."""

    min_ranks: int = 1
    max_ranks: int | None = None
    rendezvous_timeout: float = 60.0
    commit_every: int = 1
    commit_every_steps: int = 0
    rescale_every_steps: int = 0

    @classmethod
    def from_mapping(cls, mapping) -> "ElasticPolicy":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(mapping) - fields
        if unknown:
            raise ValueError(
                f"unknown elastic policy keys {sorted(unknown)}; "
                f"valid: {sorted(fields)}"
            )
        policy = cls()
        for key, value in mapping.items():
            if value is None:
                continue
            setattr(
                policy, key,
                float(value) if key == "rendezvous_timeout" else int(value),
            )
        return policy

    def commit_env(self) -> dict:
        """The member-env overlay carrying the commit/rescale cadences
        (only the non-default knobs, so an explicit ElasticStateCallback
        argument in user code still wins when the spec says nothing)."""
        env = {}
        if self.commit_every != 1:
            env["HVT_COMMIT_EVERY"] = str(self.commit_every)
        if self.commit_every_steps:
            env["HVT_COMMIT_EVERY_STEPS"] = str(self.commit_every_steps)
        if self.rescale_every_steps:
            env["HVT_RESCALE_EVERY_STEPS"] = str(self.rescale_every_steps)
        return env


def _spawn_member_local(argv, env, member_id, slot, tag_output=True):
    """One elastic member as a local subprocess (the per-rank unit the
    elastic supervisor restarts — contrast `launcher.start_local`, which
    only knows whole fleets)."""
    import subprocess

    from horovod_tpu.runtime import ENV_ELASTIC_MEMBER, ENV_LOCAL_RANK

    child_env = dict(os.environ)
    child_env.update(env or {})
    child_env[ENV_ELASTIC_MEMBER] = member_id
    child_env[ENV_LOCAL_RANK] = str(slot)
    proc = subprocess.Popen(
        argv,
        env=child_env,
        stdout=subprocess.PIPE if tag_output else None,
        stderr=subprocess.STDOUT if tag_output else None,
        text=tag_output,
    )
    if tag_output:
        launcher._stream(proc, member_id)
    return proc


def supervise_elastic(
    nprocs: int,
    argv: list[str],
    env: dict[str, str] | None = None,
    policy: RestartPolicy | None = None,
    elastic: ElasticPolicy | None = None,
    *,
    model_dir: str | None = None,
    log_path: str | None = None,
    status_port: int | None = None,
    coordinator_host: str = "127.0.0.1",
    sync_port_base: int | None = None,
    spawn=None,
    spares: int = 0,
    policy_config: "policy_lib.PolicyConfig | None" = None,
    tag_output: bool = True,
    sleep=time.sleep,
    verbose: bool = True,
    poll_interval: float = 0.1,
    controller=None,
    journal_tags: dict | None = None,
) -> int:
    """Elastic launch-and-supervise loop: continue-through-failure.

    Where `supervise` can only kill-and-relaunch the WHOLE fleet, this
    mode owns a rendezvous `Coordinator` and supervises members
    individually:

    * a member that LEAVES cleanly (scheduler SIGTERM honored by the
      elastic callback, the ``leave`` fault kind, exit 143) shrinks the
      fleet in place — survivors re-rendezvous at the next commit
      boundary and keep training from committed state, their processes
      untouched;
    * a replacement is spawned (budget and backoff permitting) and the
      fleet GROWS back when it joins;
    * a member that dies hard (crash/SIGKILL) is marked dead — the jax
      coordination service tears the peers of that generation down with
      it (a collective with a dead rank cannot be aborted), so hard
      faults escalate to per-rank restarts: every dead member is
      respawned, rejoins, and restores from the last checkpoint (the
      `ElasticState` fallback path);
    * a member whose TCP beats go stale (`Coordinator.stale_members` —
      no shared filesystem needed, the pod-mode answer) is killed and
      treated as a hang.

    The restart budget/backoff semantics are `RestartPolicy`'s,
    progress-aware over ``model_dir``: replacements stop being spawned
    once the no-progress budget is spent — the fleet then simply stays
    shrunken while it still clears ``min_ranks``, and only fails once it
    cannot. Every membership/rescale event lands in the JSONL journal,
    generation-tagged, CI-gateable (``shrink=1..N --aggregate count``)
    and servable (`fleet_status`, the /healthz ``fleet`` section).

    ``spawn(member_id, slot, env)``: optional member factory (the ssh
    path's hook). It receives the RESOLVED env overlay — including
    ``HVT_ELASTIC_COORDINATOR``, which only exists once the coordinator
    here has started — and must apply it to the child; a closure over the
    caller's own env dict would silently miss the coordinator address.

    ``spares`` (or the policy config's ``spares``): K extra members
    spawned beyond ``nprocs`` as WARM STANDBYS. The world still caps at
    ``max_ranks`` (default ``nprocs``), so whichever K members lose the
    initial rendezvous race park at the coordinator's door (the
    ``HVT_ELASTIC_SPARE`` knock-and-retry in `ElasticClient.sync` —
    processes up, imports warm, re-syncing every half second) and join
    the generation an eviction or death frees a slot in: world size is
    PRESERVED instead of shrunk, without spending a restart.

    ``controller``: the fleet scheduler's duck-typed hook
    (`launch.fleetd.JobController`) — how `hvt-launch fleet` drives one
    job's supervisor from outside without reimplementing it. The
    contract, every method optional-free and called from this loop only:

    * ``take_preempts() -> list[member_id]`` — members the scheduler
      wants preempted NOW. Each gets the clean-leave treatment the
      policy engine's eviction gets (SIGTERM → the elastic callback's
      flag → leave at the next commit boundary, grace-escalated): the
      exit spends NO restart budget and queues NO respawn — a
      ``preempt`` record is journaled instead. Preemption is capacity
      reclamation, not failure.
    * ``capacity() -> int | None`` — a dynamic world-size cap below
      ``max_ranks`` (the job's current host allocation). Respawns and
      grows are dropped while live+joining members would exceed it.
    * ``take_grows() -> int`` — fresh members to launch immediately
      (the scheduler granted hosts back); launched into the smallest
      free slots, budget-free (a grow restores capacity, it does not
      remedy a failure).
    * ``classify_exit(member_id, code, kind) -> (kind, charge) | None``
      — reclassify a death (the ``host_lost`` path: every rank on a
      dead host is one event; the first co-resident death returns
      ``("host_lost", True)`` — charged once — the rest
      ``("host_lost", False)``, journaled as ``host_lost`` records and
      respawned capacity-permitting without touching the budget).
    * ``on_exit(member_id, kind)`` — post-reap notification with the
      final classification (host bookkeeping).

    With a controller attached an EMPTY fleet is a wait state, not
    extinction: a job whose only host just died idles (coordinator up,
    zero members) until the scheduler regrows it or tears it down.

    ``journal_tags``: fields stamped on every journal record (the fleet
    launch tags ``job=<name>`` so multi-job aggregation stays
    attributable — `ci_gate` scopes counts by it).

    ``policy_config`` (default: resolved from the env's ``HVT_POLICY*``
    knobs) runs the policy engine (`launch.policy`) inside this loop —
    this mode owns the full actuator: a confirmed straggler's member is
    SIGTERMed so the elastic callback's leave→shrink path re-slices its
    work (no restart-budget spend, no respawn; a parked spare grows the
    world back), and every hang collection is auto-triaged with the
    `hvt-sched replay` verdict journaled before the respawn decision."""
    from horovod_tpu.elastic.coordinator import Coordinator
    from horovod_tpu.runtime import ENV_ELASTIC_COORDINATOR

    policy = policy or RestartPolicy()
    elastic = elastic or ElasticPolicy()
    max_ranks = elastic.max_ranks or nprocs
    env, model_dir, _, log_path = _resolve_dirs(
        dict(env or {}), model_dir, None,
        log_path, RestartPolicy(heartbeat_timeout=None),
    )
    if policy_config is None:
        policy_config = policy_lib.PolicyConfig.from_env(env)
    spares = spares if spares > 0 else policy_config.spares
    if spares > 0:
        # Every member gets the park-when-full retry: any member that
        # loses a rendezvous race to a full world (an initial spare, OR
        # a respawn whose slot a promoted spare already took) becomes
        # the next warm standby instead of dying on ElasticError.
        env["HVT_ELASTIC_SPARE"] = "1"
    flight_dir = resolve_flight_dir(env)
    log = RestartLog(log_path, extra=journal_tags)
    log.touch()
    coord = Coordinator(
        host=coordinator_host,
        min_ranks=elastic.min_ranks,
        max_ranks=max_ranks,
        expected=min(nprocs, max_ranks),
        rendezvous_timeout=elastic.rendezvous_timeout,
        # A member whose beats are fresh is mid-epoch, not dead: exempt it
        # from rendezvous-timeout expiry so a joiner waiting out a long
        # epoch cannot get actively-training survivors declared dead.
        heartbeat_window=(
            policy.heartbeat_timeout
            if policy.heartbeat_timeout is not None
            else elastic.rendezvous_timeout
        ),
        sync_port_base=sync_port_base,
        journal=log.write,
    ).start()
    env[ENV_ELASTIC_COORDINATOR] = coord.address
    env.update(elastic.commit_env())
    budget = {"max": policy.max_restarts, "used": 0}
    status_server = (
        start_status_server(status_port, log_path, coord=coord,
                            budget=budget, model_dir=model_dir,
                            # Spares ride slots PAST the world (their
                            # exporters bind base + slot too), so the
                            # scrape map must cover every spawnable slot
                            # or a promoted spare's rank — and any rank
                            # whose slot shifted past a parked spare —
                            # goes unobserved.
                            fleet_ports=member_metrics_ports(
                                env, min(nprocs, max_ranks) + spares
                            ), env=env)
        if status_port is not None else None
    )
    if spawn is None:
        spawn = lambda member_id, slot, env: _spawn_member_local(  # noqa: E731
            argv, env, member_id, slot, tag_output=tag_output
        )

    members: dict[str, dict] = {}   # live procs: id -> {proc, slot, spawned}
    seq = 0

    def launch(slot: int):
        nonlocal seq
        member_id = f"m{seq}"
        seq += 1
        members[member_id] = {
            "proc": spawn(member_id, slot, dict(env)), "slot": slot,
            "spawned": time.monotonic(),
        }
        return member_id

    # --- policy engine (launch.policy) ----------------------------------
    # Members the engine deliberately evicted: their exits must spend NO
    # restart budget and queue NO respawn — the eviction IS the remedy
    # (a parked spare grows the world back, or the fleet deliberately
    # stays smaller).
    policy_evicted: set = set()
    # Members the fleet CONTROLLER deliberately preempted (capacity
    # reclamation for a higher-priority job): same zero-budget/no-respawn
    # semantics as a policy eviction, but journaled as `preempt` — the
    # scheduler regrows the job later via take_grows().
    preempted: set = set()

    def notify_exit(member_id: str, kind: str) -> None:
        if controller is not None:
            controller.on_exit(member_id, kind)

    def parked_spares() -> int:
        """Live member processes the coordinator has never admitted —
        with ``spares`` those are the warm standbys knocking at a full
        world. (A respawn mid-join counts too, briefly: equally
        promotable, so the promote accounting stays honest.)"""
        return sum(
            1 for mid, rec in members.items()
            if rec["proc"].poll() is None
            and coord.member_status(mid)[0] == "unknown"
        )

    def evict_member(world_rank: int) -> str:
        """The engine's actuator: SIGTERM the live member holding
        ``world_rank``. The elastic callback's flag-only handler turns
        that into a clean leave at the next commit/rescale boundary —
        the coordinator's existing shrink path re-slices the work."""
        for mid, m in coord.snapshot()["members"].items():
            if m.get("status") != "live" or m.get("rank") != world_rank:
                continue
            rec = members.get(mid)
            if rec is None or rec["proc"].poll() is not None:
                return "no-process"
            policy_evicted.add(mid)
            # Arm the existing grace escalation: an evictee too wedged
            # to honor its own leave still gets reaped.
            rec["terminated_at"] = time.monotonic()
            rec["proc"].terminate()
            if verbose:
                print(
                    f"supervisor: policy evicting {mid} (rank "
                    f"{world_rank}) — confirmed straggler"
                )
            return "sigterm"
        return "no-member"

    engine = (
        policy_lib.PolicyEngine(
            policy_config, log.write, evict=evict_member,
            spare_count=parked_spares,
        )
        if policy_config.active else None
    )

    marker = newest_checkpoint_marker(model_dir)
    # STEP-granular progress: members report their committed
    # progress_marker(epoch, step) over beats/syncs, so an elastic fleet
    # advancing optimizer steps between failures counts as progressing
    # even when no new checkpoint FILE landed (sub-epoch commits live on
    # the coordinator, not on disk). The budget then only burns on truly
    # stuck loops — same fault, same committed step, every time.
    # -1 is the exact "nothing committed" baseline: members report -1
    # until their first commit, and every commit path records >= 1 step
    # or epoch of real training, so the -1 -> first-marker transition is
    # genuine progress, never a free budget reset.
    best_progress = -1

    def committed_progress() -> int:
        return max(
            (m["progress"] for m in coord.snapshot()["members"].values()),
            default=-1,
        )

    restarts_used = 0
    oom_used = 0
    total_restarts = 0
    backoff = policy.backoff
    hang_killed: set[str] = set()
    flight_collected: set[int] = set()  # spawn-seq marks, one per hang
    respawn_queue: list[tuple[float, int]] = []  # (due, slot)
    job_done = False
    done_since: float | None = None
    last_failure = 1
    startup_timeout = (
        policy.startup_timeout
        if policy.startup_timeout is not None
        else (10.0 * policy.heartbeat_timeout
              if policy.heartbeat_timeout is not None else None)
    )

    def teardown(code: int) -> int:
        for rec in members.values():
            if rec["proc"].poll() is None:
                rec["proc"].terminate()
        deadline = time.monotonic() + policy.grace_seconds
        for rec in members.values():
            p = rec["proc"]
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                p.kill()
                p.wait()
        # The final gateable scrape, while the coordinator still answers
        # (launch/job.py `metrics_checks:` reads this post-run).
        dump_metrics(
            log_path, coord, budget, model_dir,
            members=(
                status_server.fleet_cache["members"]
                if status_server is not None else None
            ),
        )
        coord.stop()
        if status_server is not None:
            status_server.shutdown()
        return code

    try:
        # Spares ride extra slots past the world: rendezvous admits the
        # first max_ranks joiners, the rest park (HVT_ELASTIC_SPARE).
        for slot in range(min(nprocs, max_ranks) + spares):
            launch(slot)
        while True:
            now = time.monotonic()
            # --- reap exits -------------------------------------------------
            for member_id in list(members):
                rec = members[member_id]
                code = rec["proc"].poll()
                if code is None:
                    continue
                del members[member_id]
                status, reason = coord.member_status(member_id)
                if status == "left" and reason == "done":
                    job_done = True
                    notify_exit(member_id, "done")
                    continue
                if member_id in policy_evicted:
                    # Deliberate policy eviction: the engine already
                    # journaled the decision; the coordinator journaled
                    # the leave/shrink. No budget spend, no respawn —
                    # a parked spare (if any) takes the freed slot.
                    policy_evicted.discard(member_id)
                    if status != "left":
                        # The evictee was too wedged for a clean leave
                        # and the grace escalation killed it.
                        coord.mark_dead(member_id, reason="evicted")
                    notify_exit(member_id, "evicted")
                    continue
                if member_id in preempted:
                    # Scheduler-initiated preemption completed: the host
                    # goes back to the pool (on_exit), the budget stays
                    # untouched, and NO respawn queues — take_grows()
                    # will regrow the job when hosts free up.
                    preempted.discard(member_id)
                    if status != "left":
                        coord.mark_dead(member_id, reason="preempted")
                    notify_exit(member_id, "preempt")
                    continue
                if code == 0:
                    # Finished without the leave handshake (a non-elastic
                    # script, or the coordinator raced teardown): still a
                    # success signal; unblock any pending rendezvous.
                    job_done = True
                    coord.mark_dead(member_id, reason="exit0-no-leave")
                    notify_exit(member_id, "done")
                    continue
                charge = True
                if status == "left":
                    # Planned departure (preemption/leave): the coordinator
                    # already journaled the leave and survivors shrink in
                    # place. Grow back with a replacement.
                    kind = "leave"
                else:
                    kind = "hang" if member_id in hang_killed else classify(
                        code
                    )
                    if controller is not None:
                        override = controller.classify_exit(
                            member_id, code, kind
                        )
                        if override is not None:
                            kind, charge = override
                    if kind == "hang" and seq not in flight_collected:
                        # ONE collection per hang episode: a fleet-wide
                        # wedge reaps every member as `hang` in one
                        # pass of this loop, and the spawn counter only
                        # advances on the respawns that follow — so
                        # marking the current `seq` dedupes the
                        # episode's members while a LATER hang (after
                        # respawns) still collects fresh evidence.
                        flight_collected.add(seq)
                        files = collect_flight_records(
                            flight_dir, log, seq, kind=kind,
                            member=member_id,
                        )
                        if engine is not None and files:
                            # Replay verdict into the journal BEFORE
                            # the respawn decision below.
                            engine.on_hang(os.path.dirname(files[0]))
                    coord.mark_dead(member_id, reason=kind)
                    last_failure = code if code else 1
                notify_exit(member_id, kind)
                if not job_done:
                    if not charge:
                        # A host-loss sibling: the incident was already
                        # charged ONCE (the first co-resident death).
                        # Journal the event, queue the replacement —
                        # capacity-gated below, since the dead host's
                        # units are gone until the scheduler regrows —
                        # and leave every budget untouched.
                        log.write(
                            "host_lost", 1.0, member=member_id, kind=kind,
                            exit_code=code, generation=coord.generation,
                        )
                        respawn_queue.append((now + backoff, rec["slot"]))
                        continue
                    new_marker = newest_checkpoint_marker(model_dir)
                    cur_progress = committed_progress()
                    progressed = (
                        (model_dir is not None and new_marker != marker)
                        # Step advance IS progress: a fresher committed
                        # (epoch, step) marker on the coordinator since
                        # the last failure, checkpoint file or not.
                        or cur_progress > best_progress
                    )
                    marker = new_marker
                    best_progress = max(best_progress, cur_progress)
                    if progressed:
                        restarts_used = 0
                        oom_used = 0
                        backoff = policy.backoff
                    budget["used"] = restarts_used
                    oom_exhausted = (
                        kind == "oom-kill"
                        and policy.oom_kill_budget is not None
                        and oom_used >= policy.oom_kill_budget
                    )
                    if restarts_used >= policy.max_restarts \
                            or oom_exhausted:
                        log.write(
                            "supervisor_gave_up", 1.0, member=member_id,
                            kind=kind, exit_code=code,
                            generation=coord.generation,
                            restarts=total_restarts,
                            **({"budget": "oom-kill"}
                               if oom_exhausted else {}),
                        )
                        if verbose:
                            spent = (
                                f"oom-kill budget "
                                f"({policy.oom_kill_budget}) spent"
                                if oom_exhausted else "no-progress "
                                "budget spent"
                            )
                            print(
                                f"supervisor: not replacing {member_id} "
                                f"({kind}, exit {code}) — {spent} after "
                                f"{total_restarts} restart(s)"
                            )
                        continue
                    restarts_used += 1
                    if kind == "oom-kill":
                        oom_used += 1
                    budget["used"] = restarts_used
                    total_restarts += 1
                    log.write(
                        "restarts", float(total_restarts),
                        member=member_id, kind=kind, exit_code=code,
                        progressed=progressed, backoff_s=backoff,
                        generation=coord.generation,
                        progress_marker=cur_progress,
                    )
                    if verbose:
                        print(
                            f"supervisor: {member_id} {kind} (exit {code}) "
                            f"— replacement in {backoff:.1f}s "
                            f"(restart {total_restarts})"
                        )
                    respawn_queue.append((now + backoff, rec["slot"]))
                    backoff = min(
                        backoff * policy.backoff_factor, policy.backoff_max
                    )
            def soft_kill(rec):
                """First pass SIGTERMs; `terminated_at` arms the escalation
                below. A wedged member ignores SIGTERM by construction —
                the elastic callback installs a flag-only handler during
                fit, and a rank stuck in a native collective or the `hang`
                fault's sleep never reaches a teardown path — so without
                the SIGKILL escalation it would never be reaped and the
                fleet would wait on it forever."""
                if "terminated_at" not in rec:
                    rec["terminated_at"] = now
                    rec["proc"].terminate()

            # --- scheduler preemption (fleet controller) --------------------
            if controller is not None and not job_done:
                for victim in controller.take_preempts():
                    vrec = members.get(victim)
                    if (vrec is None or vrec["proc"].poll() is not None
                            or victim in preempted):
                        continue
                    preempted.add(victim)
                    log.write(
                        "preempt", 1.0, member=victim,
                        generation=coord.generation,
                    )
                    if verbose:
                        print(
                            f"supervisor: preempting {victim} — the "
                            "scheduler is reclaiming its host"
                        )
                    # Clean-leave path with the same grace escalation an
                    # eviction gets: SIGTERM → elastic flag → leave at
                    # the commit boundary; a wedged victim is killed.
                    soft_kill(vrec)
            # --- hang detection over TCP beats ------------------------------
            if policy.heartbeat_timeout is not None:
                for member_id in coord.stale_members(
                    policy.heartbeat_timeout
                ):
                    rec = members.get(member_id)
                    if rec is not None and rec["proc"].poll() is None:
                        hang_killed.add(member_id)
                        soft_kill(rec)
            if startup_timeout is not None:
                for member_id, rec in members.items():
                    if (
                        rec["proc"].poll() is None
                        and coord.member_status(member_id)[0] == "unknown"
                        and now - rec["spawned"] > startup_timeout
                    ):
                        hang_killed.add(member_id)
                        soft_kill(rec)
            for rec in members.values():
                t0 = rec.get("terminated_at")
                if t0 is None or rec["proc"].poll() is not None:
                    continue
                if now - t0 > policy.grace_seconds:
                    rec["proc"].kill()
                elif now - rec.get("resignaled_at", t0) > 3.0:
                    # One SIGTERM is not guaranteed delivery: if it lands
                    # inside jax.distributed.initialize, XLA's preemption
                    # notifier owns the signal and silently eats it (the
                    # elastic loop only re-installs its own handler after
                    # ensure_world returns). Keep re-sending TERM through
                    # the grace window so a late one still triggers the
                    # clean leave — otherwise the SIGKILL escalation
                    # strands the peers in a collective until the gloo
                    # timeout aborts them, turning a free preemption into
                    # charged crashes.
                    rec["resignaled_at"] = now
                    rec["proc"].terminate()
            # --- policy engine: observe → (warn → evict/promote) ------------
            if engine is not None and not job_done:
                engine.poll(
                    status_server.fleet_cache["members"]
                    if status_server is not None else {}
                )
            # --- grow back --------------------------------------------------
            if not job_done:
                cap = max_ranks
                if controller is not None:
                    ctrl_cap = controller.capacity()
                    if ctrl_cap is not None:
                        # The job's live host allocation is the real
                        # ceiling: a respawn with no host unit to land on
                        # is dropped (take_grows() relaunches when the
                        # scheduler grants hosts back).
                        cap = min(cap, ctrl_cap)

                def joining() -> int:
                    return sum(
                        1 for m in members
                        if coord.member_status(m)[0] == "unknown"
                    )

                due = [r for r in respawn_queue if r[0] <= now]
                respawn_queue = [r for r in respawn_queue if r[0] > now]
                for _, slot in due:
                    if coord.live_count() + joining() < cap:
                        launch(slot)
                if controller is not None:
                    for _ in range(controller.take_grows()):
                        if coord.live_count() + joining() >= cap:
                            break
                        used = {rec["slot"] for rec in members.values()}
                        slot = 0
                        while slot in used:
                            slot += 1
                        launch(slot)
                        log.write(
                            "regrow", 1.0, slot=slot,
                            generation=coord.generation,
                        )
            # --- end states -------------------------------------------------
            if not job_done:
                # A member that reported leave(done) over TCP finished
                # training even if its process hasn't been reaped yet.
                # Without this, a done-leave drops live_count below
                # min_ranks a poll tick before the exit lands, and a fleet
                # with a spent restart budget would read its own success
                # as "below min_ranks — giving up" (observed with
                # max_restarts=0).
                job_done = any(
                    m["status"] == "left" and m["reason"] == "done"
                    for m in coord.snapshot()["members"].values()
                )
            if job_done and members:
                # Training is complete; peers get a grace window to finish
                # their own clean leave, then any straggler (typically a
                # replacement parked in a rendezvous that can never settle)
                # is terminated rather than waited out.
                if done_since is None:
                    done_since = now
                elif now - done_since > policy.grace_seconds:
                    for rec in members.values():
                        if rec["proc"].poll() is None:
                            soft_kill(rec)  # escalates to kill() above
            if job_done and not members:
                if verbose and total_restarts:
                    print(
                        f"supervisor: training complete after "
                        f"{total_restarts} per-rank restart(s)"
                    )
                return teardown(0)
            if not members and not respawn_queue and controller is None:
                # With a fleet controller an empty world is a WAIT state
                # (the job's hosts died or were reclaimed; take_grows()
                # will repopulate it) — the budget-spent check below still
                # ends a job that can never recover.
                if verbose:
                    print(
                        f"supervisor: fleet extinct (last failure "
                        f"{last_failure}) after {total_restarts} restart(s)"
                    )
                return teardown(shell_code(last_failure) or 1)
            if (
                not job_done
                and not respawn_queue
                and coord.live_count() < elastic.min_ranks
                and all(
                    coord.member_status(m)[0] != "unknown" for m in members
                )
                and restarts_used >= policy.max_restarts
            ):
                if verbose:
                    print(
                        f"supervisor: live ranks below min_ranks="
                        f"{elastic.min_ranks} with the restart budget "
                        "spent — giving up"
                    )
                return teardown(shell_code(last_failure) or 1)
            sleep(poll_interval)
    except BaseException:
        teardown(1)
        raise


def journal_records(journal_path: str | None) -> list:
    """Every parseable record of a supervisor journal, rotated ``.1``
    predecessor first so counts survive a `RestartLog` rotation — the
    shared reader behind `fleet_status` and the status endpoint's
    ``/journal`` route. Torn tail lines are skipped; missing files read
    as an empty journal."""
    records: list = []
    if not journal_path:
        return records
    for part in (journal_path + ".1", journal_path):
        if not os.path.exists(part):
            continue
        with open(part) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail mid-append
    return records


def manifest_progress(model_dir: str | None) -> tuple:
    """Best committed ``(epoch, step, cumulative_step, steps_per_epoch)``
    readable from the checkpoint progress manifests under ``model_dir``
    — stdlib-only (the supervisor never imports jax): single-file
    ``.meta.json`` manifests and sharded ``index.json`` "progress"
    records alike.

    ``cumulative_step`` is ``epoch x steps_per_epoch + step`` when the
    manifest's durable stream cursor carries the epoch geometry
    (`Trainer.stream_cursor` does; ``steps_per_epoch`` is then returned
    too so fresher NON-manifest progress — the elastic commit marker —
    can be put on the same cumulative scale), the raw within-epoch
    ``step`` otherwise. This is the honest "how many optimizer steps has
    this job durably committed" figure the ``hvt_committed_step`` gauge
    exports. ``(-1, -1, -1, None)`` when nothing is readable.

    Called on every scrape: per-file parses are memoized by stat
    signature (manifests are write-once via atomic rename), so a
    steady-state scrape costs one stat-walk — the JSON parsing only
    re-runs for manifests that actually changed."""
    best = (-1, -1, -1, None)
    if not model_dir or not os.path.isdir(model_dir):
        return best
    seen = set()
    for root, _, files in os.walk(model_dir):
        for name in files:
            if not (name.endswith(".meta.json") or name == "index.json"):
                continue
            full = os.path.join(root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            sig = (st.st_mtime_ns, st.st_size)
            seen.add(full)
            cached = _manifest_cache.get(full)
            if cached is not None and cached[0] == sig:
                parsed = cached[1]
            else:
                parsed = _parse_manifest(full, name)
                _manifest_cache[full] = (sig, parsed)
            if parsed is not None and parsed[:2] > best[:2]:
                best = parsed
    # Drop cache entries for deleted checkpoints (bounded memory over
    # retention-pruned long runs).
    for stale in set(_manifest_cache) - seen:
        del _manifest_cache[stale]
    return best


# path -> ((mtime_ns, size), parsed tuple | None) — see manifest_progress.
_manifest_cache: dict = {}


def _parse_manifest(full: str, name: str):
    """(epoch, step, cumulative, steps_per_epoch) of one progress
    manifest, or None when unreadable/progress-free."""
    try:
        with open(full) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # torn manifest mid-write — skip, never crash
    prog = rec.get("progress")
    if isinstance(prog, dict):   # sharded index.json shape
        epoch, step = prog.get("epoch"), prog.get("step")
    elif name.endswith(".meta.json"):
        epoch, step = rec.get("epoch"), rec.get("step")
    else:
        return None
    if epoch is None or step is None:
        return None
    epoch, step = int(epoch), int(step)
    spe = ((rec.get("cursor") or {}).get("position") or {}).get(
        "steps_per_epoch"
    )
    total = epoch * int(spe) + step if spe else step
    return (epoch, step, total, int(spe) if spe else None)


def supervisor_metrics(log_path: str | None, coord=None, budget=None,
                       model_dir: str | None = None) -> obs_core.Registry:
    """One scrape of the supervisor's pane of glass, as a FRESH obs
    registry (private per scrape — concurrent scrapes and multiple
    supervisors in one test process never share instruments; the
    declarations stay global, so undeclared names are still refused).

    Aggregates every slice of truth the supervisor can reach:

    * the restart journal → ``hvt_restarts_total`` /
      ``hvt_fleet_shrinks_total`` / ``hvt_fleet_grows_total`` /
      ``hvt_supervisor_gave_up_total`` /
      ``hvt_policy_actions_total{action,outcome}`` (the policy engine's
      ``policy_*`` decision records) and the last settled
      generation/size;
    * the live rendezvous coordinator (elastic mode) →
      ``hvt_fleet_live_members``, per-member
      ``hvt_member_heartbeat_age_seconds``, and the committed progress
      markers;
    * the checkpoint manifests under ``model_dir`` → committed
      ``(epoch, step)`` for non-elastic fleets (and the cumulative-step
      upgrade when the manifest carries the stream geometry);
    * ``budget`` (the supervise loops' shared dict) →
      ``hvt_restart_budget_remaining``."""
    reg = obs_core.Registry()
    records = journal_records(log_path)
    restarts = gave_up = shrinks = grows = flight_dumps = 0
    policy_actions: dict = {}  # (action, outcome) -> count
    generation = size = None
    for rec in records:
        name = rec.get("name")
        if name == "restarts":
            restarts = int(rec.get("value", 0))
        elif name == "supervisor_gave_up":
            gave_up += 1
        elif name == "shrink":
            shrinks += 1
        elif name == "grow":
            grows += 1
        elif name == "flight_dump":
            flight_dumps += 1
        elif isinstance(name, str) and name.startswith("policy_"):
            key = (name[len("policy_"):],
                   str(rec.get("outcome", "applied")))
            policy_actions[key] = policy_actions.get(key, 0) + 1
        if name in ("start", "shrink", "grow", "steady"):
            generation = rec.get("generation")
            size = rec.get("size")
    reg.counter_set("hvt_restarts_total", restarts)
    reg.counter_set("hvt_fleet_shrinks_total", shrinks)
    reg.counter_set("hvt_fleet_grows_total", grows)
    reg.counter_set("hvt_supervisor_gave_up_total", gave_up)
    reg.counter_set("hvt_flight_dumps_total", flight_dumps)
    for (action, outcome), n in sorted(policy_actions.items()):
        reg.counter_set(
            "hvt_policy_actions_total", n, action=action, outcome=outcome,
        )
    epoch, step, total, spe = manifest_progress(model_dir)
    if coord is not None:
        snap = coord.snapshot()
        generation = snap.get("generation", generation)
        settle = snap.get("last_settle") or {}
        size = settle.get("size", size)
        members = snap.get("members", {})
        reg.gauge(
            "hvt_fleet_live_members",
            sum(1 for m in members.values() if m.get("status") == "live"),
        )
        for member_id, m in sorted(members.items()):
            if m.get("beat_age_s") is not None:
                reg.gauge(
                    "hvt_member_heartbeat_age_seconds",
                    m["beat_age_s"], member=member_id,
                )
        # The elastic commit markers live on the coordinator
        # (epoch·RADIX + step) — fresher than any checkpoint file for
        # sub-epoch commit cadences.
        from horovod_tpu.elastic.coordinator import PROGRESS_STEP_RADIX

        marker = max(
            (m.get("progress", -1) for m in members.values()), default=-1
        )
        if marker >= 0:
            m_epoch = marker // PROGRESS_STEP_RADIX
            m_step = marker % PROGRESS_STEP_RADIX
            if (m_epoch, m_step) >= (epoch, step):
                epoch, step = m_epoch, m_step
                # Put the fresher marker on the SAME cumulative scale as
                # the manifest total (the hvt_committed_step contract):
                # the manifest's stream cursor carries steps_per_epoch,
                # so a sub-epoch commit marker converts exactly; without
                # a geometry the gauge degrades to the within-epoch step
                # monotonically (never below the manifest total).
                m_total = (
                    m_epoch * spe + m_step if spe else m_step
                )
                total = max(total, m_total)
    if generation is not None:
        reg.gauge("hvt_elastic_generation", generation)
    if size is not None:
        reg.gauge("hvt_fleet_size", size)
    if epoch >= 0:
        reg.gauge("hvt_committed_epoch", epoch)
        reg.gauge("hvt_committed_step", max(total, step))
    if budget:
        reg.gauge(
            "hvt_restart_budget_remaining",
            max(0, budget.get("max", 0) - budget.get("used", 0)),
        )
    return reg


def member_metrics_ports(env, n_slots: int):
    """The fleet-rollup port map: ``{local rank/slot: exporter port}``
    when the member env exports a non-ephemeral ``HVT_METRICS_PORT``
    base (each member binds base + its local rank — obs/server.py),
    else None (base 0 binds ephemerally; the supervisor cannot know the
    ports, so the rollup stays off). Local/elastic-local launches only:
    the exporters bind loopback on each HOST, which off-host supervision
    cannot reach."""
    raw = (env or {}).get("HVT_METRICS_PORT") or registry.get_raw(
        "HVT_METRICS_PORT"
    )
    try:
        base = int(raw) if raw else 0
    except ValueError:
        return None
    if base <= 0:
        return None
    return {slot: base + slot for slot in range(n_slots)}


def default_metrics_dump_path(model_dir: str | None,
                              log_path: str | None) -> str | None:
    """Where the final supervisor scrape lands: beside the checkpoints
    (``<model_dir>/metrics.prom``), else beside the journal. The SINGLE
    resolver — the dump writer and `launch.job`'s ``metrics_checks:``
    reader must agree on the path or the gate reads a stale file."""
    root = model_dir or (os.path.dirname(log_path) if log_path else None)
    return os.path.join(root, "metrics.prom") if root else None


def dump_metrics(log_path: str | None, coord=None, budget=None,
                 model_dir: str | None = None,
                 path: str | None = None, members: dict | None = None) -> str | None:
    """Write one final text-exposition scrape beside the journal
    (`default_metrics_dump_path`) so metrics survive the supervisor —
    the gateable job output `launch.job`'s ``metrics_checks:`` block
    reads post-run. ``members``: the fleet poller's last per-rank
    exporter scrapes (`start_status_server`'s cache) — merged in with
    ``rank`` labels so the per-rank step-phase/skew series survive the
    fleet (its exporters are gone by dump time). Best-effort: a failed
    dump must never change the job's exit code."""
    if path is None:
        path = default_metrics_dump_path(model_dir, log_path)
        if path is None:
            return None
    try:
        text = obs_prom.render(
            supervisor_metrics(log_path, coord, budget, model_dir)
        )
        if members:
            text = obs_fleet.merge_fleet(text, members)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:  # hvt: noqa[HVT005] — a scrape dump is
            # derived/regenerable observability output, not a checkpoint
            # artifact; a torn dump fails the gate loudly (parse error).
            f.write(text)
        return path
    except OSError:
        return None


def start_status_server(port: int, log_path: str | None, coord=None,
                        host: str | None = None, budget=None,
                        model_dir: str | None = None, fleet_ports=None,
                        env=None):
    """Serve the supervisor's own status over HTTP (the ``--status-port``
    surface): fleet state WITHOUT a serving bundle — previously the
    journal was only visible through ``serve --fleet-journal``'s
    ``/healthz``, i.e. only once a model server was up.

    Binds loopback by default: the routes are unauthenticated and expose
    member ids/hosts/progress and the full journal, so reaching them from
    off-host (a fleet dashboard, a kubelet probing the pod IP) is an
    explicit opt-in — pass ``host=`` or set ``HVT_STATUS_HOST=0.0.0.0``.

    Routes (all JSON):

    * ``GET /status``  → ``{"fleet": fleet_status(...), "coordinator":
      <rendezvous snapshot or null>}`` — generation/size/restart/rescale
      counts plus, on elastic launches, the live membership table.
    * ``GET /journal`` → ``{"records": [...]}`` — the full restart/elastic
      journal (rotation-spanning), each line as a JSON object.
    * ``GET /healthz`` → ``{"status": "ok", "fleet": ...}`` — probe form.
    * ``GET /metrics`` → Prometheus text exposition (`supervisor_metrics`
      — restart-journal counts, elastic generation, committed
      (epoch, step), per-member heartbeat ages, restart budget
      remaining), built fresh per scrape.
    * ``GET /fleet``  → the FLEET rollup (``fleet_ports`` launches
      only): the supervisor exposition spliced with a fresh scrape of
      every reachable member trainer exporter, each member series
      re-labeled with its ``rank`` — plus computed fleet series
      (``hvt_fleet_step_ms{stat="slowest"|"fastest"}``) — so ONE
      Prometheus scrape target per job sees every rank
      (`obs.fleet.merge_fleet`). A background poller re-scrapes every
      ``HVT_FLEET_POLL_S`` seconds into ``server.fleet_cache`` so the
      final ``dump_metrics`` can carry the per-rank series after the
      fleet is gone.

    ``fleet_ports``: ``{rank: exporter port}`` or a zero-arg callable
    returning one (`member_metrics_ports` builds it from the member
    env); None leaves ``/fleet`` serving 404. ``env``: the job env
    mapping, overlaid on the supervisor's own environ when reading the
    poll cadence (``HVT_FLEET_POLL_S``) — so a job spec's ``env:``
    block tunes its own fleet polling.

    Returns the started server (a daemon thread runs it); callers own
    ``shutdown()``. Port 0 binds an ephemeral port —
    ``server.server_address[1]`` carries the real one."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if host is None:
        host = registry.get_str("HVT_STATUS_HOST")
    fleet_cache: dict = {"members": {}}

    def _scrape_members() -> dict:
        """One pass over the member exporters; the cache keeps the
        newest successful scrape per rank, so a member mid-restart
        drops out of the live rollup but its last-seen series still
        make the final dump (dump_metrics merges the cache)."""
        ports = fleet_ports() if callable(fleet_ports) else fleet_ports
        members: dict = {}
        for rank in sorted(ports or {}):
            text = obs_fleet.scrape(
                f"http://127.0.0.1:{ports[rank]}/metrics"
            )
            if text:
                members[rank] = text
        if members:
            fleet_cache["members"].update(members)
        return members

    def _fleet_rollup() -> str:
        members = _scrape_members()
        sup = obs_prom.render(
            supervisor_metrics(log_path, coord, budget, model_dir)
        )
        return obs_fleet.merge_fleet(sup, members)

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # health probes are noise
            pass

        def do_GET(self):
            try:
                if self.path == "/metrics":
                    obs_prom.write_http(self, supervisor_metrics(
                        log_path, coord, budget, model_dir
                    ))
                elif self.path == "/fleet":
                    if fleet_ports is None:
                        self._send(404, {
                            "error": "no fleet rollup — the members "
                            "export no known metrics ports (launch with "
                            "--metrics-port / HVT_METRICS_PORT > 0)",
                        })
                        return
                    body = _fleet_rollup().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", obs_prom.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/status":
                    self._send(200, {
                        "fleet": fleet_status(log_path),
                        "coordinator": coord.snapshot()
                        if coord is not None else None,
                    })
                elif self.path == "/journal":
                    self._send(200, {"records": journal_records(log_path)})
                elif self.path == "/healthz":
                    self._send(200, {"status": "ok",
                                     "fleet": fleet_status(log_path)})
                else:
                    self._send(404, {"error": f"no route {self.path}"})
            except Exception as e:  # observability must never crash
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

    server = ThreadingHTTPServer((host, port), Handler)
    server.fleet_cache = fleet_cache  # dump_metrics reads "members"
    threading.Thread(target=server.serve_forever, daemon=True).start()
    if fleet_ports is not None:
        environ = dict(os.environ)
        environ.update(env or {})
        poll_s = registry.get_float(
            "HVT_FLEET_POLL_S", environ=environ
        ) or 0.0
        if poll_s > 0:
            stop = threading.Event()

            def _poll():
                # Cache refresh only — the render/merge work is paid on
                # /fleet requests and the final dump, not every tick.
                while not stop.wait(poll_s):
                    try:
                        _scrape_members()
                    except Exception:
                        pass  # a flaky member scrape never kills polling

            threading.Thread(target=_poll, daemon=True).start()
            # Stop the poller with the server: long-lived test processes
            # run many supervisors, and an orphan poller re-scraping
            # dead ports forever is a slow leak.
            orig_shutdown = server.shutdown

            def shutdown():
                stop.set()
                orig_shutdown()

            server.shutdown = shutdown
    return server


def fleet_status(journal_path: str | None, events: int = 8) -> dict:
    """Summarize a supervisor journal for serving/health surfaces: current
    generation/size (from the last settle record), restart/shrink/grow
    counts, and the trailing events. Tolerant of torn lines and of a
    missing file (a fleet that never ran restarts supervised). Reads the
    rotated ``.1`` predecessor (if any) before the live file, so counts
    and settle state are continuous across a `RestartLog` rotation."""
    status: dict = {
        "journal": journal_path, "generation": None, "size": None,
        "restarts": 0, "shrinks": 0, "grows": 0, "events": [],
    }
    if not journal_path or not (
        os.path.exists(journal_path)
        or os.path.exists(journal_path + ".1")
    ):
        status["error"] = "journal not found"
        return status
    records = journal_records(journal_path)
    for rec in records:
        name = rec.get("name")
        if name in ("start", "shrink", "grow", "steady"):
            status["generation"] = rec.get("generation")
            status["size"] = rec.get("size")
        if name == "restarts":
            status["restarts"] = int(rec.get("value", 0))
        elif name == "shrink":
            status["shrinks"] += 1
        elif name == "grow":
            status["grows"] += 1
    status["events"] = [
        {k: r.get(k) for k in
         ("name", "kind", "member", "generation", "size", "wall_time")
         if k in r}
        for r in records[-events:]
    ]
    return status


def supervise_hosts(
    hosts: list[str],
    argv: list[str],
    env: dict[str, str] | None = None,
    policy: RestartPolicy | None = None,
    *,
    coordinator_port: int = 9981,
    workdir: str | None = None,
    model_dir: str | None = None,
    heartbeat_dir: str | None = None,
    log_path: str | None = None,
    status_port: int | None = None,
    policy_config: "policy_lib.PolicyConfig | None" = None,
    sleep=time.sleep,
) -> int:
    """`launcher.start_hosts` under supervision (the ``hvt-launch pod
    --max-restarts`` path).

    Multi-host caveats (all three want a shared filesystem — NFS/GCS-fuse —
    mounted at the same paths on the launcher and every host):

    * **Hang detection** reads ``heartbeat_dir`` on the LAUNCHER's
      filesystem; without a shared mount, set ``heartbeat_timeout=None``
      and supervision still covers crash/preemption restarts.
    * **Progress detection** likewise walks ``model_dir`` locally; without
      a shared mount every restart reads as no-progress, so
      ``max_restarts`` bounds TOTAL restarts, not consecutive stuck ones.
    * **Hang teardown** terminates the local ssh clients; a wedged remote
      rank that writes no output may survive as an orphan on its host
      (ssh without a pty cannot signal it). Each relaunch therefore dials
      a ROTATED coordinator port (base + attempt) so an orphan holding the
      old port cannot wedge every subsequent attempt; pair with a host
      provisioner that sweeps orphans (ROADMAP follow-up: coordinator-side
      TCP heartbeats + remote kill)."""
    policy = policy or RestartPolicy()
    if (
        policy.heartbeat_timeout is not None
        and heartbeat_dir is None
        and default_model_dir(env) is None
    ):
        # Without a model dir (or an explicit heartbeat dir) the heartbeat
        # dir falls back to a LAUNCHER-LOCAL tmpdir that remote ranks can
        # never write — hang detection would silently never fire. Fail
        # fast with the fix (satellite of the elastic ISSUE).
        raise ValueError(
            "pod-mode hang detection (--heartbeat-timeout) needs a "
            "heartbeat dir on a filesystem shared with every host: set "
            "PS_MODEL_PATH to a shared mount (NFS/GCS-fuse) or pass "
            "heartbeat_dir= explicitly — or use --elastic, whose "
            "heartbeats ride the rendezvous TCP socket and need no "
            "shared filesystem"
        )
    env, model_dir, heartbeat_dir, log_path = _resolve_dirs(
        env, model_dir, heartbeat_dir, log_path, policy
    )
    launches = {"n": 0}

    def start():
        port = coordinator_port + launches["n"]
        launches["n"] += 1
        return launcher.start_hosts(
            hosts, argv, env=env, coordinator_port=port, workdir=workdir,
        )

    if policy_config is None:
        policy_config = policy_lib.PolicyConfig.from_env(env)
    return supervise(
        start,
        policy,
        model_dir=model_dir,
        heartbeat_dir=heartbeat_dir,
        log_path=log_path,
        status_port=status_port,
        flight_dir=resolve_flight_dir(env),
        policy_config=policy_config,
        sleep=sleep,
    )


def supervise_elastic_hosts(
    hosts: list[str],
    argv: list[str],
    env: dict[str, str] | None = None,
    policy: RestartPolicy | None = None,
    elastic: ElasticPolicy | None = None,
    *,
    sync_port_base: int = 9981,
    workdir: str | None = None,
    model_dir: str | None = None,
    log_path: str | None = None,
    status_port: int | None = None,
    spares: int = 0,
    policy_config: "policy_lib.PolicyConfig | None" = None,
    ssh_args: tuple[str, ...] = ("-o", "StrictHostKeyChecking=no"),
    sleep=time.sleep,
    verbose: bool = True,
) -> int:
    """`supervise_elastic` over ssh — one member per host, the ``hvt-launch
    pod --elastic`` path. Each member (and each replacement, respawned onto
    the SAME host) is one ssh client; heartbeats are TCP beats to the
    launcher-side coordinator, so no shared filesystem is needed for hang
    detection (the `supervise_hosts` caveat this mode exists to remove).
    Progress detection over ``model_dir`` still reads the LAUNCHER's
    filesystem — without a shared mount the restart budget bounds total
    restarts, exactly as in `supervise_hosts`. The jax.distributed port
    rotates with the generation (``sync_port_base +
    generation % SYNC_PORT_WINDOW``) so an orphan holding a recent port
    cannot wedge the next world."""
    import shlex as shlex_lib
    import socket as socket_lib
    import subprocess

    from horovod_tpu.runtime import ENV_ELASTIC_MEMBER, ENV_LOCAL_RANK

    def spawn(member_id: str, slot: int, env: dict[str, str]):
        # ``env`` is the overlay supervise_elastic resolved (model dir,
        # journal path, HVT_ELASTIC_COORDINATOR) — NOT this function's
        # caller env. Supervisor-owned identity keys are applied last so a
        # stale HVT_ELASTIC_MEMBER/HVT_LOCAL_RANK leaked into --env can
        # never override the assigned member id and slot.
        host = hosts[slot % len(hosts)]
        remote_env = {
            **env,
            ENV_ELASTIC_MEMBER: member_id,
            ENV_LOCAL_RANK: "0",
        }
        exports = " ".join(
            f"{k}={shlex_lib.quote(v)}" for k, v in remote_env.items()
        )
        cd = f"cd {shlex_lib.quote(workdir)} && " if workdir else ""
        remote_cmd = (
            f"{cd}{exports} "
            f"{' '.join(shlex_lib.quote(a) for a in argv)}"
        )
        proc = subprocess.Popen(
            ["ssh", *ssh_args, host, remote_cmd],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        launcher._stream(proc, f"{host}/{member_id}")
        return proc

    return supervise_elastic(
        len(hosts), argv, env=env, policy=policy, elastic=elastic,
        model_dir=model_dir, log_path=log_path, status_port=status_port,
        coordinator_host=socket_lib.gethostname(),
        sync_port_base=sync_port_base, spawn=spawn, spares=spares,
        policy_config=policy_config, sleep=sleep,
        verbose=verbose,
    )
