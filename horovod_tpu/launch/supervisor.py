"""Restart supervisor — fail-*restart* semantics around the fail-stop
launchers (SURVEY.md §5.3, the layer the reference leaves to a human).

The reference's fault model ends at fail-stop: any rank failure kills the
MPI job (`launcher.Fleet.wait`) and recovery is *manual* — an operator
reruns the command and `restore_latest_and_broadcast` resumes from the
newest checkpoint (`tests/test_resume_e2e.py` proves that leg). This module
closes the loop: `supervise()` relaunches the whole fleet automatically,
with three properties the manual loop lacks:

* **Failure classification.** Exit 143 / SIGTERM is a *preemption* (the
  gang-scheduler reclaiming the slice — the convention
  `PreemptionCheckpointCallback(exit_code=143)` emits); anything else
  nonzero is a *crash*; a fleet the supervisor itself had to kill for
  stale heartbeats is a *hang*.
* **Progress-aware restart budget.** The budget decrements only when a
  launch made *no progress* (the newest checkpoint under ``model_dir``
  unchanged since the previous launch). A transient fault that keeps
  losing different epochs restarts indefinitely; a deterministic crash
  loop — same fault, same epoch, every launch — burns through
  ``max_restarts`` and exits with the original exit code. Backoff is
  exponential between no-progress restarts and resets on progress.
* **Hang detection.** A rank wedged in a collective produces no exit code
  at all (the classic NCCL/ICI failure mode, arXiv:1810.11112). Each rank
  touches ``<heartbeat_dir>/rank-<i>`` from a trainer callback
  (`callbacks.HeartbeatCallback`, auto-installed by ``fit()`` when the
  supervisor exports ``HVT_HEARTBEAT_DIR``); when the *newest* heartbeat
  is older than ``heartbeat_timeout`` the supervisor kills the fleet and
  relaunches it. Size the timeout above worst-case step + compile time —
  the first beat lands at train begin, before the first step compiles.
  On multi-host (pod) launches hang detection needs ``heartbeat_dir`` on
  a filesystem shared with every host, and teardown reaches only the
  local ssh clients — see `supervise_hosts` for the orphan caveats and
  the coordinator-port rotation that keeps relaunches viable anyway.

Every restart decision is appended to a JSONL log whose records are
metric-shaped (``{"name": "restarts", "value": <total so far>, ...}``)
precisely so the existing CI gate reads it unchanged:

    hvt-launch gate --metrics restarts.jsonl --check restarts=1..1 \
        --aggregate count

Deterministic chaos for testing lives in `horovod_tpu.testing.faults`
(``HVT_FAULT=rank:epoch:kind``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import signal
import tempfile
import time

from horovod_tpu.launch import launcher
from horovod_tpu.runtime import ENV_HEARTBEAT_DIR

# Any file named like a checkpoint artifact counts as progress: single-file
# epochs (checkpoint-3.msgpack), sharded dirs (checkpoint-3.sharded/...),
# EMA shadows. Matched against the basename, extension-agnostic like
# checkpoint.latest_checkpoint.
_CHECKPOINT_RE = re.compile(r"checkpoint-(\d+)")


@dataclasses.dataclass
class RestartPolicy:
    """Knobs for `supervise` (CLI: --max-restarts/--backoff/
    --heartbeat-timeout; YAML: the job's ``restart:`` block).

    ``max_restarts`` bounds *consecutive no-progress* restarts, not total
    restarts — see the module docstring. ``heartbeat_timeout=None``
    disables hang detection; size it above the longest legitimate
    beat-free span (worst-case compile + step on the streamed fit path,
    worst-case EPOCH on the device-cached path where batch callbacks fire
    once per epoch, plus any post-fit export/eval work).
    ``startup_timeout`` separately bounds time-to-FIRST-beat, so a fleet
    that wedges before training (stuck ``jax.distributed.initialize``, an
    orphan holding the coordinator port) is also caught; default
    ``None`` = 10 × ``heartbeat_timeout`` (imports + distributed init +
    build trace all precede the first beat)."""

    max_restarts: int = 3
    backoff: float = 1.0
    backoff_factor: float = 2.0
    backoff_max: float = 60.0
    heartbeat_timeout: float | None = None
    startup_timeout: float | None = None
    grace_seconds: float = 30.0

    @classmethod
    def from_mapping(cls, mapping) -> "RestartPolicy":
        """Build a policy from a partial dict — the single constructor both
        front-ends (CLI flags, the YAML ``restart:`` block) funnel through,
        so a new knob can't land in one and silently no-op in the other.
        Unknown keys are rejected loudly. ``None`` values mean 'keep the
        default' (unset CLI flags)."""
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(mapping) - fields
        if unknown:
            raise ValueError(
                f"unknown restart policy keys {sorted(unknown)}; "
                f"valid: {sorted(fields)}"
            )
        policy = cls()
        for key, value in mapping.items():
            if value is None:
                continue
            setattr(
                policy, key,
                int(value) if key == "max_restarts" else float(value),
            )
        return policy


def classify(exit_code: int, hang: bool = False) -> str:
    """Map a fleet outcome to a restart-log kind.

    143 (= 128 + SIGTERM, the `PreemptionCheckpointCallback` convention) and
    a raw SIGTERM death both read as the scheduler reclaiming the slice."""
    if hang:
        return "hang"
    if exit_code in (143, -signal.SIGTERM):
        return "preemption"
    return "crash"


def shell_code(exit_code: int) -> int:
    """Popen returncodes are negative for signal deaths; shells speak
    128+sig. Positive codes pass through untouched (the acceptance contract:
    a deterministic ``exit 7`` loop exits the supervisor with 7)."""
    if exit_code > 0:
        return exit_code
    if exit_code < 0:
        return 128 - exit_code
    return 0


def newest_checkpoint_marker(model_dir: str | None):
    """Identity of the newest checkpoint-like file under ``model_dir``
    (recursive — single-file checkpoints and sharded-dir shard files alike),
    as a comparable ``(path, mtime_ns, size)`` tuple; None when there are
    none. Two calls comparing unequal == progress was made in between."""
    if not model_dir or not os.path.isdir(model_dir):
        return None
    newest = None
    for root, _, files in os.walk(model_dir):
        for name in files:
            if not _CHECKPOINT_RE.search(name) and not _CHECKPOINT_RE.search(
                os.path.basename(root)
            ):
                continue
            full = os.path.join(root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue  # racing a writer's atomic rename
            key = (st.st_mtime_ns, full)
            if newest is None or key > newest[0]:
                newest = (key, (full, st.st_mtime_ns, st.st_size))
    return newest[1] if newest else None


def _reset_heartbeats(heartbeat_dir: str) -> None:
    """Clear stale beats before a (re)launch — a leftover rank file from the
    previous attempt would read as instantly-stale and kill the new fleet
    before it trains a step."""
    os.makedirs(heartbeat_dir, exist_ok=True)
    for name in os.listdir(heartbeat_dir):
        if name.startswith("rank-"):
            try:
                os.remove(os.path.join(heartbeat_dir, name))
            except OSError:
                pass


def newest_beat(heartbeat_dir: str) -> float | None:
    """Wall-clock mtime of the freshest ``rank-*`` beat, None if none."""
    try:
        names = os.listdir(heartbeat_dir)
    except OSError:
        return None
    newest = None
    for name in names:
        if not name.startswith("rank-"):
            continue
        try:
            mt = os.stat(os.path.join(heartbeat_dir, name)).st_mtime
        except OSError:
            continue
        newest = mt if newest is None else max(newest, mt)
    return newest


def heartbeats_stale(heartbeat_dir: str, timeout: float,
                     now=None) -> bool:
    """True when heartbeats exist but the newest is older than ``timeout``
    of wall-clock ``now``. Same-clock convenience check (single-host
    tooling, tests); the supervisor's own abort hook uses skew-immune
    change-detection instead (`_throttled_staleness_check`). No files yet
    = not stale here — time-to-FIRST-beat is bounded separately by the
    abort hook's startup timeout."""
    newest = newest_beat(heartbeat_dir)
    if newest is None:
        return False
    return (now if now is not None else time.time()) - newest > timeout


def _throttled_staleness_check(heartbeat_dir: str, timeout: float,
                               startup_timeout: float):
    """An abort hook for `Fleet.wait` that stats the heartbeat dir at a
    cadence proportional to the timeout (bounded to [0.5s, 5s]) rather than
    at the fleet's 10 Hz process-poll rate — a question with timeout-scale
    resolution must not generate constant metadata traffic on the
    NFS/GCS-fuse mounts multi-host hang detection runs over.

    Two hang shapes are bounded: beats that STOPPED and beats that never
    STARTED (no rank file within ``startup_timeout`` of the launch — a
    fleet wedged in distributed init produces no exit code and no beats,
    and would otherwise be supervised forever).

    Staleness is judged by whether the newest beat's mtime has CHANGED
    within ``timeout`` of the supervisor's own monotonic clock — never by
    comparing rank-written mtimes against the supervisor's wall clock.
    On multi-host (NFS/GCS-fuse) deployments the rank hosts' clocks can
    skew past the timeout in either direction; wall-clock comparison
    would then kill healthy fleets (or mask real hangs), while
    change-detection only requires the mtimes to be *distinct* across
    beats."""
    interval = max(0.5, min(5.0, timeout / 10.0))
    t0 = time.monotonic()
    state = {"next": 0.0, "stale": False, "beat": None, "changed_at": t0}

    def abort() -> bool:
        now = time.monotonic()
        if now >= state["next"]:
            state["next"] = now + interval
            beat = newest_beat(heartbeat_dir)
            if beat is None:
                state["stale"] = now - t0 > startup_timeout
            else:
                if beat != state["beat"]:
                    state["beat"] = beat
                    state["changed_at"] = now
                state["stale"] = now - state["changed_at"] > timeout
        return state["stale"]

    return abort


class RestartLog:
    """Append-only JSONL restart journal. Records double as CI-gate metrics:
    each carries ``name``/``value`` (value = total restarts so far), so
    ``ci_gate.check_metrics(log, 'restarts', (1, 1), how='count')`` asserts
    restart counts with no new machinery."""

    def __init__(self, path: str | None):
        self.path = path

    def touch(self) -> None:
        """Ensure the journal exists even for a zero-restart run: the CI
        gate fails on a MISSING file for every aggregate, so 'ran
        supervised, zero restarts' (`restarts=0..0 --aggregate count`)
        must be distinguishable from 'never ran'."""
        if not self.path:
            return
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a"):
            pass

    def write(self, name: str, value: float, **fields) -> None:
        if not self.path:
            return
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        record = {"name": name, "value": value, "wall_time": time.time(),
                  **fields}
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()


def supervise(
    start,
    policy: RestartPolicy | None = None,
    *,
    model_dir: str | None = None,
    heartbeat_dir: str | None = None,
    log_path: str | None = None,
    sleep=time.sleep,
    verbose: bool = True,
) -> int:
    """Launch-monitor-relaunch loop. ``start`` is a zero-arg callable
    returning a running `launcher.Fleet` (close over `start_local` /
    `start_hosts` with the env already carrying ``HVT_HEARTBEAT_DIR`` —
    `supervise_local` does this wiring). Returns 0 on fleet success, else
    the final failure's shell exit code once the no-progress budget is
    exhausted."""
    policy = policy or RestartPolicy()
    log = RestartLog(log_path)
    log.touch()
    marker = newest_checkpoint_marker(model_dir)
    restarts_used = 0   # consecutive no-progress restarts — the budget
    total_restarts = 0  # lifetime count — what the log/gate report
    backoff = policy.backoff
    attempt = 0

    while True:
        attempt += 1
        abort = None
        if heartbeat_dir and policy.heartbeat_timeout is not None:
            _reset_heartbeats(heartbeat_dir)
            abort = _throttled_staleness_check(
                heartbeat_dir, policy.heartbeat_timeout,
                policy.startup_timeout
                if policy.startup_timeout is not None
                else 10.0 * policy.heartbeat_timeout,
            )
        fleet = start()
        code = fleet.wait(policy.grace_seconds, abort=abort)
        if code == 0 and not fleet.aborted:
            if verbose and total_restarts:
                print(f"supervisor: fleet succeeded after "
                      f"{total_restarts} restart(s)")
            return 0

        kind = classify(code, hang=fleet.aborted)
        new_marker = newest_checkpoint_marker(model_dir)
        progressed = model_dir is not None and new_marker != marker
        marker = new_marker
        if progressed:
            # Fresh checkpoint since launch: the fault is not a
            # deterministic loop — full budget and backoff again.
            restarts_used = 0
            backoff = policy.backoff
        if restarts_used >= policy.max_restarts:
            log.write(
                "supervisor_gave_up", 1.0, attempt=attempt, kind=kind,
                exit_code=code, restarts=total_restarts,
            )
            if verbose:
                print(
                    f"supervisor: giving up after {total_restarts} "
                    f"restart(s) — attempt {attempt} {kind} "
                    f"(exit {code}), no progress in the last "
                    f"{restarts_used} restart(s)"
                )
            # `or 1`: a hang-killed rank that trapped SIGTERM and exited 0
            # must still surface as failure.
            return shell_code(code) or 1
        restarts_used += 1
        total_restarts += 1
        log.write(
            "restarts", float(total_restarts), attempt=attempt, kind=kind,
            exit_code=code, progressed=progressed, backoff_s=backoff,
        )
        if verbose:
            print(
                f"supervisor: attempt {attempt} {kind} (exit {code}, "
                f"{'progress' if progressed else 'no progress'}) — "
                f"restart {total_restarts} in {backoff:.1f}s"
            )
        sleep(backoff)
        backoff = min(backoff * policy.backoff_factor, policy.backoff_max)


def default_heartbeat_dir(model_dir: str | None) -> str:
    """``<model_dir>/hb`` when the job has a model dir (shared-filesystem
    deployments get multi-host hang detection for free), else a tmpdir."""
    if model_dir:
        return os.path.join(model_dir, "hb")
    return tempfile.mkdtemp(prefix="hvt-hb-")


def default_model_dir(env) -> str | None:
    """The progress-detection root: job env's PS_MODEL_PATH, falling back
    to the launcher's own environment."""
    return (env or {}).get("PS_MODEL_PATH") or os.environ.get("PS_MODEL_PATH")


def default_log_path(env) -> str | None:
    """Where the restart journal lands by default: beside the checkpoints.
    The SINGLE resolver — `run_job`'s stale-journal reset and the
    supervisor's writer must agree on the path or the reset silently
    guards the wrong file."""
    model_dir = default_model_dir(env)
    return os.path.join(model_dir, "restarts.jsonl") if model_dir else None


def _resolve_dirs(env, model_dir, heartbeat_dir, log_path, policy):
    """Shared CLI/YAML wiring: model dir from PS_MODEL_PATH, heartbeat dir
    exported to children, restart log defaulted beside the checkpoints."""
    env = dict(env or {})
    model_dir = model_dir or default_model_dir(env)
    if policy.heartbeat_timeout is not None:
        heartbeat_dir = heartbeat_dir or default_heartbeat_dir(model_dir)
        env[ENV_HEARTBEAT_DIR] = heartbeat_dir
    else:
        heartbeat_dir = None
    if log_path is None:
        log_path = default_log_path(env)
    return env, model_dir, heartbeat_dir, log_path


def supervise_local(
    nprocs: int,
    argv: list[str],
    env: dict[str, str] | None = None,
    policy: RestartPolicy | None = None,
    *,
    model_dir: str | None = None,
    heartbeat_dir: str | None = None,
    log_path: str | None = None,
    tag_output: bool = True,
    sleep=time.sleep,
) -> int:
    """`launcher.start_local` under supervision (the ``hvt-launch run
    --max-restarts`` path)."""
    policy = policy or RestartPolicy()
    env, model_dir, heartbeat_dir, log_path = _resolve_dirs(
        env, model_dir, heartbeat_dir, log_path, policy
    )
    return supervise(
        lambda: launcher.start_local(
            nprocs, argv, env=env, tag_output=tag_output
        ),
        policy,
        model_dir=model_dir,
        heartbeat_dir=heartbeat_dir,
        log_path=log_path,
        sleep=sleep,
    )


def supervise_hosts(
    hosts: list[str],
    argv: list[str],
    env: dict[str, str] | None = None,
    policy: RestartPolicy | None = None,
    *,
    coordinator_port: int = 9981,
    workdir: str | None = None,
    model_dir: str | None = None,
    heartbeat_dir: str | None = None,
    log_path: str | None = None,
    sleep=time.sleep,
) -> int:
    """`launcher.start_hosts` under supervision (the ``hvt-launch pod
    --max-restarts`` path).

    Multi-host caveats (all three want a shared filesystem — NFS/GCS-fuse —
    mounted at the same paths on the launcher and every host):

    * **Hang detection** reads ``heartbeat_dir`` on the LAUNCHER's
      filesystem; without a shared mount, set ``heartbeat_timeout=None``
      and supervision still covers crash/preemption restarts.
    * **Progress detection** likewise walks ``model_dir`` locally; without
      a shared mount every restart reads as no-progress, so
      ``max_restarts`` bounds TOTAL restarts, not consecutive stuck ones.
    * **Hang teardown** terminates the local ssh clients; a wedged remote
      rank that writes no output may survive as an orphan on its host
      (ssh without a pty cannot signal it). Each relaunch therefore dials
      a ROTATED coordinator port (base + attempt) so an orphan holding the
      old port cannot wedge every subsequent attempt; pair with a host
      provisioner that sweeps orphans (ROADMAP follow-up: coordinator-side
      TCP heartbeats + remote kill)."""
    policy = policy or RestartPolicy()
    env, model_dir, heartbeat_dir, log_path = _resolve_dirs(
        env, model_dir, heartbeat_dir, log_path, policy
    )
    launches = {"n": 0}

    def start():
        port = coordinator_port + launches["n"]
        launches["n"] += 1
        return launcher.start_hosts(
            hosts, argv, env=env, coordinator_port=port, workdir=workdir,
        )

    return supervise(
        start,
        policy,
        model_dir=model_dir,
        heartbeat_dir=heartbeat_dir,
        log_path=log_path,
        sleep=sleep,
    )
