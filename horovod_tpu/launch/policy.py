"""Supervisor policy engine — closing the observe→act loop (ROADMAP
item 4's control-plane half).

PRs 13–15 built the sensing tier: live `SkewProbe` straggler gauges on
every member exporter, the supervisor's ``/fleet`` poller caching those
scrapes, flight records quarantined on hang classifications, and
`hvt-sched replay` naming the first divergent collective submission. All
of it terminated at a human reading a dashboard. This module is the
actuator that reads the SAME signals the supervisor already owns and
drives the elastic shrink/grow machinery from them:

* **Straggler eviction** (`PolicyEngine.poll`): the `/fleet` poller's
  cached member expositions carry ``hvt_straggler_rank`` /
  ``hvt_barrier_wait_ms`` / ``hvt_step_samples_total``. A new *window*
  opens only when a sample counter advances (scrapes between SkewProbe
  publishes are identical — wall-clock polls must not inflate the
  evidence); a majority-named straggler across
  ``straggler_windows`` consecutive windows with barrier-wait above
  ``straggler_wait_ms`` triggers evict-and-shrink: SIGTERM the named
  member so the elastic callback's existing ``leave``→shrink path
  re-slices its work — or, when warm spares are parked at rendezvous
  (``supervise_elastic(spares=K)``), hot-spare promotion: the freed
  slot admits a knocking spare and world size is preserved.
* **Hang auto-triage** (`PolicyEngine.on_hang`): the supervisor's hang
  path already quarantine-copies flight records; the engine runs the
  `hvt-sched replay` cross-check over the copies and journals the
  first-divergence verdict (members, seq, op) BEFORE the relaunch
  decision — a ``reorder`` hang is diagnosed, not just restarted.
* **Safety rails** — an actuator that misfires is worse than none:
  a per-action eviction budget and cooldown SEPARATE from the restart
  budget, an escalation ladder (observe → journal warning →
  evict/promote → the existing restart machinery), and
  ``HVT_POLICY=off|dry-run|on`` where ``dry-run`` journals every
  decision it *would* take without acting.

Every decision is one ``policy_<action>`` journal record (same JSONL
journal the restart supervisor writes, so `ci_gate` gates it with the
existing ``journal_checks:`` grammar) and surfaces as
``hvt_policy_actions_total{action,outcome}`` on the supervisor's
``/metrics`` and ``/fleet`` panes (`supervisor.supervisor_metrics`
counts the journal).

The engine is deliberately pure over its inputs: ``members`` is a
``{slot: exposition text}`` dict (the fleet cache), the actuator is an
injected callable, and the clock is injectable — every ladder rung unit
tests without a process tree.
"""

from __future__ import annotations

import dataclasses
import os
import time

from horovod_tpu.analysis import registry
from horovod_tpu.obs import prom as obs_prom

MODES = ("off", "dry-run", "on")

# The SkewProbe gauges the detector reads from each member exposition
# (trainer.py publishes them at every step-phase sample window).
_SAMPLES = "hvt_step_samples_total"
_STRAGGLER = "hvt_straggler_rank"
_BARRIER_WAIT = "hvt_barrier_wait_ms"


def _check_mode(mode: str) -> str:
    if mode not in MODES:
        raise ValueError(
            f"unknown policy mode {mode!r}; valid: {list(MODES)}"
        )
    return mode


@dataclasses.dataclass
class PolicyConfig:
    """Knobs for the policy engine (CLI: ``--policy``/``--spares``; YAML:
    the job's ``policy:`` block; env: the ``HVT_POLICY*`` knobs).

    ``mode``: ``off`` (engine never constructed), ``dry-run`` (every
    decision journaled with ``outcome="dry-run"``, nothing acted on), or
    ``on``. The action knobs are separate from `RestartPolicy`'s restart
    budget by design — the whole point of eviction is rescuing a run
    WITHOUT spending a restart:

    * ``straggler_windows``: consecutive fresh sample windows the same
      rank must be majority-named (with barrier-wait over
      ``straggler_wait_ms``) before the evict rung fires;
    * ``straggler_warn_windows``: the observe→warn rung — streak length
      at which a ``policy_warn`` is journaled (once per rank);
    * ``evict_budget``: evictions per supervisor lifetime (the budget is
      also charged in dry-run, so a dry run journals exactly what a real
      run would do);
    * ``cooldown_s``: minimum seconds between policy ACTIONS — the fleet
      must be given time to re-settle before the next intervention;
    * ``spares``: warm standbys for `supervise_elastic` — K extra
      members spawned at launch that park at rendezvous (world full) and
      join the generation an eviction frees a slot in, preserving world
      size instead of shrinking."""

    mode: str = "off"
    straggler_windows: int = 3
    straggler_warn_windows: int = 1
    straggler_wait_ms: float = 100.0
    evict_budget: int = 1
    cooldown_s: float = 60.0
    spares: int = 0

    @classmethod
    def from_mapping(cls, mapping) -> "PolicyConfig":
        """Build a config from a partial dict — the single constructor the
        CLI flags and the YAML ``policy:`` block funnel through (the
        `RestartPolicy.from_mapping` contract: unknown keys rejected
        loudly, ``None`` values keep the default)."""
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(mapping) - fields
        if unknown:
            raise ValueError(
                f"unknown policy keys {sorted(unknown)}; "
                f"valid: {sorted(fields)}"
            )
        config = cls()
        for key, value in mapping.items():
            if value is None:
                continue
            if key == "mode":
                config.mode = _check_mode(str(value))
            elif key in ("straggler_wait_ms", "cooldown_s"):
                setattr(config, key, float(value))
            else:
                setattr(config, key, int(value))
        return config

    @classmethod
    def from_env(cls, env=None) -> "PolicyConfig":
        """Resolve from the ``HVT_POLICY*`` knobs, the job env overlay
        winning over the supervisor's own environment (the
        `resolve_flight_dir` precedence)."""
        environ = dict(os.environ)
        environ.update(env or {})
        return cls(
            mode=_check_mode(
                registry.get_str("HVT_POLICY", environ=environ) or "off"
            ),
            straggler_windows=registry.get_int(
                "HVT_POLICY_STRAGGLER_WINDOWS", environ=environ
            ),
            straggler_wait_ms=registry.get_float(
                "HVT_POLICY_STRAGGLER_WAIT_MS", environ=environ
            ),
            evict_budget=registry.get_int(
                "HVT_POLICY_EVICT_BUDGET", environ=environ
            ),
            cooldown_s=registry.get_float(
                "HVT_POLICY_COOLDOWN_S", environ=environ
            ),
            spares=registry.get_int("HVT_POLICY_SPARES", environ=environ),
        )

    @property
    def active(self) -> bool:
        return self.mode != "off"

    @property
    def dry_run(self) -> bool:
        return self.mode == "dry-run"


class StragglerDetector:
    """Windowed majority vote over the fleet cache's member expositions.

    Pure state machine: `observe` takes ``{slot: exposition text}`` and
    returns None until a FRESH sample window exists (some member's
    ``hvt_step_samples_total`` advanced since the last observation),
    else a window summary with the running confirmation ``streak``. The
    freshness gate is what makes ``straggler_windows`` mean "N distinct
    SkewProbe publishes", not "N wall-clock polls of the same cached
    scrape"."""

    def __init__(self, windows: int, wait_ms: float):
        self.windows = windows
        self.wait_ms = wait_ms
        self._samples: dict = {}   # member key -> last sample counter
        self.candidate: int | None = None
        self.streak = 0

    def observe(self, members: dict | None) -> dict | None:
        parsed = {}
        for key, text in (members or {}).items():
            try:
                parsed[key] = obs_prom.parse_text(text)
            except ValueError:
                continue  # a torn member scrape must not kill the vote
        fresh = False
        for key, vals in parsed.items():
            samples = vals.get(_SAMPLES)
            if samples is None:
                continue
            if samples != self._samples.get(key):
                self._samples[key] = samples
                fresh = True
        if not fresh:
            return None
        votes: dict = {}
        waits = []
        for vals in parsed.values():
            named = vals.get(_STRAGGLER)
            if named is not None and named >= 0:
                votes[int(named)] = votes.get(int(named), 0) + 1
            wait = vals.get(_BARRIER_WAIT)
            if wait is not None:
                waits.append(wait)
        voters = sum(votes.values())
        # Smallest rank wins a tie — deterministic, and matches the
        # probe's own tie-break.
        rank, count = (
            min(votes.items(), key=lambda kv: (-kv[1], kv[0]))
            if votes else (None, 0)
        )
        max_wait = max(waits, default=0.0)
        # >= 2 voters: one member's self-report is not cross-rank
        # evidence — and after a shrink to one rank the survivor's
        # LAST-published gauges go stale at the old verdict, which must
        # never re-trigger the ladder.
        confirmed = (
            rank is not None
            and voters >= 2
            and count * 2 > voters
            and max_wait >= self.wait_ms
        )
        if confirmed:
            self.streak = self.streak + 1 if rank == self.candidate else 1
            self.candidate = rank
        else:
            self.candidate, self.streak = None, 0
        return {
            "confirmed": confirmed,
            "rank": self.candidate,
            "streak": self.streak,
            "wait_ms": round(max_wait, 3),
            "voters": voters,
        }


class PolicyEngine:
    """The supervisor-resident observe→act loop.

    ``journal``: a `RestartLog.write`-shaped callable — every decision
    lands as ``policy_<action>`` with an ``outcome`` field.
    ``evict``: optional actuator ``(world_rank) -> outcome str``; None
    means this supervise mode has no per-member actuator (whole-fleet
    `supervise`), so the evict rung journals ``outcome="unsupported"``.
    ``spare_count``: optional zero-arg callable counting currently
    parked warm standbys (`supervise_elastic` wires it); a successful
    eviction with spares available additionally journals
    ``policy_promote`` — the freed slot's knocking spare preserves world
    size.

    The engine throttles its own parsing (``min_poll_s``) so wiring it
    into a 10 Hz supervision loop costs nothing between windows."""

    def __init__(self, config: PolicyConfig, journal, *, evict=None,
                 spare_count=None, min_poll_s: float = 1.0,
                 clock=time.monotonic):
        self.config = config
        self._journal = journal
        self._evict = evict
        self._spare_count = spare_count
        self._clock = clock
        self._min_poll_s = min_poll_s
        self._next_poll = 0.0
        self.detector = StragglerDetector(
            config.straggler_windows, config.straggler_wait_ms
        )
        self.evicts_used = 0
        self._last_action_at: float | None = None
        self._warned: set = set()
        self._decided: set = set()

    def _record(self, action: str, outcome: str, **fields) -> None:
        self._journal(
            f"policy_{action}", 1.0, mode=self.config.mode,
            outcome=outcome, **fields,
        )

    def poll(self, members: dict | None) -> None:
        """One observation of the fleet cache; walks the ladder when a
        fresh window confirms a straggler."""
        now = self._clock()
        if now < self._next_poll:
            return
        self._next_poll = now + self._min_poll_s
        window = self.detector.observe(members)
        if not window or not window["confirmed"]:
            return
        rank, streak = window["rank"], window["streak"]
        cfg = self.config
        if streak >= cfg.straggler_warn_windows and rank not in self._warned:
            # The warn rung is journal-only in every mode — it IS the
            # dry half of the ladder.
            self._warned.add(rank)
            self._record(
                "warn", "journaled", rank=rank, streak=streak,
                wait_ms=window["wait_ms"], voters=window["voters"],
            )
        if streak < cfg.straggler_windows or rank in self._decided:
            return
        if (
            self._last_action_at is not None
            and now - self._last_action_at < cfg.cooldown_s
        ):
            return  # cooling down; the streak keeps the evidence warm
        if self.evicts_used >= cfg.evict_budget:
            # Decide once, then defer to the restart machinery — the
            # ladder's final rung is the budget the supervisor already
            # owns, not an unbounded actuator.
            self._decided.add(rank)
            self._record(
                "evict", "budget-exhausted", rank=rank, streak=streak,
                wait_ms=window["wait_ms"], voters=window["voters"],
            )
            return
        spares = int(self._spare_count()) if self._spare_count else 0
        self._decided.add(rank)
        self.evicts_used += 1
        self._last_action_at = now
        if cfg.dry_run:
            self._record(
                "evict", "dry-run", rank=rank, streak=streak,
                wait_ms=window["wait_ms"], voters=window["voters"],
                spares=spares,
            )
            if spares:
                self._record("promote", "dry-run", rank=rank, spares=spares)
            return
        if self._evict is None:
            self._record(
                "evict", "unsupported", rank=rank, streak=streak,
                wait_ms=window["wait_ms"], voters=window["voters"],
            )
            return
        outcome = self._evict(rank) or "error"
        self._record(
            "evict", outcome, rank=rank, streak=streak,
            wait_ms=window["wait_ms"], voters=window["voters"],
            spares=spares,
        )
        if spares and outcome == "sigterm":
            self._record("promote", "released", rank=rank, spares=spares)

    def serve_autoscaler(self) -> "ServeAutoscaler":
        """The serving-tier rung: an autoscaler sharing this engine's
        mode/cooldown discipline (the `ServeFleet` autoscale thread
        constructs one directly when it runs without a PolicyEngine)."""
        return ServeAutoscaler(cooldown_s=self.config.cooldown_s)

    def on_hang(self, dump_dir: str | None) -> dict | None:
        """Auto-triage one quarantined hang collection: run the
        `hvt-sched replay` cross-check over ``dump_dir`` and journal the
        verdict as ``policy_triage`` — called by the supervise loops
        right after `collect_flight_records`, BEFORE the relaunch
        decision is journaled. Returns the verdict (or None when there
        was nothing to cross-check)."""
        if not dump_dir:
            return None
        from horovod_tpu import flight

        verdict = flight.replay_verdict(flight.load_members(dump_dir))
        if verdict is None:
            return None
        fields = {k: v for k, v in verdict.items() if k != "status"}
        self._record("triage", verdict["status"], dir=dump_dir, **fields)
        return verdict


# --- serving-tier autoscaling (the ServeFleet hook) -------------------------

_TTFT_COUNT = "hvt_serve_ttft_seconds_count"
_TTFT_BUCKET = "hvt_serve_ttft_seconds_bucket"


def histogram_quantile(series: dict, name: str, q: float,
                       window_floor: dict | None = None) -> float | None:
    """Prometheus-style ``histogram_quantile`` over one parsed exposition
    (`obs_prom.parse_text` output): linear interpolation inside the
    winning cumulative bucket, the standard over-estimate for ``+Inf``
    (the last finite edge). ``window_floor``: per-``le`` counts to
    SUBTRACT first — pass the previous scrape's buckets to get the
    quantile of just the window between two scrapes (counters only grow,
    so lifetime buckets would let the fleet's good first hour mask a bad
    last minute). Returns None with no observations."""
    prefix = f"{name}_bucket{{le=\""
    edges: list[tuple[float, float]] = []
    for key, value in series.items():
        if not key.startswith(prefix):
            continue
        le = key[len(prefix):-2]
        edge = float("inf") if le == "+Inf" else float(le)
        value -= (window_floor or {}).get(edge, 0.0)
        edges.append((edge, value))
    if not edges:
        return None
    edges.sort()
    total = edges[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_edge, prev_cum = 0.0, 0.0
    for edge, cum in edges:
        if cum >= target:
            if edge == float("inf"):
                return prev_edge  # the standard +Inf clamp
            span = cum - prev_cum
            if span <= 0:
                return edge
            return prev_edge + (edge - prev_edge) * (
                (target - prev_cum) / span
            )
        prev_edge, prev_cum = edge, cum
    return edges[-1][0]


class ServeAutoscaler:
    """TTFT-driven scale decision over the serving router's exposition.

    The same shape as `StragglerDetector`: a pure state machine whose
    `observe` takes one parsed exposition (`obs_prom.parse_text` of the
    router registry — the tier-level TTFT histogram every request
    crosses) and returns ``"up"``, ``"down"``, or None. Discipline
    ported from the training-side ladder:

    * **freshness gate** — a window only opens when
      ``hvt_serve_ttft_seconds_count`` ADVANCED since the last one
      (idle fleets neither scale up on stale tails nor scale down to
      zero on no evidence);
    * **windowed quantile** — p95 is computed over just the requests
      since the previous window (bucket deltas), not lifetime counts;
    * **streak** — ``streak_windows`` consecutive breaches (p95 above
      ``ttft_p95_ms``) scale up; the same streak of p95 under
      ``ttft_p95_ms * down_factor`` scales down;
    * **cooldown** — ``cooldown_s`` between decisions either way.

    Thresholds default from the ``HVT_SERVE_TTFT_P95_MS`` knob; the
    caller (`serving.fleet.ServeFleet`) journals every decision as
    ``policy_scale_up`` / ``policy_scale_down`` and owns the actuators.
    """

    def __init__(self, ttft_p95_ms: float | None = None,
                 streak_windows: int = 3, down_factor: float = 0.3,
                 cooldown_s: float = 30.0, clock=time.monotonic):
        if ttft_p95_ms is None:
            ttft_p95_ms = registry.get_float("HVT_SERVE_TTFT_P95_MS")
        self.ttft_p95_ms = ttft_p95_ms
        self.streak_windows = streak_windows
        self.down_factor = down_factor
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._count: float | None = None
        self._buckets: dict = {}
        self.up_streak = 0
        self.down_streak = 0
        self._last_action_at: float | None = None
        self.last_p95_ms: float | None = None

    def _bucket_counts(self, series: dict) -> dict:
        prefix = f"{_TTFT_BUCKET}{{le=\""
        out = {}
        for key, value in series.items():
            if key.startswith(prefix):
                le = key[len(prefix):-2]
                out[float("inf") if le == "+Inf" else float(le)] = value
        return out

    def observe(self, series: dict) -> str | None:
        count = series.get(_TTFT_COUNT)
        if count is None or count == self._count:
            return None  # no fresh evidence — not a window
        floor = self._buckets if self._count is not None else None
        self._count = count
        self._buckets = self._bucket_counts(series)
        p95 = histogram_quantile(
            series, "hvt_serve_ttft_seconds", 0.95, window_floor=floor
        )
        if p95 is None:
            return None
        self.last_p95_ms = p95 * 1000.0
        if self.last_p95_ms > self.ttft_p95_ms:
            self.up_streak += 1
            self.down_streak = 0
        elif self.last_p95_ms < self.ttft_p95_ms * self.down_factor:
            self.down_streak += 1
            self.up_streak = 0
        else:
            self.up_streak = self.down_streak = 0
        now = self._clock()
        if (
            self._last_action_at is not None
            and now - self._last_action_at < self.cooldown_s
        ):
            return None  # cooling down; streaks keep accumulating
        if self.up_streak >= self.streak_windows:
            self._last_action_at = now
            self.up_streak = 0
            return "up"
        if self.down_streak >= self.streak_windows:
            self._last_action_at = now
            self.down_streak = 0
            return "down"
        return None
