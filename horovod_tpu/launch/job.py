"""YAML job specs — the `.ps_project/` role, TPU-native.

A spec binds a command, a topology (local nprocs or a host list), env, and
post-run metric checks, mirroring what `distributed-keras-sample.yaml` (the
experiment) + `config.yaml` (the workflow with its checks) express for the
reference. See `horovod_tpu/launch/jobs/mnist-ci.yaml` for the shape.
"""

from __future__ import annotations

import os
import shlex

import yaml

from horovod_tpu.launch import ci_gate, launcher


def validate_spec(spec) -> list:
    """Validate a parsed job spec BEFORE any side effect (fresh-dir wipe,
    metrics reset, process spawn). Returns a list of error strings — empty
    means the spec is launchable. Each supervised block (``restart:``,
    ``elastic:``, ``policy:``) is dry-built through the same
    ``from_mapping`` constructor the launch path uses, so a typo'd key
    fails here with the constructor's own message (which names the bad
    key and the valid set) instead of mid-run."""
    errors: list = []
    if not isinstance(spec, dict):
        return [f"spec must be a mapping, got {type(spec).__name__}"]
    job = spec.get("job")
    if not isinstance(job, dict):
        return [f"job: must be a mapping, got {job!r}"]
    if "serve" in job:
        serve = job["serve"] or {}
        if not isinstance(serve, dict):
            errors.append(f"job serve: must be a mapping, got {serve!r}")
        else:
            unknown = set(serve) - {
                "bundle", "demo", "replicas", "requests", "swap",
                "coalesce", "journal", "port", "host",
            }
            if unknown:
                errors.append(
                    f"job serve: unknown keys {sorted(unknown)}"
                )
            if not (serve.get("demo") or serve.get("bundle")):
                errors.append("job serve: needs bundle: or demo: true")
        if job.get("command"):
            errors.append(
                "job serve: replaces command: — a serve job IS the fleet"
            )
        for key in ("restart", "elastic", "policy"):
            if key in job:
                errors.append(
                    f"job serve: conflicts with {key}: (the fleet "
                    "supervises its own replicas)"
                )
        if "tune" in job:
            errors.append(
                "job serve: conflicts with tune: (the autotuner races "
                "training configs; a serve fleet has none)"
            )
        return errors
    if not job.get("command"):
        errors.append("job command: is required")

    from horovod_tpu.launch import supervisor
    from horovod_tpu.launch import policy as policy_lib

    builders = {
        "restart": lambda m: supervisor.RestartPolicy.from_mapping(
            {k: v for k, v in m.items() if k != "log"}
        ),
        "elastic": supervisor.ElasticPolicy.from_mapping,
        "policy": policy_lib.PolicyConfig.from_mapping,
    }
    for key, build in builders.items():
        if key not in job:
            continue
        block = job[key] or {}
        if not isinstance(block, dict):
            errors.append(f"job {key}: must be a mapping, got {block!r}")
            continue
        try:
            build(block)
        except (TypeError, ValueError) as e:
            errors.append(f"job {key}: {e}")
    if "policy" in job and not ("restart" in job or "elastic" in job):
        errors.append(
            "job policy: needs a supervised launch — add a restart: or "
            "elastic: block (the policy engine lives in the supervisor)"
        )
    # `tune:` — dry-validated through the same constructor-style hook as
    # the supervised blocks, so a typo'd key or a non-tunable knob name
    # fails here, before any probe runs.
    if "tune" in job:
        from horovod_tpu.tune import insitu as tune_insitu

        try:
            tune_insitu.validate_block(job["tune"] or {})
        except tune_insitu.TuneError as e:
            errors.append(f"job tune: {e}")
    return errors


def run_job(spec_path: str) -> int:
    """Execute a job spec: launch, then gate. Returns a shell exit code."""
    with open(spec_path) as f:
        spec = yaml.safe_load(f)

    problems = validate_spec(spec)
    if problems:
        for p in problems:
            print(f"{spec_path}: {p}")
        return 1

    job = spec.get("job", {})
    command = job.get("command")
    argv = (
        command if isinstance(command, list) else shlex.split(command)
    ) if command else []
    env = {str(k): str(v) for k, v in (job.get("env") or {}).items()}

    # `tune:` block — resolve the autotuner BEFORE launching (ISSUE 19):
    #   tune:
    #     mode: probe            # offline | probe | off
    #     # knobs: [HVT_BUCKET_BYTES, HVT_OVERLAP_REDUCTION]
    #     # evidence: .          # BENCH_* evidence dir
    #     # steps: 3             # probe: real steps per timed leg
    #     # candidates: 3        # probe: shortlist size
    #     # store: path          # default <PS_MODEL_PATH>/tune.json
    # The winning config lands in the resolved env (spec-pinned env
    # still wins — an operator's explicit knob is a decision, not a
    # suggestion) and is persisted to the store keyed by a fingerprint,
    # so a RESTART of the same job reuses it instead of re-probing; the
    # journal records tune_selected / tune_reused.
    tune_event = None
    if "tune" in job:
        from horovod_tpu.tune import insitu as tune_insitu

        try:
            tuned_env, tune_event = tune_insitu.resolve(
                job["tune"] or {}, env, workdir=job.get("workdir")
            )
        except tune_insitu.TuneError as e:
            print(f"job tune: {e}")
            return 1
        for name, value in tuned_env.items():
            env.setdefault(name, value)

    def _fresh_journal(lp, model_dir):
        # Every supervised branch resets the journal through here, so
        # the tune event survives the reset into THIS run's journal.
        _reset_journal(lp, model_dir)
        if tune_event and lp:
            from horovod_tpu.launch import supervisor as _sup

            _sup.RestartLog(lp).write(
                tune_event["event"], 1,
                **{k: v for k, v in tune_event.items() if k != "event"}
            )

    checks = spec.get("checks") or {}
    metrics_path = spec.get(
        "metrics",
        os.path.join(env.get("PS_MODEL_PATH", "./models"), "metrics.jsonl"),
    )
    # The sink appends; a leftover stream from a previous run must not feed
    # this run's gate (a regressed run could pass on old values).
    if checks and os.path.exists(metrics_path):
        os.remove(metrics_path)

    hosts = job.get("hosts")
    # `fresh: true`: wipe the job-owned model dir before launching. CI jobs
    # reuse a fixed PS_MODEL_PATH across runs, and the entry scripts resume
    # from the newest checkpoint by design — a gated convergence run must
    # train from scratch, not resume a finished run (which would push no
    # metrics and fail the gate on an empty stream). The wipe happens where
    # the entry script will look: on hosts[0] (the single writer), with a
    # relative path resolved against the job's workdir, exactly like the
    # remote command itself.
    if job.get("fresh"):
        # Entry scripts default to ./models when PS_MODEL_PATH is unset.
        raw = env.get("PS_MODEL_PATH", "./models")
        if hosts:
            target = raw if os.path.isabs(raw) else os.path.join(
                job.get("workdir") or ".", raw
            )
        else:
            target = os.path.abspath(raw)
        norm = os.path.normpath(target)
        if norm in ("/", ".", os.path.expanduser("~")) or (
            os.path.isabs(norm) and norm.count(os.sep) < 2
        ):
            print(f"refusing to wipe suspicious fresh dir {norm}")
            return 1
        if hosts:
            code = _remote_rm(
                hosts[0], norm, recursive=True,
                why="a stale checkpoint would make the run resume instead "
                "of train — refusing to gate",
            )
            if code != 0:
                return code
        else:
            import shutil

            shutil.rmtree(norm, ignore_errors=True)
    if hosts and checks:
        # The local purge above only covered the launcher's filesystem; the
        # sink appends on the coordinator host, so reset it there too. A
        # failed reset is fatal: gating against a possibly-stale stream
        # could PASS a broken run.
        code = _remote_rm(
            hosts[0], metrics_path, recursive=False,
            why="refusing to gate against a possibly-stale stream",
        )
        if code != 0:
            return code
    # `restart:` block — supervised fail-restart launch (supervisor.py):
    #   restart:
    #     max_restarts: 3         # consecutive no-progress budget
    #     backoff: 1.0            # seconds, doubles per no-progress restart
    #     heartbeat_timeout: 300  # omit to disable hang detection
    #     log: path/restarts.jsonl  # default $PS_MODEL_PATH/restarts.jsonl
    # `elastic:` block — elastic rendezvous launch (supervisor.py
    # supervise_elastic + horovod_tpu.elastic):
    #   elastic:
    #     min_ranks: 2            # smallest world to shrink to
    #     max_ranks: 3            # largest world to grow back to
    #     rendezvous_timeout: 60  # seconds a round waits for stragglers
    #     commit_every: 1         # elastic commit cadence, epochs
    #     commit_every_steps: 0   # sub-epoch cadence, optimizer steps
    #                             # (0 = epoch cadence only; commits are
    #                             # accumulation-boundary-aligned)
    #     rescale_every_steps: 0  # sub-epoch MEMBERSHIP agreement cadence,
    #                             # optimizer steps (0 = epoch boundaries
    #                             # only): joins/leaves execute mid-epoch
    #                             # and survivors resume at the committed
    #                             # step (fit initial_step)
    # Composes with `restart:` for the budget/backoff/heartbeat knobs; the
    # journal (restart log) carries the generation-tagged shrink/grow
    # events the gate and /healthz read. A top-level `status_port: N` under
    # job: serves the supervisor's own HTTP status (GET /status, /journal,
    # /healthz — supervisor.start_status_server) for the run's duration.
    # `policy:` block — the supervisor policy engine (launch/policy.py):
    #   policy:
    #     mode: "on"              # off | dry-run | on
    #     straggler_windows: 3    # confirmed windows before eviction
    #     straggler_wait_ms: 100  # min peak barrier wait to count a window
    #     evict_budget: 1         # evictions per run (not restart budget)
    #     cooldown_s: 60          # seconds between policy actions
    #     spares: 0               # warm standbys (elastic: only)
    # Requires a restart:/elastic: block (validated up front); decisions
    # land in the journal as policy_* events and in the metrics dump as
    # hvt_policy_actions_total{action,outcome}.
    log_path = None  # set by the supervised branches; journal_checks needs it
    status_port = int(job["status_port"]) if job.get("status_port") else None
    if status_port is not None and not ("elastic" in job or "restart" in job):
        # Match the CLI, where --status-port without supervision flags
        # errors: the status server is the SUPERVISOR's — an unsupervised
        # launch has nothing to serve, and silently ignoring the key
        # would leave the operator's /healthz probes failing against a
        # job that looks correctly configured.
        print("job status_port: needs a supervised launch — add a "
              "restart: or elastic: block")
        return 1
    pcfg = None
    if "policy" in job:
        from horovod_tpu.launch import policy as policy_lib

        # validate_spec already dry-built this mapping; a failure here
        # would be a programming error, not a user one.
        pcfg = policy_lib.PolicyConfig.from_mapping(job["policy"] or {})
    # `serve:` block — a serving-fleet job (serving/fleet.py): N
    # continuous-batching replicas behind one router, smoke traffic, an
    # optional zero-downtime weight swap mid-load. The fleet journals to
    # the restart-journal grammar and dumps its router registry to
    # metrics.prom at stop, so `journal_checks:` and `metrics_checks:`
    # gate it exactly like a supervised training job:
    #   serve:
    #     demo: true        # self-export a tiny streaming bundle
    #     # bundle: path    # ... or serve this exported bundle
    #     replicas: 2
    #     requests: 40      # drive N requests through the router
    #     swap: true        # weight-swap mid-traffic (demo re-exports)
    #     # journal: path   # default $PS_MODEL_PATH/restarts.jsonl
    if "serve" in job:
        from horovod_tpu.launch import supervisor
        from horovod_tpu.serving import fleet as serve_fleet

        serve = job["serve"] or {}
        log_path = serve.get("journal") or supervisor.default_log_path(env)
        if not log_path:
            print("job serve: needs journal: or env PS_MODEL_PATH "
                  "(the journal is the job's gateable output)")
            return 1
        _fresh_journal(log_path, supervisor.default_model_dir(env))
        # The fleet reads knobs and spawns replica subprocesses from
        # THIS process's environment — a serve job is always local.
        os.environ.update(env)
        serve_argv = ["--replicas", str(serve.get("replicas", 2)),
                      "--journal", log_path,
                      "--port", str(serve.get("port", 0)),
                      "--host", str(serve.get("host", "127.0.0.1"))]
        if serve.get("demo"):
            serve_argv.append("--demo")
        else:
            serve_argv.insert(0, str(serve["bundle"]))
        if serve.get("requests"):
            serve_argv += ["--requests", str(serve["requests"])]
        if serve.get("swap"):
            serve_argv.append("--swap")
        if serve.get("coalesce"):
            serve_argv.append("--coalesce")
        code = serve_fleet.main(serve_argv)
    elif "elastic" in job:
        elastic_map = job["elastic"] or {}
        if not isinstance(elastic_map, dict):
            print(f"job elastic: must be a mapping, got {elastic_map!r}")
            return 1
        from horovod_tpu.launch import supervisor

        elastic = supervisor.ElasticPolicy.from_mapping(elastic_map)
        restart = job.get("restart") or {}
        if not isinstance(restart, dict):
            print(f"job restart: must be a mapping, got {restart!r}")
            return 1
        policy = supervisor.RestartPolicy.from_mapping(
            {k: v for k, v in restart.items() if k != "log"}
        )
        log_path = restart.get("log") or supervisor.default_log_path(env)
        _fresh_journal(log_path, supervisor.default_model_dir(env))
        if hosts:
            code = supervisor.supervise_elastic_hosts(
                list(hosts), argv, env=env, policy=policy, elastic=elastic,
                sync_port_base=int(job.get("coordinator_port", 9981)),
                workdir=job.get("workdir"), log_path=log_path,
                status_port=status_port, policy_config=pcfg,
            )
        else:
            code = supervisor.supervise_elastic(
                int(job.get("nprocs", 1)), argv, env=env, policy=policy,
                elastic=elastic, log_path=log_path,
                status_port=status_port, policy_config=pcfg,
            )
    elif "restart" in job:
        # Key-present-but-empty (`restart:` with every knob commented out)
        # means "supervise with defaults" — matching the CLI, where any
        # supervision flag opts in. Only a mapping (or nothing) is valid;
        # `restart: true` etc. must fail loudly, not run unsupervised.
        restart = job["restart"] or {}
        if not isinstance(restart, dict):
            print(f"job restart: must be a mapping, got {restart!r}")
            return 1
        from horovod_tpu.launch import supervisor

        policy = supervisor.RestartPolicy.from_mapping(
            {k: v for k, v in restart.items() if k != "log"}
        )
        log_path = restart.get("log") or supervisor.default_log_path(env)
        # Same hygiene as the metrics stream above: a previous run's
        # restart journal must not feed this run's log/gate.
        _fresh_journal(log_path, supervisor.default_model_dir(env))
        if hosts:
            code = supervisor.supervise_hosts(
                list(hosts), argv, env=env, policy=policy,
                coordinator_port=int(job.get("coordinator_port", 9981)),
                workdir=job.get("workdir"), log_path=log_path,
                status_port=status_port, policy_config=pcfg,
            )
        else:
            code = supervisor.supervise_local(
                int(job.get("nprocs", 1)), argv, env=env, policy=policy,
                log_path=log_path, status_port=status_port,
                policy_config=pcfg,
            )
    elif hosts:
        code = launcher.run_hosts(
            list(hosts), argv, env=env,
            coordinator_port=int(job.get("coordinator_port", 9981)),
            workdir=job.get("workdir"),
        )
    else:
        code = launcher.run_local(int(job.get("nprocs", 1)), argv, env=env)
    if code != 0:
        print(f"job failed with exit code {code}")
        return code

    # `journal_checks:` — the same {name: {target, aggregate}} grammar as
    # `checks:`, evaluated against the supervisor's restart JOURNAL instead
    # of the metrics stream. This is how an elastic CI job gates its
    # lifecycle in-spec ("the shrink actually happened, nobody gave up"):
    #   journal_checks:
    #     shrink: {target: "1..9", aggregate: count}
    # Requires a supervised launch (restart:/elastic: block) — without one
    # there is no journal and the gate fails loudly rather than
    # vacuously passing.
    journal_checks = spec.get("journal_checks") or {}
    if journal_checks:
        if not log_path:
            print("journal_checks: needs a restart:/elastic: block "
                  "(no restart journal was written)")
            return 1
        if not ci_gate.run_checks(log_path, journal_checks):
            return 1

    # `metrics_checks:` — gate the supervisor's FINAL Prometheus scrape
    # (dumped to <PS_MODEL_PATH>/metrics.prom at teardown — the same
    # series GET /metrics serves live), so the one-pane-of-glass metrics
    # join the journal as gateable job outputs:
    #   metrics_checks:
    #     hvt_committed_step: {target: "1..1000000"}
    #     hvt_restarts_total: {target: "0..0"}
    # Requires a supervised launch (restart:/elastic: block) — the dump
    # is the supervisor's; without one the gate fails loudly.
    metrics_checks = spec.get("metrics_checks") or {}
    if metrics_checks:
        if not log_path:
            print("metrics_checks: needs a restart:/elastic: block "
                  "(no supervisor metrics dump was written)")
            return 1
        from horovod_tpu.launch import supervisor

        prom_path = supervisor.default_metrics_dump_path(
            supervisor.default_model_dir(env), log_path
        )
        if not ci_gate.run_prom_checks(prom_path, metrics_checks):
            return 1

    if not checks:
        return 0
    if hosts:
        # The primary process (rank 0 on hosts[0]) wrote the stream there;
        # without shared storage it must be fetched before gating.
        metrics_path = _fetch_remote_metrics(hosts[0], metrics_path)
    return 0 if ci_gate.run_checks(metrics_path, checks) else 1


def _reset_journal(log_path: str | None, model_dir: str | None = None) -> None:
    """Remove a previous run's restart journal AND its rotated ``.1``
    predecessor — the gate reads across the rotation boundary, so a stale
    predecessor could feed this run's journal checks. The supervisor's
    final metrics dump (``metrics.prom``) gets the same hygiene: a stale
    dump must not feed this run's ``metrics_checks:``."""
    if not log_path:
        return
    from horovod_tpu.launch import supervisor

    paths = [log_path, log_path + ".1"]
    prom = supervisor.default_metrics_dump_path(model_dir, log_path)
    if prom:
        paths.append(prom)
    for p in paths:
        if os.path.exists(p):
            os.remove(p)


def _remote_rm(host: str, path: str, recursive: bool, why: str) -> int:
    """Remove a path on a remote host over ssh; nonzero (with a message) on
    failure — callers treat failure as fatal for gating correctness."""
    import subprocess

    flag = "-rf" if recursive else "-f"
    res = subprocess.run(
        ["ssh", "-o", "StrictHostKeyChecking=no", host,
         f"rm {flag} {shlex.quote(path)}"],
        capture_output=True,
        text=True,
    )
    if res.returncode != 0:
        print(f"cannot remove {path} on {host} ({res.stderr.strip()}); {why}")
        return res.returncode or 1
    return 0


def _fetch_remote_metrics(host: str, remote_path: str) -> str:
    """scp the metrics stream from the coordinator host; on failure fall back
    to the local path (covers the shared-filesystem deployment)."""
    import subprocess
    import tempfile

    local = os.path.join(tempfile.mkdtemp(prefix="hvt-gate-"), "metrics.jsonl")
    res = subprocess.run(
        ["scp", "-o", "StrictHostKeyChecking=no", f"{host}:{remote_path}", local],
        capture_output=True,
    )
    return local if res.returncode == 0 else remote_path
