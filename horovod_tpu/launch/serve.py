"""Minimal HTTP model server — the TF-Serving role over this framework's
serving bundles.

The reference's export tail produces a SavedModel "so that it can be
served by TF Serving" (mnist_keras.py:126-140); this module is the
native half of that story: it serves a StableHLO bundle over HTTP with
no TF anywhere. Two bundle kinds, auto-detected:

* **predict bundles** (`checkpoint.export_serving`) — the reference's
  ``input → prob`` classifier contract;
* **generation bundles** (`serving.export_generate`) — the flagship LM's
  compiled prefill + decode loop, tokenizer riding along.

Endpoints (JSON, shapes follow the exported signature's trailing dims):

* ``GET  /healthz``                → ``{"status": "ok", "bundle": ...}``
  (+ a ``fleet`` section — generation/size/restart/rescale events from the
  supervisor journal — when launched with ``--fleet-journal``)
* ``POST /v1/predict``  body ``{"input": [[...], ...]}``
                                   → ``{"prob": [[...], ...]}``
* ``POST /v1/generate`` body ``{"prompt": [[ids...], ...]}`` or
  ``{"text": ["...", ...]}`` (+ optional ``"seed": N``)
                                   → ``{"tokens": [[ids...], ...]}``
                                     (+ ``"text": [...]`` with a tokenizer)
* ``POST /v1/generate`` with ``"stream": true`` (streaming bundles —
  `serving.export_generate(streaming_chunk=K)`) → ``application/x-ndjson``:
  one ``{"tokens": [[ids...]]}`` line per generated chunk, then a final
  ``{"done": true, "tokens": ..., "text": ...}`` line.

Batching: the exported program is compiled for ONE batch shape (static
shapes are the deal with XLA). Requests of any row count are padded up /
split to the bundle's batch size server-side — and generation prompts of
any length ≤ the compiled prompt_len ride the ragged-lengths path — so
clients never see the static-shape constraint.

Concurrency: one device worker drains a **coalescing queue** — rows from
concurrent requests are packed together into the compiled batch shape, so
N simultaneous single-row clients cost ~ceil(N/batch) device dispatches
instead of N (measured ~batch× requests/sec at saturation; bench.py's
serve row). Handler threads only enqueue and wait; the device callable
never runs re-entrantly. Sampled generation bundles (temperature > 0) are
the exception: each request owns its rng seed for the whole compiled
call, so they serialize per-request through the worker instead of mixing
rows from different seeds (``app.stats['device_calls']`` exposes the
dispatch count either way).

Run:  ``python -m horovod_tpu.launch.serve <bundle_dir> [--port 8000]``
(or `serve_forever(bundle_dir, port)` programmatically; tests use
`make_server` + a background thread).
"""

from __future__ import annotations

import itertools
import json
import queue as queue_lib
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from horovod_tpu.obs import core as obs_core
from horovod_tpu.obs import prom as obs_prom


# Monotone per-process request ids for the serving `request` spans —
# enough to correlate a request's children in a merged timeline.
_request_ids = itertools.count(1)


class _Slot:
    """One request row's rendezvous with the device worker.

    ``started``/``finished`` carry the worker's clocks around the device
    call that served this row — (wall, perf) at dispatch and perf at
    completion — so the submitting handler thread can emit queue-wait /
    decode trace spans for its request (only stamped, and only read,
    when spans are on)."""

    __slots__ = ("event", "value", "error", "started", "finished")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error = None
        self.started = None
        self.finished = None

    def set(self, value):
        self.value = value
        self.event.set()

    def set_err(self, e):
        self.error = e
        self.event.set()

    def get(self):
        self.event.wait()
        if self.error is not None:
            raise self.error
        return self.value


class _Batcher:
    """The coalescing device worker.

    Handler threads `submit` lists of row-items and block; the single
    worker thread drains the queue, packs up to ``batch`` rows — across
    requests — into one device call, and distributes per-row results.
    ``run_rows(items) -> results`` is the only code that touches the
    device, so the compiled callable never runs re-entrantly and the old
    global lock is gone.

    When ``HVT_TRACE_DIR`` is set, the worker stamps each slot with the
    wall/perf clocks around its device call so `submit` can emit
    ``queue_wait`` / ``decode`` child spans for ITS request — the spans
    belong to the handler thread's open ``request`` span, but the
    interval they measure happened on the worker (`trace.emit_span`).
    """

    def __init__(self, run_rows, batch: int, stats: dict):
        self.run_rows = run_rows
        self.batch = batch
        self.stats = stats
        self.q: queue_lib.Queue = queue_lib.Queue()
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def submit(self, items: list) -> list:
        from horovod_tpu import trace as trace_lib

        slots = [_Slot() for _ in items]
        t_sub, p_sub = time.time(), time.perf_counter()
        for it, s in zip(items, slots):
            self.q.put((it, s))
        out = [s.get() for s in slots]
        if trace_lib.span_dir() and slots and slots[0].started is not None:
            started_wall, started_perf = slots[0].started
            done_perf = slots[-1].finished
            trace_lib.emit_span(
                "queue_wait", t_sub, max(0.0, started_perf - p_sub)
            )
            if done_perf is not None:
                trace_lib.emit_span(
                    "decode", started_wall, done_perf - started_perf,
                    rows=len(items),
                )
        return out

    def stop(self):
        """Retire the worker (weight reload rebuilds the batcher —
        the old worker must not keep draining the dead queue)."""
        self.q.put(_Batcher._STOP)

    _STOP = object()

    def _loop(self):
        while True:
            first = self.q.get()
            if first is _Batcher._STOP:
                return
            group = [first]
            while len(group) < self.batch:
                try:
                    item = self.q.get_nowait()
                except queue_lib.Empty:
                    break
                if item is _Batcher._STOP:
                    self.q.put(item)  # honor it after this group
                    break
                group.append(item)
            self.stats["device_calls"] += 1
            self.stats["rows"] += len(group)
            started = (time.time(), time.perf_counter())
            for _, s in group:
                s.started = started
            try:
                results = self.run_rows([it for it, _ in group])
                done = time.perf_counter()
                for (_, s), r in zip(group, results):
                    s.finished = done
                    s.set(r)
            except Exception as e:
                for _, s in group:
                    s.set_err(e)


class _ModelApp:
    """A predict bundle, its static batch size, and the pad/split logic."""

    kind = "predict"

    def __init__(self, bundle_dir: str, coalesce: bool = True):
        from horovod_tpu import checkpoint

        self.bundle_dir = bundle_dir
        self.fn = checkpoint.load_serving(bundle_dir)
        with open(f"{bundle_dir}/{checkpoint.SIGNATURE_FILE}") as f:
            self.signature = json.load(f)["signature"]
        spec = self.signature["inputs"]["input"]
        self.batch = int(spec["shape"][0])
        self.row_shape = tuple(int(d) for d in spec["shape"][1:])
        self.dtype = np.dtype(spec["dtype"])
        self.stats = {"device_calls": 0, "rows": 0}
        # coalesce=False keeps the legacy serialize-whole-requests path —
        # the bench's before/after baseline (bench.py serve row).
        self._lock = None if coalesce else threading.Lock()
        self._batcher = (
            _Batcher(self._run_rows, self.batch, self.stats)
            if coalesce else None
        )

    def _run_rows(self, rows: list) -> list:
        chunk = np.stack(rows)
        n = len(chunk)
        if n < self.batch:  # pad to the compiled shape
            chunk = np.concatenate(
                [chunk, np.repeat(chunk[-1:], self.batch - n, 0)]
            )
        return list(np.asarray(self.fn(chunk))[:n])

    def predict(self, rows: np.ndarray) -> np.ndarray:
        if rows.ndim != 1 + len(self.row_shape) or (
            rows.shape[1:] != self.row_shape
        ):
            raise ValueError(
                f"input rows must be shaped {('N',) + self.row_shape}, "
                f"got {rows.shape}"
            )
        rows = rows.astype(self.dtype)
        if self._batcher is not None:
            return np.stack(self._batcher.submit(list(rows)))
        out = []
        with self._lock:
            for start in range(0, len(rows), self.batch):
                self.stats["device_calls"] += 1
                self.stats["rows"] += len(rows[start : start + self.batch])
                out.append(self._run_rows(list(rows[start : start + self.batch])))
        return np.concatenate([np.stack(o) for o in out])


class _GenerateApp:
    """A generation bundle behind the coalescing worker — or, with
    ``continuous=True``, behind the per-decode-step scheduler
    (`horovod_tpu.serving.engine.ContinuousBatchingEngine`).

    Coalescing (the default): greedy bundles (temperature == 0: the rng
    is dead code in the exported program) coalesce rows across concurrent
    requests exactly like predict bundles; sampled bundles serialize
    whole requests. Continuous (streaming bundles only): every request
    row is an independently scheduled sequence — admitted into free
    decode capacity mid-flight, retired the chunk it finishes, refused
    with 429 (`AdmissionError`) when the paged-KV wait queue is full.
    Sized by the ``HVT_SERVE_MAX_SEQS`` / ``HVT_SERVE_BLOCK_TOKENS`` /
    ``HVT_SERVE_KV_BLOCKS`` / ``HVT_SERVE_QUEUE_DEPTH`` knobs.
    """

    kind = "generate"
    # Class-level defaults so partially-constructed instances (tests
    # stub the app without running _load) take the legacy path.
    engine = None
    continuous = False

    def __init__(self, bundle_dir: str, coalesce: bool = True,
                 continuous: bool = False):
        self.continuous = continuous
        self._coalesce = coalesce
        self._lock = threading.Lock()
        self._load(bundle_dir)

    def _load(self, bundle_dir: str) -> None:
        """(Re)build the app around ``bundle_dir`` — the boot path AND
        the ``/admin/reload`` weight-swap target."""
        from horovod_tpu import serving

        self.bundle_dir = bundle_dir
        self.bundle = serving.load_generate(bundle_dir)
        self.signature = {
            "inputs": {
                "prompt": {
                    "shape": [self.bundle.batch_size, self.bundle.prompt_len],
                    "dtype": "int32",
                }
            },
            "outputs": {"tokens": {}},
            "meta": self.bundle.meta,
        }
        self.stats = {"device_calls": 0, "rows": 0}
        if getattr(self, "_batcher", None) is not None:
            self._batcher.stop()  # reload: retire the old worker
        if self.continuous:
            from horovod_tpu.analysis import registry as knobs
            from horovod_tpu.serving.engine import ContinuousBatchingEngine

            self.engine = ContinuousBatchingEngine(
                self.bundle,
                max_seqs=knobs.get_int("HVT_SERVE_MAX_SEQS"),
                block_tokens=knobs.get_int("HVT_SERVE_BLOCK_TOKENS"),
                kv_blocks=knobs.get_int("HVT_SERVE_KV_BLOCKS"),
                queue_depth=knobs.get_int("HVT_SERVE_QUEUE_DEPTH"),
            )
            self._batcher = None
            return
        self.engine = None
        greedy = float(self.bundle.meta.get("temperature", 0.0)) == 0.0
        # The batcher's dispatches take the SAME lock the sampled and
        # streaming paths use, so the compiled programs never run
        # re-entrantly whatever mix of request kinds is in flight.
        self._batcher = (
            _Batcher(
                self._locked_generate_batch,
                self.bundle.batch_size,
                self.stats,
            )
            if (self._coalesce and greedy) else None
        )

    def reload(self, bundle_dir: str) -> None:
        """Swap weights in place: drain the engine (continuous) or hold
        the device lock (coalescing) while the new bundle loads. The
        fleet drains this replica at the ROUTER first, so by the time
        reload arrives nothing should be in flight — the engine drain
        here is the belt to that suspender."""
        from horovod_tpu.analysis import registry as knobs

        if self.engine is not None:
            timeout = knobs.get_float("HVT_SERVE_DRAIN_TIMEOUT_S")
            if not self.engine.drain(timeout):
                raise RuntimeError(
                    f"engine still busy after {timeout}s drain — refusing "
                    "to swap weights under live sequences"
                )
            self.engine.stop()
            self._load(bundle_dir)
            return
        with self._lock:
            # Coalescing path: the lock serializes against every
            # dispatch; requests queued behind it resume on new weights.
            self._load(bundle_dir)

    def _locked_generate_batch(self, rows: list) -> list:
        with self._lock:
            return self.bundle.generate_batch(rows)

    def _payload_prompts(self, payload: dict):
        if "text" in payload and "prompt" in payload:
            raise ValueError("pass 'text' OR 'prompt', not both")
        if "text" in payload:
            texts = payload["text"]
            if not isinstance(texts, list):
                raise ValueError("'text' must be a list of strings")
            if self.bundle.tokenizer is None:
                raise ValueError(
                    "this bundle has no tokenizer — POST token ids "
                    "under 'prompt' instead"
                )
            return [self.bundle.tokenizer.encode(t) for t in texts]
        return payload["prompt"]

    def stream(self, payload: dict):
        """NDJSON streaming: one ``{"tokens": [[...]]}`` line per chunk,
        then a final ``{"done": true, ...}`` line (with the detokenized
        text when the bundle carries a tokenizer). The device lock is
        taken PER DISPATCH — the carried state is self-contained, so
        while one stream's client drains a chunk over the network, other
        requests' device calls interleave instead of queueing behind a
        slow reader."""
        from horovod_tpu import trace as trace_lib

        seed = int(payload.get("seed", 0))
        # Validate BEFORE any slot/lock/submit: a request that can never
        # run must be rejected at the door, not after it holds device
        # capacity (the head-of-line accounting fix — previously the
        # first dispatch validated inside the device lock).
        prompts = self.bundle.validate_prompts(
            self._payload_prompts(payload)
        )
        if not prompts:
            raise ValueError("need at least one prompt")
        if self.engine is not None:
            yield from self._engine_stream(prompts)
            return
        if len(prompts) > self.bundle.batch_size:
            raise ValueError(
                f"streaming takes 1..{self.bundle.batch_size} prompts "
                f"per request, got {len(prompts)}"
            )
        rows = [[] for _ in prompts]
        it = self.bundle.stream_chunks(prompts, seed=seed)
        while True:
            t_q, p_q = time.time(), time.perf_counter()
            with self._lock:
                # Per-dispatch queue-wait/decode child spans: the
                # request span around the whole stream plus the FIRST
                # decode child's end is TTFT as span structure.
                trace_lib.emit_span(
                    "queue_wait", t_q, time.perf_counter() - p_q
                )
                try:
                    with trace_lib.span("decode", rows=len(prompts)):
                        chunk = next(it)
                except StopIteration:
                    break
                self.stats["device_calls"] += 1
            for i, part in enumerate(chunk):
                rows[i].extend(part)
            yield {"tokens": chunk}
        self.stats["rows"] += len(prompts)
        trimmed = [self.bundle._trim(np.asarray(r)) for r in rows]
        final = {"done": True, "tokens": trimmed}
        if self.bundle.tokenizer is not None:
            final["text"] = [
                self.bundle.tokenizer.decode(g) for g in trimmed
            ]
        yield final

    def _engine_stream(self, prompts: list):
        """Continuous streaming: each prompt row is its own scheduled
        sequence. Single-row requests keep the legacy NDJSON schema
        exactly; multi-row requests tag each chunk line with its
        ``row`` (rows finish independently under the scheduler, so
        chunks cannot be zipped across rows the way one compiled
        dispatch used to guarantee)."""
        reqs = [self.engine.submit(p, stream=True) for p in prompts]
        multi = len(reqs) > 1
        for i, r in enumerate(reqs):
            for piece in r.iter_chunks():
                line = {"tokens": [piece]}
                if multi:
                    line["row"] = i
                yield line
        self.stats["rows"] += len(prompts)
        trimmed = [r.tokens for r in reqs]
        final = {"done": True, "tokens": trimmed}
        if self.bundle.tokenizer is not None:
            final["text"] = [
                self.bundle.tokenizer.decode(g) for g in trimmed
            ]
        yield final

    def generate(self, payload: dict) -> dict:
        from horovod_tpu import trace as trace_lib

        seed = int(payload.get("seed", 0))
        # Tokenize and validate OUTSIDE the lock — only the compiled
        # call needs serializing through the device, and a request that
        # fails validation must be 400'd BEFORE it occupies a batch slot
        # or bumps the dispatch accounting (the head-of-line fix: the
        # sampled path used to count device_calls/rows and take the
        # device lock first, then discover the prompts were invalid).
        prompts = self.bundle.validate_prompts(
            self._payload_prompts(payload)
        )
        if self.engine is not None:
            # Continuous scheduling: every row an independent sequence;
            # the engine owns dispatch accounting and trace spans.
            reqs = [self.engine.submit(p) for p in prompts]
            tokens = [r.result() for r in reqs]
            self.stats["rows"] += len(prompts)
        elif self._batcher is not None:
            # Rows coalesce across requests (greedy: the seed is dead
            # code in the program). The batcher emits this request's
            # queue_wait/decode spans.
            tokens = self._batcher.submit(prompts) if prompts else []
        else:
            t_q, p_q = time.time(), time.perf_counter()
            with self._lock:
                # Lock wait IS the sampled path's queue: requests
                # serialize whole through the device here.
                trace_lib.emit_span(
                    "queue_wait", t_q, time.perf_counter() - p_q
                )
                self.stats["device_calls"] += max(
                    1, -(-len(prompts) // self.bundle.batch_size)
                )
                self.stats["rows"] += len(prompts)
                with trace_lib.span("decode", rows=len(prompts)):
                    tokens = self.bundle.generate_tokens(
                        prompts, seed=seed
                    )
        out = {"tokens": tokens}
        if self.bundle.tokenizer is not None:
            out["text"] = [self.bundle.tokenizer.decode(g) for g in tokens]
        return out


def _make_app(bundle_dir: str, coalesce: bool = True,
              continuous: bool = False):
    from horovod_tpu import serving

    if serving.is_generate_bundle(bundle_dir):
        return _GenerateApp(bundle_dir, coalesce=coalesce,
                            continuous=continuous)
    if continuous:
        raise ValueError(
            "continuous batching serves generation bundles only — "
            f"{bundle_dir} is a predict bundle"
        )
    return _ModelApp(bundle_dir, coalesce=coalesce)


def make_server(bundle_dir: str, port: int = 0, host: str = "127.0.0.1",
                coalesce: bool = True, fleet_journal: str | None = None,
                continuous: bool = False, allow_reload: bool = False):
    """Build (but don't start) the HTTP server; ``server.server_address``
    carries the bound port when ``port=0``. ``coalesce=False`` keeps the
    legacy serialize-whole-requests path (the bench baseline);
    ``continuous=True`` routes /v1/generate through the per-decode-step
    scheduler (streaming bundles only; full admissions answer 429).
    ``allow_reload=True`` mounts ``POST /admin/reload`` (the fleet's
    zero-downtime weight-swap hook — opt-in, because it lets any client
    point the server at a new bundle path).

    ``fleet_journal``: path to a supervisor restart/rescale journal
    (``restarts.jsonl``); when given, ``GET /healthz`` grows a ``fleet``
    section — current generation/size, restart/shrink/grow counts, last
    events — read fresh per request (`supervisor.fleet_status`), so a
    health probe sees training-fleet trouble from the serving side.

    ``GET /metrics`` serves the Prometheus text exposition of this
    server's OWN registry (one private `obs.Registry` per server, so
    several servers in one process never share instruments): request
    counts by route/code, queue depth (sampled at scrape), device-call /
    row totals, request-latency and TTFT/TPOT histograms."""
    app = _make_app(bundle_dir, coalesce=coalesce, continuous=continuous)
    reg = obs_core.Registry()

    def _collect(r):
        # stats/queue are owned by the app; the scrape mirrors them.
        engine = getattr(app, "engine", None)
        if engine is not None:
            s = engine.stats()
            r.counter_set(
                "hvt_serve_device_calls_total", s["device_calls_total"]
            )
            r.counter_set("hvt_serve_rows_total", app.stats["rows"])
            r.counter_set("hvt_serve_admitted_total", s["admitted_total"])
            r.counter_set("hvt_serve_retired_total", s["retired_total"])
            r.counter_set("hvt_serve_rejected_total", s["rejected_total"])
            r.gauge("hvt_serve_live_seqs", s["live_seqs"])
            r.gauge("hvt_serve_queue_depth", s["queue_depth"])
            r.gauge("hvt_serve_kv_blocks_used", s["kv_blocks_used"])
            r.gauge("hvt_serve_kv_blocks_free", s["kv_blocks_free"])
            return
        r.counter_set(
            "hvt_serve_device_calls_total", app.stats["device_calls"]
        )
        r.counter_set("hvt_serve_rows_total", app.stats["rows"])
        batcher = getattr(app, "_batcher", None)
        r.gauge(
            "hvt_serve_queue_depth",
            batcher.q.qsize() if batcher is not None else 0,
        )

    reg.register_collector(_collect)

    # The `route` label must come from a CLOSED set: serve_forever binds
    # 0.0.0.0 by default, and labeling by the raw client-supplied path
    # would let any scanner mint unbounded (route, code) series — a
    # memory leak and scrape-payload blowup driven by untrusted input.
    _KNOWN_ROUTES = ("/healthz", "/metrics", "/v1/predict", "/v1/generate",
                     "/admin/reload")
    inflight = {"n": 0}
    inflight_lock = threading.Lock()

    def _route(path: str) -> str:
        path = path.split("?", 1)[0]
        return path if path in _KNOWN_ROUTES else "other"

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            reg.counter(
                "hvt_serve_requests_total", route=_route(self.path),
                code=str(code),
            )

        def log_message(self, *args):  # quiet: one line per request is noise
            pass

        def do_GET(self):
            if self.path == "/metrics":
                obs_prom.write_http(self, reg)
            elif self.path == "/healthz":
                with inflight_lock:
                    n_inflight = inflight["n"]
                payload = {"status": "ok", "bundle": app.bundle_dir,
                           "kind": app.kind, "signature": app.signature,
                           "stats": dict(app.stats),
                           "inflight": n_inflight}
                engine = getattr(app, "engine", None)
                if engine is not None:
                    payload["scheduler"] = engine.stats()
                if fleet_journal is not None:
                    from horovod_tpu.launch.supervisor import fleet_status

                    payload["fleet"] = fleet_status(fleet_journal)
                self._send(200, payload)
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path == "/admin/reload":
                self._handle_reload()
                return
            route = (app.kind, self.path)
            if route not in (
                ("predict", "/v1/predict"), ("generate", "/v1/generate")
            ):
                hint = (
                    f"this server holds a {app.kind} bundle; its route is "
                    f"/v1/{app.kind}"
                )
                self._send(404, {"error": f"no route {self.path} — {hint}"})
                return
            # One `request` span per POST (HVT_TRACE_DIR runs): the app
            # layer nests queue_wait + decode children under it, so
            # `hvt-trace timeline` shows the serving tier's TTFT as span
            # structure (request start -> first decode child end), not
            # just histograms.
            from horovod_tpu import trace as trace_lib

            with inflight_lock:
                inflight["n"] += 1
            try:
                with trace_lib.span(
                    "request", req=next(_request_ids),
                    route=_route(self.path),
                ):
                    self._handle_post()
            finally:
                with inflight_lock:
                    inflight["n"] -= 1

        def _handle_reload(self):
            """The fleet's weight-swap hook: swap to a new bundle dir in
            place. Opt-in (``allow_reload``) and mutually journaled by
            the caller — the server itself only validates and swaps."""
            if not allow_reload:
                self._send(
                    404, {"error": "reload not enabled on this server "
                          "(--allow-reload)"}
                )
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length))
                new_dir = payload["bundle_dir"]
                if not hasattr(app, "reload"):
                    raise ValueError(
                        f"{app.kind} bundles do not support reload"
                    )
                app.reload(new_dir)
                self._send(200, {"ok": True, "bundle": new_dir})
            except (KeyError, ValueError, TypeError) as e:
                self._send(400, {"error": str(e)})
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        def _handle_post(self):
            t0 = time.perf_counter()
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length))
                if app.kind == "generate" and payload.get("stream"):
                    # NDJSON streaming: no Content-Length; the body is
                    # line-delimited JSON chunks, connection-close
                    # terminated (HTTP/1.0 semantics of this server).
                    chunks = app.stream(payload)
                    first = next(chunks)  # validation runs BEFORE headers
                    # TTFT: first chunk computed and about to flush —
                    # the streaming definition (prefill + first decode
                    # chunk); later chunks feed the TPOT tail below.
                    ttft = time.perf_counter() - t0
                    reg.histogram("hvt_serve_ttft_seconds", ttft)
                    n_tokens = 0
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/x-ndjson"
                    )
                    self.end_headers()
                    reg.counter(
                        "hvt_serve_requests_total", route=_route(self.path),
                        code="200",
                    )
                    try:
                        for item in itertools.chain((first,), chunks):
                            if "tokens" in item and not item.get("done"):
                                n_tokens += sum(
                                    len(r) for r in item["tokens"]
                                )
                            self.wfile.write(
                                json.dumps(item).encode() + b"\n"
                            )
                            self.wfile.flush()
                        total = time.perf_counter() - t0
                        reg.histogram(
                            "hvt_serve_request_seconds", total,
                            route=_route(self.path),
                        )
                        if n_tokens > 1:
                            # Decode tail per token, past the first chunk.
                            reg.histogram(
                                "hvt_serve_tpot_seconds",
                                (total - ttft) / max(1, n_tokens - 1),
                            )
                    except Exception as e:
                        # Headers are out — a second status line would
                        # corrupt the body. Keep the errors-are-JSON
                        # contract with an error NDJSON line; the missing
                        # 'done' line tells the client the stream died.
                        self.wfile.write(
                            json.dumps(
                                {"error": f"{type(e).__name__}: {e}"}
                            ).encode() + b"\n"
                        )
                        self.wfile.flush()
                elif app.kind == "generate":
                    out = app.generate(payload)
                    dt = time.perf_counter() - t0
                    reg.histogram(
                        "hvt_serve_request_seconds", dt, route=_route(self.path)
                    )
                    # One-shot generation is a single dispatch: prefill
                    # and every decode step land together, so TTFT is
                    # the whole call and TPOT its per-token amortization
                    # (documented approximation; streaming requests
                    # carry the real split).
                    n_tokens = sum(len(r) for r in out.get("tokens", []))
                    reg.histogram("hvt_serve_ttft_seconds", dt)
                    if n_tokens:
                        reg.histogram(
                            "hvt_serve_tpot_seconds", dt / n_tokens
                        )
                    self._send(200, out)
                else:
                    rows = np.asarray(payload["input"])
                    prob = app.predict(rows)
                    reg.histogram(
                        "hvt_serve_request_seconds",
                        time.perf_counter() - t0, route=_route(self.path),
                    )
                    self._send(200, {"prob": prob.tolist()})
            except (KeyError, ValueError, TypeError) as e:
                self._send(400, {"error": str(e)})
            except Exception as e:  # device/runtime failures -> 5xx JSON,
                # never a dropped socket (the module's errors-are-JSON
                # contract; XlaRuntimeError does not subclass ValueError).
                from horovod_tpu.serving import engine as engine_mod

                if isinstance(e, engine_mod.AdmissionError):
                    # Admission refused (wait queue full behind the paged
                    # KV budget) is back-pressure, not failure: 429 tells
                    # the client to retry later, and keeps the zero-500s
                    # CI gate honest about actual server faults.
                    self._send(429, {"error": str(e)})
                else:
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

    server = ThreadingHTTPServer((host, port), Handler)
    server.app = app  # tests reach the model through the server handle
    server.metrics_registry = reg  # tests + the --metrics-port exporter

    def _inflight_count() -> int:
        with inflight_lock:
            return inflight["n"]

    server.inflight_count = _inflight_count  # the SIGTERM drain barrier
    return server


def _join_fleet(coordinator: str, member: str, stop: threading.Event):
    """Replica-side membership: sync into the rendezvous coordinator,
    then heartbeat until told to stop. Returns the `ElasticClient` so
    the SIGTERM path can send an explicit, journaled `leave` (the fleet
    watchdog treats leave/dead as the drain trigger)."""
    from horovod_tpu.elastic.coordinator import ElasticClient

    client = ElasticClient(coordinator, member)

    def _beat_loop():
        try:
            client.sync()  # blocks until the rendezvous admits us
        except Exception:
            return  # coordinator gone before we joined — nothing to beat
        while not stop.wait(1.0):
            try:
                client.beat()
                if client.last_beat_pending:
                    # A new generation formed (peer joined/left) — re-sync
                    # so the coordinator's ledger keeps us 'live'.
                    client.sync()
            except Exception:
                return  # coordinator gone; the fleet owns that story
    threading.Thread(target=_beat_loop, daemon=True).start()
    return client


def serve_forever(bundle_dir: str, port: int = 8000, host: str = "0.0.0.0",
                  fleet_journal: str | None = None,
                  metrics_port: int | None = None,
                  continuous: bool = False, allow_reload: bool = False,
                  coordinator: str | None = None,
                  member: str | None = None):
    import signal

    from horovod_tpu.analysis import registry as knobs

    server = make_server(bundle_dir, port=port, host=host,
                         fleet_journal=fleet_journal,
                         continuous=continuous, allow_reload=allow_reload)
    if metrics_port is not None:
        # The same per-server registry on a dedicated scrape port, for
        # deployments that keep the serving port client-facing and the
        # metrics port on the ops network (`/metrics` stays mounted on
        # the main port either way).
        from horovod_tpu.obs import server as obs_server

        obs_server.start_metrics_server(
            metrics_port, registry=server.metrics_registry
        )
    stop_beats = threading.Event()
    client = (
        _join_fleet(coordinator, member or f"serve-{port}", stop_beats)
        if coordinator else None
    )

    def _graceful(_signum, _frame):
        """SIGTERM = drain-then-exit: announce departure to the
        coordinator FIRST (the router stops dispatching here), finish
        what is already in flight, then stop accepting. Runs the
        shutdown from a helper thread — signal handlers run on the main
        thread, which is inside serve_forever()."""
        def _drain_and_stop():
            stop_beats.set()
            if client is not None:
                try:
                    client.leave()
                except Exception:
                    pass
            deadline = time.monotonic() + knobs.get_float(
                "HVT_SERVE_DRAIN_TIMEOUT_S"
            )
            while server.inflight_count() and time.monotonic() < deadline:
                time.sleep(0.05)
            engine = getattr(server.app, "engine", None)
            if engine is not None:
                engine.drain(max(0.0, deadline - time.monotonic()))
                engine.stop()
            server.shutdown()
        threading.Thread(target=_drain_and_stop, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    inputs = server.app.signature["inputs"]
    shape = next(iter(inputs.values()))["shape"]
    print(
        f"serving {bundle_dir} ({server.app.kind}) on "
        f"http://{host}:{server.server_address[1]} (input {shape})"
        + (" [continuous]" if continuous else ""),
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    finally:
        stop_beats.set()


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "bundle_dir",
        help="a serving bundle dir: checkpoint.export_serving (predict) "
        "or serving.export_generate (generation) — kind auto-detected",
    )
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument(
        "--fleet-journal", default=None, metavar="PATH",
        help="supervisor restart/rescale journal (restarts.jsonl); adds a "
        "'fleet' section to GET /healthz — generation, size, "
        "restart/shrink/grow counts, recent events",
    )
    p.add_argument(
        "--metrics-port", type=int, default=None, metavar="N",
        help="ALSO serve this server's Prometheus /metrics on a "
        "dedicated port (loopback by default, HVT_STATUS_HOST to "
        "expose); GET /metrics on the main port works regardless",
    )
    p.add_argument(
        "--continuous", action="store_true",
        help="per-decode-step continuous batching (streaming generation "
        "bundles only): admit/evict at every decode chunk, paged-KV "
        "admission control, 429 on exhaustion",
    )
    p.add_argument(
        "--allow-reload", action="store_true",
        help="mount POST /admin/reload (zero-downtime weight swap; the "
        "fleet drives it during `hvt-launch serve` swaps)",
    )
    p.add_argument(
        "--coordinator", default=None, metavar="HOST:PORT",
        help="rendezvous coordinator address: join the serving fleet as "
        "a member (heartbeats + journaled leave on SIGTERM)",
    )
    p.add_argument(
        "--member", default=None, metavar="NAME",
        help="member name to present to the coordinator "
        "(default serve-<port>)",
    )
    args = p.parse_args(argv)
    serve_forever(args.bundle_dir, port=args.port, host=args.host,
                  fleet_journal=args.fleet_journal,
                  metrics_port=args.metrics_port,
                  continuous=args.continuous,
                  allow_reload=args.allow_reload,
                  coordinator=args.coordinator, member=args.member)


if __name__ == "__main__":
    main()
