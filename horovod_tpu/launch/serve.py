"""Minimal HTTP model server — the TF-Serving role over this framework's
serving bundles.

The reference's export tail produces a SavedModel "so that it can be
served by TF Serving" (mnist_keras.py:126-140); this module is the
native half of that story: it serves a StableHLO bundle over HTTP with
no TF anywhere. Two bundle kinds, auto-detected:

* **predict bundles** (`checkpoint.export_serving`) — the reference's
  ``input → prob`` classifier contract;
* **generation bundles** (`serving.export_generate`) — the flagship LM's
  compiled prefill + decode loop, tokenizer riding along.

Endpoints (JSON, shapes follow the exported signature's trailing dims):

* ``GET  /healthz``                → ``{"status": "ok", "bundle": ...}``
* ``POST /v1/predict``  body ``{"input": [[...], ...]}``
                                   → ``{"prob": [[...], ...]}``
* ``POST /v1/generate`` body ``{"prompt": [[ids...], ...]}`` or
  ``{"text": ["...", ...]}`` (+ optional ``"seed": N``)
                                   → ``{"tokens": [[ids...], ...]}``
                                     (+ ``"text": [...]`` with a tokenizer)

Batching: the exported program is compiled for ONE batch shape (static
shapes are the deal with XLA). Requests of any row count are padded up /
split to the bundle's batch size server-side — and generation prompts of
any length ≤ the compiled prompt_len ride the ragged-lengths path — so
clients never see the static-shape constraint. The compiled callable is locked — requests
serialize through the device; concurrency comes from the accelerator
being fast, not from re-entrancy.

Run:  ``python -m horovod_tpu.launch.serve <bundle_dir> [--port 8000]``
(or `serve_forever(bundle_dir, port)` programmatically; tests use
`make_server` + a background thread).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np


class _ModelApp:
    """A predict bundle, its static batch size, and the pad/split logic."""

    kind = "predict"

    def __init__(self, bundle_dir: str):
        from horovod_tpu import checkpoint

        self.bundle_dir = bundle_dir
        self.fn = checkpoint.load_serving(bundle_dir)
        with open(f"{bundle_dir}/{checkpoint.SIGNATURE_FILE}") as f:
            self.signature = json.load(f)["signature"]
        spec = self.signature["inputs"]["input"]
        self.batch = int(spec["shape"][0])
        self.row_shape = tuple(int(d) for d in spec["shape"][1:])
        self.dtype = np.dtype(spec["dtype"])
        self._lock = threading.Lock()

    def predict(self, rows: np.ndarray) -> np.ndarray:
        if rows.ndim != 1 + len(self.row_shape) or (
            rows.shape[1:] != self.row_shape
        ):
            raise ValueError(
                f"input rows must be shaped {('N',) + self.row_shape}, "
                f"got {rows.shape}"
            )
        rows = rows.astype(self.dtype)
        out = []
        with self._lock:
            for start in range(0, len(rows), self.batch):
                chunk = rows[start : start + self.batch]
                n = len(chunk)
                if n < self.batch:  # pad to the compiled shape
                    chunk = np.concatenate(
                        [chunk, np.repeat(chunk[-1:], self.batch - n, 0)]
                    )
                out.append(np.asarray(self.fn(chunk))[:n])
        return np.concatenate(out)


class _GenerateApp:
    """A generation bundle behind the same lock discipline."""

    kind = "generate"

    def __init__(self, bundle_dir: str):
        from horovod_tpu import serving

        self.bundle_dir = bundle_dir
        self.bundle = serving.load_generate(bundle_dir)
        self.signature = {
            "inputs": {
                "prompt": {
                    "shape": [self.bundle.batch_size, self.bundle.prompt_len],
                    "dtype": "int32",
                }
            },
            "outputs": {"tokens": {}},
            "meta": self.bundle.meta,
        }
        self._lock = threading.Lock()

    def generate(self, payload: dict) -> dict:
        seed = int(payload.get("seed", 0))
        if "text" in payload and "prompt" in payload:
            raise ValueError("pass 'text' OR 'prompt', not both")
        # Tokenize OUTSIDE the lock — only the compiled call needs
        # serializing through the device; CPU encode/decode of one request
        # must not block another's device run.
        if "text" in payload:
            texts = payload["text"]
            if not isinstance(texts, list):
                raise ValueError("'text' must be a list of strings")
            if self.bundle.tokenizer is None:
                raise ValueError(
                    "this bundle has no tokenizer — POST token ids "
                    "under 'prompt' instead"
                )
            prompts = [self.bundle.tokenizer.encode(t) for t in texts]
        else:
            prompts = payload["prompt"]
        with self._lock:
            tokens = self.bundle.generate_tokens(prompts, seed=seed)
        out = {"tokens": tokens}
        if self.bundle.tokenizer is not None:
            out["text"] = [self.bundle.tokenizer.decode(g) for g in tokens]
        return out


def _make_app(bundle_dir: str):
    from horovod_tpu import serving

    if serving.is_generate_bundle(bundle_dir):
        return _GenerateApp(bundle_dir)
    return _ModelApp(bundle_dir)


def make_server(bundle_dir: str, port: int = 0, host: str = "127.0.0.1"):
    """Build (but don't start) the HTTP server; ``server.server_address``
    carries the bound port when ``port=0``."""
    app = _make_app(bundle_dir)

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet: one line per request is noise
            pass

        def do_GET(self):
            if self.path == "/healthz":
                self._send(
                    200, {"status": "ok", "bundle": app.bundle_dir,
                          "kind": app.kind, "signature": app.signature}
                )
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            route = (app.kind, self.path)
            if route not in (
                ("predict", "/v1/predict"), ("generate", "/v1/generate")
            ):
                hint = (
                    f"this server holds a {app.kind} bundle; its route is "
                    f"/v1/{app.kind}"
                )
                self._send(404, {"error": f"no route {self.path} — {hint}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length))
                if app.kind == "generate":
                    self._send(200, app.generate(payload))
                else:
                    rows = np.asarray(payload["input"])
                    prob = app.predict(rows)
                    self._send(200, {"prob": prob.tolist()})
            except (KeyError, ValueError, TypeError) as e:
                self._send(400, {"error": str(e)})
            except Exception as e:  # device/runtime failures -> 5xx JSON,
                # never a dropped socket (the module's errors-are-JSON
                # contract; XlaRuntimeError does not subclass ValueError).
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

    server = ThreadingHTTPServer((host, port), Handler)
    server.app = app  # tests reach the model through the server handle
    return server


def serve_forever(bundle_dir: str, port: int = 8000, host: str = "0.0.0.0"):
    server = make_server(bundle_dir, port=port, host=host)
    inputs = server.app.signature["inputs"]
    shape = next(iter(inputs.values()))["shape"]
    print(
        f"serving {bundle_dir} ({server.app.kind}) on "
        f"http://{host}:{server.server_address[1]} (input {shape})",
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "bundle_dir",
        help="a serving bundle dir: checkpoint.export_serving (predict) "
        "or serving.export_generate (generation) — kind auto-detected",
    )
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--host", default="0.0.0.0")
    args = p.parse_args(argv)
    serve_forever(args.bundle_dir, port=args.port, host=args.host)


if __name__ == "__main__":
    main()
