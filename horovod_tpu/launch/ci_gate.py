"""CI convergence gate — the Gradient workflow's ``checks`` block, natively.

Reference semantics (config.yaml:8-11): after the multinode run, aggregate a
named metric stream (``tensorflow:loss``, ``aggregate: mean``) and require it
inside ``target: "0.0..0.3"``. Here the stream is the `horovod_tpu.metrics`
JSONL file and the target grammar is the same ``lo..hi`` string.
"""

from __future__ import annotations

import json
import os


def parse_target(target: str) -> tuple[float, float]:
    """Parse the reference's range grammar: ``"0.0..0.3"`` → (0.0, 0.3)."""
    lo, hi = target.split("..")
    return float(lo), float(hi)


def read_metric(path: str, name: str, job: str | None = None) -> list[float]:
    """All values of ``name`` in the stream, in write order. Reads a
    rotated ``.1`` predecessor (the supervisor's `RestartLog` rotation)
    before the live file, so count/last aggregates see the full window
    across the rotation boundary.

    ``job``: restrict to records whose ``job`` field equals it — the
    multi-job scoping for fleet journals (`hvt-launch fleet` tags every
    placement/preempt/regrow record with the job it concerns). ``None``
    keeps the classic single-job semantics: every record of ``name``
    counts, tagged or not."""
    values = []
    for part in (path + ".1", path):
        if not os.path.exists(part):
            continue
        with open(part) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    # A torn final line (writer killed mid-append, or a
                    # reader racing the appender) must not crash the gate —
                    # the fail-on-empty-stream semantics still hold below.
                    continue
                if rec.get("name") != name:
                    continue
                if job is not None and rec.get("job") != job:
                    continue
                values.append(float(rec["value"]))
    return values


def aggregate(values: list[float], how: str = "mean") -> float:
    if how == "count":
        # Number of records, not their values — the restart-log check
        # ("exactly one restart recorded": restarts=1..1, aggregate count;
        # the supervisor's JSONL records are metric-shaped for this).
        return float(len(values))
    if not values:
        raise ValueError("no values to aggregate")
    if how == "mean":
        return sum(values) / len(values)
    if how == "last":
        return values[-1]
    if how == "min":
        return min(values)
    if how == "max":
        return max(values)
    raise ValueError(f"unknown aggregate {how!r}")


def check_metrics(
    path: str,
    name: str,
    target: tuple[float, float],
    how: str = "mean",
    job: str | None = None,
) -> tuple[bool, float]:
    """Return (passed, aggregated value). Missing metric — or a missing
    metrics file entirely — fails the gate rather than crashing it (a run
    that logged nothing must not pass)."""
    if not os.path.exists(path) and not os.path.exists(path + ".1"):
        # A missing stream file always fails — for every aggregate: a run
        # that wrote nothing (or a typo'd path) must not pass any check.
        # (A rotated-away live file with a `.1` predecessor still counts
        # as present: the stream exists, its newest window is just empty.)
        return False, float("nan")
    values = read_metric(path, name, job=job)
    if not values and how != "count":
        # count is the exception *for an existing file*: zero matching
        # records is a legitimate answer (e.g. asserting a supervised run
        # needed no restarts — the journal exists, no restart lines).
        return False, float("nan")
    value = aggregate(values, how)
    lo, hi = target
    return lo <= value <= hi, value


def run_prom_checks(prom_path: str, checks: dict) -> bool:
    """Evaluate a ``{series: {target}}`` block against a Prometheus text
    exposition dump (the supervisor's final scrape,
    `supervisor.dump_metrics`) — the job-spec ``metrics_checks:`` gate,
    same ``lo..hi`` grammar as ``checks:``. A missing dump, an unparseable
    dump, or an ABSENT series all fail loudly: a run whose metrics never
    landed must not pass a metrics gate."""
    from horovod_tpu.obs import prom

    if not prom_path or not os.path.exists(prom_path):
        print(f"metrics check: exposition dump {prom_path} not found FAIL")
        return False
    try:
        with open(prom_path) as f:
            values = prom.parse_text(f.read())
    except ValueError as e:
        print(f"metrics check: unparseable exposition dump ({e}) FAIL")
        return False
    ok = True
    for name, rule in checks.items():
        lo, hi = parse_target(str(rule["target"]))
        value = values.get(name)
        passed = value is not None and lo <= value <= hi
        shown = "absent" if value is None else f"{value:.6g}"
        print(
            f"metrics check {name}: value={shown} target={rule['target']} "
            f"{'PASS' if passed else 'FAIL'}"
        )
        ok = ok and passed
    return ok


def run_checks(metrics_path: str, checks: dict) -> bool:
    """Evaluate a ``{name: {target, aggregate}}`` block (the config.yaml:8-11
    shape), printing one verdict line per check. Shared by the CLI and the
    YAML job runner. A rule may carry ``job: <name>`` to scope the
    aggregate to one job's records in a multi-job (fleet) journal —
    single-job specs omit it and behave exactly as before."""
    ok = True
    for name, rule in checks.items():
        how = rule.get("aggregate", "mean")
        job = rule.get("job")
        passed, value = check_metrics(
            metrics_path, name, parse_target(str(rule["target"])), how=how,
            job=job,
        )
        scope = f" job={job}" if job is not None else ""
        print(
            f"check {name}{scope}: {how}={value:.6g} "
            f"target={rule['target']} {'PASS' if passed else 'FAIL'}"
        )
        ok = ok and passed
    return ok
