from horovod_tpu.launch.launcher import main

raise SystemExit(main())
