"""Process launchers: local multi-process and ssh multi-host.

Replaces the reference's ``mpirun`` path (README.md:57): slot mapping becomes
explicit ``HVT_PROCESS_ID`` assignment, ``-x`` env propagation becomes an env
dict serialized into each remote command, and ``/generated/hostfile`` becomes
an explicit host list. `horovod_tpu.runtime.init` on the worker side consumes
the HVT_* variables (runtime.py ENV_*) and wires `jax.distributed`.
"""

from __future__ import annotations

import os
import shlex
import socket
import subprocess
import sys
import threading

from horovod_tpu.runtime import (
    ENV_COORDINATOR,
    ENV_LOCAL_RANK,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
)


def pick_free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class Fleet:
    """Handle on a launched set of coordinated processes.

    `start_local`/`start_hosts` return one; `wait()` reproduces the MPI
    fail-stop contract (any rank failure aborts the job, SURVEY.md §5.3) and
    the supervisor (`launch/supervisor.py`) additionally drives `wait(abort=
    ...)` to kill a fleet whose heartbeats went stale (a hung collective is
    invisible to exit codes — the NCCL/ICI failure mode, arXiv:1810.11112).
    """

    def __init__(self, procs: list[subprocess.Popen], pumps=()):
        self.procs = list(procs)
        self.pumps = list(pumps)
        # True when wait(abort=...) tore the fleet down itself — the
        # supervisor's hang marker (exit codes alone can't distinguish
        # "killed for staleness" from "died of SIGTERM").
        self.aborted = False

    def running(self) -> list[subprocess.Popen]:
        return [p for p in self.procs if p.poll() is None]

    def first_failure(self) -> int | None:
        """First nonzero exit code observed so far, None if none yet."""
        return next(
            (p.returncode for p in self.procs
             if p.returncode not in (None, 0)), None
        )

    def terminate(self, term_timeout: float = 10.0) -> None:
        """SIGTERM every survivor, escalate to SIGKILL after the timeout."""
        running = self.running()
        for p in running:
            p.terminate()
        for p in running:
            try:
                p.wait(timeout=term_timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def wait(self, grace_seconds: float = 30.0, abort=None) -> int:
        """Wait for all processes with fail-stop semantics: when any exits
        nonzero, surviving peers get ``grace_seconds`` to finish on their own
        (they may be blocked in a collective waiting for the dead rank — the
        MPI abort analogue, SURVEY.md §5.3) and are then terminated. Returns
        the first nonzero exit code, 0 if all succeeded.

        ``abort``: optional zero-arg callable polled while the fleet is
        healthy; returning True terminates the whole fleet immediately and
        sets ``self.aborted`` (the supervisor's stale-heartbeat kill)."""
        import time

        first_failure: int | None = None
        deadline = None
        while True:
            running = self.running()
            if first_failure is None:
                failed = self.first_failure()
                if failed is not None:
                    first_failure = failed
                    deadline = time.monotonic() + grace_seconds
            if not running:
                break
            if (
                first_failure is None
                and abort is not None
                and abort()
            ):
                self.aborted = True
                self.terminate()
                break
            if deadline is not None and time.monotonic() > deadline:
                self.terminate()
                break
            time.sleep(0.1)
        for t in self.pumps:
            t.join(timeout=5)
        if first_failure is not None:
            return first_failure
        return next((p.returncode for p in self.procs if p.returncode != 0), 0)


def _wait_fail_stop(
    procs: list[subprocess.Popen], grace_seconds: float = 30.0
) -> int:
    """Fail-stop wait over bare Popens (see `Fleet.wait` for the contract)."""
    return Fleet(procs).wait(grace_seconds)


def _stream(proc: subprocess.Popen, tag: str) -> threading.Thread:
    """Prefix-tag a child's merged output, like mpirun's rank tagging."""

    def pump():
        for line in proc.stdout:
            sys.stdout.write(f"[{tag}] {line if isinstance(line, str) else line.decode()}")
            sys.stdout.flush()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return t


def start_local(
    nprocs: int,
    argv: list[str],
    env: dict[str, str] | None = None,
    coordinator_port: int | None = None,
    tag_output: bool = True,
) -> Fleet:
    """Launch ``argv`` as ``nprocs`` coordinated processes on this host and
    return the running `Fleet` (callers `wait()` it; the supervisor monitors
    it).

    The reference's single-container multi-slot test mode (README.md:53-58:
    ``mpirun -np N`` inside one Docker image) without MPI: each child gets
    the coordinator address and its process id via HVT_* env vars."""
    port = coordinator_port or pick_free_port()
    base_env = dict(os.environ)
    base_env.update(env or {})
    procs = []
    for i in range(nprocs):
        child_env = dict(base_env)
        if nprocs > 1:
            # nprocs == 1 is the reference's bare no-launcher mode
            # (README.md:49-52): no coordinator, collectives degrade locally.
            child_env[ENV_COORDINATOR] = f"127.0.0.1:{port}"
            child_env[ENV_NUM_PROCESSES] = str(nprocs)
            child_env[ENV_PROCESS_ID] = str(i)
        child_env[ENV_LOCAL_RANK] = str(i)
        procs.append(
            subprocess.Popen(
                argv,
                env=child_env,
                stdout=subprocess.PIPE if tag_output else None,
                stderr=subprocess.STDOUT if tag_output else None,
                text=tag_output,
            )
        )
    pumps = [_stream(p, f"rank {i}") for i, p in enumerate(procs) if tag_output]
    return Fleet(procs, pumps)


def run_local(
    nprocs: int,
    argv: list[str],
    env: dict[str, str] | None = None,
    coordinator_port: int | None = None,
    tag_output: bool = True,
) -> int:
    """`start_local` + fail-stop `Fleet.wait`: returns the first nonzero
    exit code (0 if all succeeded) — like an MPI job aborting on any rank
    failure (SURVEY.md §5.3)."""
    return start_local(
        nprocs, argv, env=env, coordinator_port=coordinator_port,
        tag_output=tag_output,
    ).wait()


def start_hosts(
    hosts: list[str],
    argv: list[str],
    env: dict[str, str] | None = None,
    coordinator_port: int = 9981,
    ssh_args: tuple[str, ...] = ("-o", "StrictHostKeyChecking=no"),
    workdir: str | None = None,
) -> Fleet:
    """Launch ``argv`` once per host over ssh — one process per TPU host —
    and return the running `Fleet`.

    The multi-host path (distributed-keras-sample.yaml topology): host 0 is
    the coordinator (the 'master' whose address every worker dials, replacing
    /generated/hostfile), env is propagated by injecting ``K=V`` exports into
    the remote command (the ``mpirun -x`` role)."""
    # Hostfile entries may be ssh-style 'user@host'; the coordinator address
    # every rank dials must be the bare host.
    coord_host = hosts[0].rsplit("@", 1)[-1]
    coord = f"{coord_host}:{coordinator_port}"
    procs = []
    for i, host in enumerate(hosts):
        remote_env = {
            ENV_COORDINATOR: coord,
            ENV_NUM_PROCESSES: str(len(hosts)),
            ENV_PROCESS_ID: str(i),
            ENV_LOCAL_RANK: "0",
            **(env or {}),
        }
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in remote_env.items())
        cd = f"cd {shlex.quote(workdir)} && " if workdir else ""
        remote_cmd = f"{cd}{exports} {' '.join(shlex.quote(a) for a in argv)}"
        procs.append(
            subprocess.Popen(
                ["ssh", *ssh_args, host, remote_cmd],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    pumps = [_stream(p, f"{hosts[i]}") for i, p in enumerate(procs)]
    return Fleet(procs, pumps)


def run_hosts(
    hosts: list[str],
    argv: list[str],
    env: dict[str, str] | None = None,
    coordinator_port: int = 9981,
    ssh_args: tuple[str, ...] = ("-o", "StrictHostKeyChecking=no"),
    workdir: str | None = None,
) -> int:
    """`start_hosts` + fail-stop `Fleet.wait` (the blocking pod launch)."""
    return start_hosts(
        hosts, argv, env=env, coordinator_port=coordinator_port,
        ssh_args=ssh_args, workdir=workdir,
    ).wait()


def main(argv: list[str] | None = None) -> int:
    import argparse

    argv = list(sys.argv[1:] if argv is None else argv)
    # Everything after `--` is the launched command (run/pod only); the head
    # is parsed strictly so typo'd flags error instead of being ignored.
    command: list[str] = []
    if "--" in argv:
        split = argv.index("--")
        argv, command = argv[:split], argv[split + 1 :]

    if argv and argv[0] == "lint":
        # Delegate the whole tail to the analyzer CLI before argparse sees
        # it (its flags are not ours; exit codes 0/1/2 are the pre-commit
        # contract). Restore a `--`-split tail — hvt-lint has no trailing
        # command but argparse treats `--` as an inert separator.
        from horovod_tpu.analysis.cli import main as lint_main

        return lint_main(argv[1:] + (["--"] + command if command else []))

    if argv and argv[0] == "serve":
        # Same delegation: the serving fleet owns its flags (see
        # `python -m horovod_tpu.serving.fleet --help`) — replica count,
        # router port, journal, --swap/--requests smoke harness.
        from horovod_tpu.serving.fleet import main as serve_main

        return serve_main(argv[1:])

    if argv and argv[0] == "fleet":
        # Same delegation: the multi-job control plane owns its flags
        # (see `python -m horovod_tpu.launch.fleetd --help`) — a fleet
        # spec (shared host pool + prioritized job entries), preemption
        # as elastic shrink, per-job budget isolation, journal recovery.
        from horovod_tpu.launch.fleetd import main as fleet_main

        return fleet_main(argv[1:])

    parser = argparse.ArgumentParser(prog="python -m horovod_tpu.launch")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="N coordinated processes on this host")
    p_run.add_argument("--nprocs", type=int, required=True)
    p_run.add_argument("--env", action="append", default=[], metavar="K=V")

    p_pod = sub.add_parser("pod", help="one process per host over ssh")
    p_pod.add_argument("--hostfile", help="file with one host per line")
    p_pod.add_argument("--hosts", help="comma-separated host list")
    p_pod.add_argument("--port", type=int, default=9981)
    p_pod.add_argument("--workdir")
    p_pod.add_argument("--env", action="append", default=[], metavar="K=V")

    for p in (p_run, p_pod):
        # Supervision (launch/supervisor.py): any of these flags turns the
        # fail-stop launch into a supervised fail-restart launch.
        p.add_argument(
            "--max-restarts", type=int, default=None, metavar="N",
            help="restart the fleet on failure, up to N consecutive "
            "no-progress restarts (progress = a new checkpoint under "
            "PS_MODEL_PATH)")
        p.add_argument(
            "--backoff", type=float, default=None, metavar="SECONDS",
            help="initial restart backoff (doubles per no-progress restart)")
        p.add_argument(
            "--heartbeat-timeout", type=float, default=None, metavar="SECONDS",
            help="kill+restart the fleet when the newest rank heartbeat is "
            "older than this (hang detection; sets HVT_HEARTBEAT_DIR for "
            "the ranks)")
        p.add_argument(
            "--status-port", type=int, default=None, metavar="N",
            help="serve the supervisor's own status over HTTP on this "
            "port: GET /status (fleet_status + the elastic rendezvous "
            "snapshot), GET /journal (the restart/elastic journal as "
            "JSON), GET /healthz — no serving bundle required (the "
            "`serve --fleet-journal` surface, from the supervisor "
            "itself). Needs a supervised launch (any restart/elastic "
            "flag)")
        p.add_argument(
            "--metrics-port", type=int, default=None, metavar="N",
            help="opt-in trainer-side Prometheus exporters: export "
            "HVT_METRICS_PORT=N to the ranks, so every training process "
            "serves GET /metrics (live step-phase/MFU gauges) and "
            "POST /profile?seconds=S on port N + local_rank. The "
            "supervisor's own aggregate /metrics rides --status-port")
        p.add_argument(
            "--restart-log", default=None, metavar="PATH",
            help="JSONL restart journal (default: "
            "$PS_MODEL_PATH/restarts.jsonl; gateable — "
            "`gate --metrics <log> --check restarts=0..N --aggregate "
            "count`). Rotates to <PATH>.1 past "
            "$HVT_RESTART_LOG_MAX_LINES/$HVT_RESTART_LOG_MAX_MB "
            "(default 100000 lines / 64 MB; 0 disables) so a "
            "weeks-long elastic fleet's journal stays bounded — the "
            "gate and /healthz read across the rotation")
        # Elastic mode (launch/supervisor.py supervise_elastic +
        # horovod_tpu.elastic): members are supervised INDIVIDUALLY — a
        # clean departure shrinks the fleet in place (survivors keep
        # training from committed state), a replacement grows it back.
        p.add_argument(
            "--elastic", action="store_true",
            help="elastic launch: rendezvous coordinator + TCP heartbeats "
            "+ per-rank restart; shrink to survivors instead of "
            "relaunching the fleet (the command must drive training via "
            "horovod_tpu.elastic.run)")
        p.add_argument(
            "--min-ranks", type=int, default=None, metavar="N",
            help="smallest world the elastic fleet may shrink to "
            "(default 1)")
        p.add_argument(
            "--max-ranks", type=int, default=None, metavar="N",
            help="largest world the elastic fleet may grow to "
            "(default: the launch size)")
        # Policy engine (launch/policy.py): the supervisor's observe->act
        # loop over the /fleet metric cache.
        p.add_argument(
            "--policy", choices=("off", "dry-run", "on"), default=None,
            help="supervisor policy engine: straggler evict-and-shrink, "
            "hot-spare promotion, hang auto-triage. dry-run journals "
            "every decision (policy_* events) without acting; thresholds "
            "ride HVT_POLICY_* env knobs. Needs a supervised launch")
        p.add_argument(
            "--spares", type=int, default=None, metavar="K",
            help="keep K warm standby processes parked at rendezvous; an "
            "evicted straggler's slot is refilled by a spare in the next "
            "generation, preserving world size (elastic only)")
        # Autotuner (horovod_tpu.tune): the CLI twin of the job spec's
        # tune: block — resolve a machine-found config into the launch
        # env before any process spawns.
        p.add_argument(
            "--tune", choices=("off", "offline", "probe"), default=None,
            help="hvt-tune at launch: `offline` trusts the analytic "
            "model over recorded BENCH_* evidence; `probe` races the "
            "model's shortlist with a few real steps (paired-leg A/B) "
            "before picking. The winner lands in the launch env "
            "(explicit --env pins still win) and is persisted to "
            "<PS_MODEL_PATH>/tune.json so a relaunch reuses it")

    p_gate = sub.add_parser("gate", help="CI metric range check")
    p_gate.add_argument("--metrics", required=True, help="metrics.jsonl path")
    p_gate.add_argument("--check", action="append", required=True,
                        metavar="NAME=LO..HI")
    p_gate.add_argument("--aggregate", default="mean",
                        choices=["mean", "last", "min", "max", "count"])

    p_job = sub.add_parser("job", help="run a YAML job spec")
    p_job.add_argument("spec")

    # Handled above, declared here so `--help` lists it.
    sub.add_parser(
        "lint",
        help="hvt-lint: distributed-correctness static analysis "
        "(see `hvt-lint --help`)")
    sub.add_parser(
        "serve",
        help="elastic serving fleet: N continuous-batching replicas "
        "behind one router, zero-downtime weight swaps "
        "(see `python -m horovod_tpu.serving.fleet --help`)")
    sub.add_parser(
        "fleet",
        help="multi-job control plane: run N job specs over a shared "
        "host pool with priorities, preemption-as-elastic-shrink, "
        "per-job restart budgets, host quarantine, and a "
        "crash-recoverable fleet journal "
        "(see `python -m horovod_tpu.launch.fleetd --help`)")

    args = parser.parse_args(argv)
    if args.cmd in ("run", "pod") and not command:
        parser.error(f"{args.cmd} needs a command after `--`")
    if args.cmd not in ("run", "pod") and command:
        parser.error(f"{args.cmd} takes no trailing command")
    def restart_policy(a):
        """None unless a supervision flag was given — ANY of the four
        (--backoff or --restart-log alone supervise with default budget)."""
        if (
            a.max_restarts is None and a.heartbeat_timeout is None
            and a.backoff is None and a.restart_log is None
        ):
            return None
        from horovod_tpu.launch import supervisor

        return supervisor.RestartPolicy.from_mapping({
            "max_restarts": a.max_restarts,
            "backoff": a.backoff,
            "heartbeat_timeout": a.heartbeat_timeout,
        })

    def elastic_policy(a):
        """None unless an elastic flag was given (--min/--max-ranks alone
        opt in, like the supervision flags)."""
        if not (a.elastic or a.min_ranks is not None
                or a.max_ranks is not None):
            return None
        from horovod_tpu.launch import supervisor

        return supervisor.ElasticPolicy.from_mapping({
            "min_ranks": a.min_ranks,
            "max_ranks": a.max_ranks,
        })

    def policy_config(a, env, policy, elastic):
        """None unless --policy/--spares was given — the supervisor's own
        from_env fallback still honors HVT_POLICY* without the flags. CLI
        values override the env-derived config field-for-field."""
        if a.policy is None and a.spares is None:
            return None
        if a.spares is not None and elastic is None:
            parser.error("--spares needs --elastic (spares park at the "
                         "rendezvous and join on shrink)")
        if policy is None and elastic is None:
            parser.error(
                "--policy needs a supervised launch: add a restart flag "
                "(--max-restarts/--backoff/--heartbeat-timeout/"
                "--restart-log) or --elastic"
            )
        import dataclasses

        from horovod_tpu.launch import policy as policy_lib

        cfg = policy_lib.PolicyConfig.from_env(env)
        overrides = {}
        if a.policy is not None:
            overrides["mode"] = a.policy
        if a.spares is not None:
            overrides["spares"] = a.spares
        return dataclasses.replace(cfg, **overrides)

    def apply_tune(a, env):
        """Resolve --tune into the launch env in place (see the job
        spec's tune: block for the journaled variant)."""
        if not a.tune or a.tune == "off":
            return
        from horovod_tpu.tune import insitu as tune_insitu

        try:
            tuned_env, _ = tune_insitu.resolve({"mode": a.tune}, env)
        except tune_insitu.TuneError as e:
            parser.error(f"--tune: {e}")
        for name, value in tuned_env.items():
            env.setdefault(name, value)

    if args.cmd == "run":
        env = dict(kv.split("=", 1) for kv in args.env)
        if args.metrics_port is not None:
            env["HVT_METRICS_PORT"] = str(args.metrics_port)
        apply_tune(args, env)
        policy = restart_policy(args)
        elastic = elastic_policy(args)
        pcfg = policy_config(args, env, policy, elastic)
        if elastic is not None:
            from horovod_tpu.launch import supervisor

            return supervisor.supervise_elastic(
                args.nprocs, command, env=env, policy=policy,
                elastic=elastic, log_path=args.restart_log,
                status_port=args.status_port, policy_config=pcfg,
            )
        if policy is not None:
            from horovod_tpu.launch import supervisor

            return supervisor.supervise_local(
                args.nprocs, command, env=env, policy=policy,
                log_path=args.restart_log, status_port=args.status_port,
                policy_config=pcfg,
            )
        if args.status_port is not None:
            parser.error(
                "--status-port needs a supervised launch: add a "
                "restart flag (--max-restarts/--backoff/"
                "--heartbeat-timeout/--restart-log) or --elastic"
            )
        return run_local(args.nprocs, command, env=env)
    if args.cmd == "pod":
        if args.hostfile:
            with open(args.hostfile) as f:
                hosts = [
                    h.strip() for h in f
                    if h.strip() and not h.strip().startswith("#")
                ]
        elif args.hosts:
            hosts = args.hosts.split(",")
        else:
            parser.error("pod needs --hostfile or --hosts")
        env = dict(kv.split("=", 1) for kv in args.env)
        if args.metrics_port is not None:
            env["HVT_METRICS_PORT"] = str(args.metrics_port)
        apply_tune(args, env)
        policy = restart_policy(args)
        elastic = elastic_policy(args)
        pcfg = policy_config(args, env, policy, elastic)
        if elastic is not None:
            from horovod_tpu.launch import supervisor

            return supervisor.supervise_elastic_hosts(
                hosts, command, env=env, policy=policy, elastic=elastic,
                sync_port_base=args.port, workdir=args.workdir,
                log_path=args.restart_log, status_port=args.status_port,
                spares=(args.spares or 0), policy_config=pcfg,
            )
        if args.heartbeat_timeout is not None and not (
            env.get("PS_MODEL_PATH") or os.environ.get("PS_MODEL_PATH")
        ):
            # Fail fast at the CLI: a launcher-local tmpdir heartbeat dir
            # can never observe remote ranks' beats, so pod hang detection
            # would silently never fire (supervise_hosts raises the same
            # contract for programmatic callers).
            parser.error(
                "pod --heartbeat-timeout needs a shared filesystem for "
                "heartbeats: set PS_MODEL_PATH to a mount shared with "
                "every host (NFS/GCS-fuse), or use --elastic — its "
                "heartbeats ride the rendezvous TCP socket and need no "
                "shared filesystem"
            )
        if policy is not None:
            from horovod_tpu.launch import supervisor

            return supervisor.supervise_hosts(
                hosts, command, env=env, policy=policy,
                coordinator_port=args.port, workdir=args.workdir,
                log_path=args.restart_log, status_port=args.status_port,
                policy_config=pcfg,
            )
        if args.status_port is not None:
            parser.error(
                "--status-port needs a supervised launch: add a "
                "restart flag (--max-restarts/--backoff/"
                "--heartbeat-timeout/--restart-log) or --elastic"
            )
        return run_hosts(hosts, command, env=env,
                         coordinator_port=args.port, workdir=args.workdir)
    if args.cmd == "gate":
        from horovod_tpu.launch.ci_gate import run_checks

        checks = {}
        for spec in args.check:
            name, target = spec.split("=", 1)
            checks[name] = {"target": target, "aggregate": args.aggregate}
        return 0 if run_checks(args.metrics, checks) else 1
    if args.cmd == "job":
        from horovod_tpu.launch.job import run_job

        return run_job(args.spec)
    return 2


def cli() -> None:
    """Console entry point (`hvt-launch`, pyproject.toml)."""
    raise SystemExit(main())


if __name__ == "__main__":
    cli()
