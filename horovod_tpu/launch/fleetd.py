"""`hvt-launch fleet` — the multi-job control plane (ROADMAP item 3).

One fleetd process owns a declared HOST POOL and runs N job specs over
it — the step past everything before this, which operated exactly one
job (one supervisor, one policy engine, one serving fleet). A fleet
spec is a pool plus a list of job entries, each a complete single-job
spec (`launch.job` grammar: ``job:`` + ``checks:``/``journal_checks:``/
``metrics_checks:``) with three fleet-level keys on top:

.. code-block:: yaml

    fleet:
      pool:            # host -> slot count (one slot = one rank/unit)
        h0: {slots: 2}
        h1: {slots: 2}
      dir: ./fleet-state   # fleet journal + per-host pid registries
      tick_s: 0.5          # scheduler cadence  (HVT_FLEET_TICK_S)
      quarantine_s: 60     # dead-host cooldown (HVT_FLEET_QUARANTINE_S)
    jobs:
      - name: lm-soak
        priority: 1        # higher wins hosts
        # delay_s: 0       # arrival offset from fleet start
        job: {command: [...], elastic: {min_ranks: 1, max_ranks: 4}, ...}
        journal_checks: {...}

Semantics, in order of importance:

* **Priority placement + preemption-as-elastic-shrink.** The scheduler
  (`schedule`, a pure function — unit-testable without processes)
  places demand by priority. When a higher-priority job needs hosts —
  admission OR regrow after host loss — it reclaims them from strictly
  lower-priority *elastic* jobs via ``POST /shrink`` on the victim's
  control port: the victim's supervisor SIGTERMs members, the elastic
  callback turns that into a clean leave at the commit boundary, and
  the exit spends ZERO restart budget (a ``preempt`` journal record,
  not a ``restarts`` one). Freed hosts flow back through the victim's
  ``released`` ledger; when the pool frees up again the victim is
  regrown to full size (``POST /grow`` → `supervise_elastic`'s
  ``take_grows``). Preemption is capacity reclamation, not failure.
* **Per-job budget isolation.** Every job runs under its OWN
  `supervise_elastic` (as a separate child process) with its OWN
  restart/evict/oom budgets and its OWN journal, every record stamped
  ``job=<name>`` (`RestartLog` ``extra``). Cross-charging is a bug:
  `assert_budget_isolation` scans a finished job's journal and fails
  the fleet if any record names a different job.
* **Host-level failure is one event.** The ``hostdown`` fault kind
  (testing/faults.py) kills every rank sharing a host in one stroke;
  the job's `JobController.classify_exit` reclassifies the co-resident
  deaths as ONE ``host_lost`` — first death charged once, siblings
  free — and reports the host up to fleetd, which quarantines it for
  ``quarantine_s`` before its slots are schedulable again. Rank→host
  membership rides `HVT_FAULT_HOST_PIDS` (a per-host pid directory
  under ``<dir>/hostpids/``) so the blast radius is real even on a
  local pool.
* **fleetd itself is crash-recoverable.** Every placement / preempt /
  release / regrow / host-loss / budget / completion decision lands in
  ``fleet-journal.jsonl`` (append-only, metric-shaped — `ci_gate`
  gates it with ``job=`` scoping). Job children are spawned in their
  OWN sessions, so a SIGKILLed fleetd leaves them training; a
  restarted fleetd replays the journal, probes each recorded pid +
  control port, and ADOPTS the survivors (an ``adopt`` record) instead
  of relaunching them — monitoring adopted jobs by pid liveness and
  judging them purely by their gates.

Observability: ``GET /fleetd`` (jobs, placements, per-job budget
remaining, host states) and ``GET /metrics`` (the declared
``hvt_fleetd_*`` series, obs/core.py) on ``fleet.status_port``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shlex
import signal
import subprocess
import sys
import threading
import time
import urllib.request

from horovod_tpu.analysis import registry
from horovod_tpu.launch import ci_gate, launcher, supervisor
from horovod_tpu.obs import core as obs_core, prom as obs_prom

JOURNAL_NAME = "fleet-journal.jsonl"
# Exit codes subprocess reports for a SIGKILLed child (raw signal, or
# 128+9 when a shell wrapper re-reports it) — the host-loss shape.
_SIGKILL_CODES = (-signal.SIGKILL, 128 + signal.SIGKILL)


# --------------------------------------------------------------------------
# fleet spec
# --------------------------------------------------------------------------

@dataclasses.dataclass
class JobEntry:
    """One parsed ``jobs:`` entry — the fleet-level keys plus the
    embedded single-job spec (validated through `job.validate_spec`,
    so a typo'd restart:/elastic:/policy: block fails at load)."""

    name: str
    priority: int
    delay_s: float
    spec: dict            # the single-job spec mapping (job: + gates)
    min_units: int        # smallest schedulable world
    target_units: int     # full-size world (regrow goal)
    elastic: bool         # preemptible / controller-driven
    env: dict
    log_path: str | None  # the job's own journal (budget isolation unit)


def load_entries(spec: dict) -> tuple[dict, list[JobEntry]]:
    """Parse + validate a fleet spec mapping → (fleet config, entries).
    Raises ``ValueError`` naming every problem (all of them, not the
    first — a fleet spec is long enough that one-at-a-time hurts)."""
    from horovod_tpu.launch import job as job_lib

    errors: list[str] = []
    fleet = spec.get("fleet") or {}
    if not isinstance(fleet, dict):
        raise ValueError(f"fleet: must be a mapping, got {fleet!r}")
    pool_raw = fleet.get("pool") or {}
    pool: dict[str, int] = {}
    if not isinstance(pool_raw, dict) or not pool_raw:
        errors.append("fleet pool: needs a {host: {slots: N}} mapping")
    else:
        for host, cfg in pool_raw.items():
            slots = cfg.get("slots", 1) if isinstance(cfg, dict) else cfg
            try:
                slots = int(slots)
            except (TypeError, ValueError):
                slots = 0
            if slots <= 0:
                errors.append(f"fleet pool {host}: slots must be >= 1")
            pool[str(host)] = slots
    entries: list[JobEntry] = []
    jobs = spec.get("jobs")
    if not isinstance(jobs, list) or not jobs:
        errors.append("jobs: needs a non-empty list of job entries")
        jobs = []
    seen: set[str] = set()
    for i, e in enumerate(jobs):
        if not isinstance(e, dict) or not e.get("name"):
            errors.append(f"jobs[{i}]: needs a name:")
            continue
        name = str(e["name"])
        if name in seen:
            errors.append(f"jobs[{i}]: duplicate name {name!r}")
            continue
        seen.add(name)
        sub = {k: v for k, v in e.items()
               if k not in ("name", "priority", "delay_s")}
        for p in job_lib.validate_spec(sub):
            errors.append(f"job {name}: {p}")
        j = sub.get("job") if isinstance(sub.get("job"), dict) else {}
        if j.get("hosts"):
            errors.append(
                f"job {name}: hosts: conflicts with the fleet pool — "
                "fleetd owns placement"
            )
        env = {str(k): str(v) for k, v in (j.get("env") or {}).items()}
        elastic = "elastic" in j
        if "serve" in j:
            serve = j.get("serve") or {}
            target = int(serve.get("replicas", 2))
            minimum = target
            log_path = serve.get("journal") or supervisor.default_log_path(
                env
            )
        elif elastic:
            try:
                pol = supervisor.ElasticPolicy.from_mapping(
                    j.get("elastic") or {}
                )
            except (TypeError, ValueError):
                continue  # validate_spec already reported it
            target = pol.max_ranks or int(j.get("nprocs", 1))
            minimum = pol.min_ranks
            restart = j.get("restart") or {}
            log_path = (restart.get("log") if isinstance(restart, dict)
                        else None) or supervisor.default_log_path(env)
        else:
            target = int(j.get("nprocs", 1))
            minimum = target
            restart = j.get("restart") or {}
            log_path = (restart.get("log") if isinstance(restart, dict)
                        else None) or supervisor.default_log_path(env)
        if not log_path:
            errors.append(
                f"job {name}: needs restart.log or env PS_MODEL_PATH — "
                "the per-job journal is the budget-isolation unit"
            )
        entries.append(JobEntry(
            name=name, priority=int(e.get("priority", 0)),
            delay_s=float(e.get("delay_s", 0.0)), spec=sub,
            min_units=minimum, target_units=target, elastic=elastic,
            env=env, log_path=log_path,
        ))
    data_service = fleet.get("data_service")
    if data_service is not None and not isinstance(data_service, dict):
        errors.append(
            "fleet data_service: must be a mapping "
            "({dir:, port:, metrics_port:} — all optional)"
        )
        data_service = None
    if errors:
        raise ValueError("; ".join(errors))
    return {
        "pool": pool,
        "dir": str(fleet.get("dir") or "./fleet-state"),
        "tick_s": fleet.get("tick_s"),
        "quarantine_s": fleet.get("quarantine_s"),
        "status_port": fleet.get("status_port"),
        "data_service": data_service,
    }, entries


# --------------------------------------------------------------------------
# the scheduler — pure, deterministic, unit-testable without processes
# --------------------------------------------------------------------------

def free_units(pool: dict, allocs: dict, now: float) -> dict:
    """Schedulable units per host: declared slots minus allocated,
    zero while quarantined (``until`` in wall-clock seconds)."""
    used: dict[str, int] = {}
    for hosts in allocs.values():
        for h in hosts:
            used[h] = used.get(h, 0) + 1
    free: dict[str, int] = {}
    for h in sorted(pool):
        if pool[h].get("until", 0.0) > now:
            continue
        n = pool[h]["slots"] - used.get(h, 0)
        if n > 0:
            free[h] = n
    return free


def schedule(jobs: list, pool: dict, now: float) -> list:
    """One scheduling pass over plain state → a list of action dicts.

    ``jobs``: ``{name, priority, state, arrival, alloc: [host,...],
    min, target, requested, preemptible}`` per job.  ``pool``:
    ``{host: {slots, until}}``.  Actions:

    * ``{"op": "place", "job", "hosts"}`` — admit a pending job.
    * ``{"op": "grow", "job", "hosts"}`` — regrow a running job.
    * ``{"op": "shrink", "job", "target", "for"}`` — preempt a
      lower-priority elastic job down to ``target`` units (idempotent:
      the actor only acts when ``target`` drops below what it already
      requested).
    * ``{"op": "wait", "job", "need"}`` — demand acknowledged, no
      capacity yet (preemption in flight, or genuinely full).

    Demand is served priority-descending (name-tiebroken); free units
    pack host-name order. Preemption reclaims from STRICTLY
    lower-priority running elastic jobs, lowest priority first, never
    below each victim's ``min``. A pending job is placed at full
    target when possible, degraded to whatever is free (>= its min)
    when nothing can be reclaimed, and otherwise waits.
    """
    allocs = {j["name"]: j["alloc"] for j in jobs if j["state"] == "running"}
    free = free_units(pool, allocs, now)

    def take(n: int) -> list:
        got: list = []
        # Most-free host first: gang jobs pack onto whole hosts (the
        # shape preemption vacates), not one slot each across the pool.
        for h in sorted(free, key=lambda h: (-free[h], h)):
            while free[h] > 0 and len(got) < n:
                free[h] -= 1
                got.append(h)
        return got

    def plan_preempts(claimant: dict, want: int) -> int:
        """Queue shrink actions against lower-priority elastic jobs;
        returns the unit count expected to free up (asynchronously —
        the victims leave cleanly, they are not killed here)."""
        freed = 0
        victims = [v for v in jobs
                   if v["state"] == "running" and v["preemptible"]
                   and v["priority"] < claimant["priority"]]
        for v in sorted(victims, key=lambda v: (v["priority"], v["name"])):
            if freed >= want:
                break
            cur = shrunk.get(
                v["name"], min(v["requested"], len(v["alloc"])))
            give = min(cur - v["min"], want - freed)
            if give <= 0:
                continue
            shrunk[v["name"]] = cur - give
            freed += give
            actions.append({"op": "shrink", "job": v["name"],
                            "target": cur - give, "for": claimant["name"]})
        return freed

    actions: list = []
    shrunk: dict = {}  # victim -> planned target this pass
    # Units already preempted but not yet vacated (shrink acknowledged,
    # members still mid-clean-leave). A claimant counts these against
    # its deficit BEFORE planning new preemption — otherwise every tick
    # of a slow clean leave squeezes the victim one unit further.
    pending_free = sum(
        max(0, len(v["alloc"]) - min(v["requested"], len(v["alloc"])))
        for v in jobs if v["state"] == "running" and v["preemptible"]
    )
    demand = [j for j in jobs
              if (j["state"] == "pending" and now >= j["arrival"])
              or (j["state"] == "running" and j["preemptible"]
                  and len(j["alloc"]) < j["target"])]
    for j in sorted(demand, key=lambda j: (-j["priority"], j["name"])):
        need = j["target"] - len(j["alloc"])
        nfree = sum(free.values())
        if j["state"] == "pending":
            if nfree >= need:
                actions.append({"op": "place", "job": j["name"],
                                "hosts": take(need)})
                continue
            short = need - nfree
            claimed = min(short, pending_free)
            pending_free -= claimed
            short -= claimed
            reclaim = plan_preempts(j, short) if short > 0 else 0
            if claimed or reclaim or nfree < j["min"]:
                # Preemption in flight (or hopeless): don't grab a
                # partial allocation that would leave the freed units
                # fragmented — admit in one piece next pass.
                actions.append({"op": "wait", "job": j["name"],
                                "need": need})
            else:
                # Nothing to reclaim but >= min is free: admit degraded,
                # regrow later like any shrunken elastic job.
                actions.append({"op": "place", "job": j["name"],
                                "hosts": take(nfree)})
        else:
            got = take(min(need, nfree))
            if got:
                actions.append({"op": "grow", "job": j["name"],
                                "hosts": got})
            short = need - len(got)
            claimed = min(short, pending_free)
            pending_free -= claimed
            short -= claimed
            if short > 0:
                plan_preempts(j, short)
    return actions


# --------------------------------------------------------------------------
# per-job controller — lives in the job child, drives supervise_elastic
# --------------------------------------------------------------------------

class JobController:
    """`supervise_elastic`'s fleet hook for ONE job (the ``controller``
    duck type its docstring specifies), plus the fleetd-facing ledger
    served over the job's control port.

    The unit of accounting is a HOST UNIT (one slot on one host):
    ``alloc`` is the multiset of units the scheduler has granted,
    ``capacity()`` is its size, and every spawned member is pinned to a
    unit — the member env carries ``HVT_FLEET_HOST`` and the host's
    shared pid registry (`HVT_FAULT_HOST_PIDS`), which is what gives
    the ``hostdown`` fault its real blast radius and this controller
    its ground truth for ``host_lost`` classification.

    ``released`` and ``lost_hosts`` are APPEND-ONLY ledgers: fleetd
    keeps a seen-cursor per ledger (journal-reconstructible), so a
    scrape lost to a fleetd crash is re-read, never double-counted.
    """

    def __init__(self, name: str, hosts: list, fleet_dir: str,
                 argv: list, tag_output: bool = True):
        self.name = name
        self.alloc: list = list(hosts)
        self.fleet_dir = fleet_dir
        self.argv = list(argv)
        self.tag_output = tag_output
        self._target = len(self.alloc)
        self._members: dict = {}   # member_id -> {host, proc, preempting}
        self._released: list = []  # append-only host units given back
        self._lost: list = []      # append-only hosts declared dead
        self._lost_set: set = set()
        self._pending_grow = 0
        self._lock = threading.RLock()

    # -- spawn: pin each member to a unit, wire the host identity ----------
    def _live_per_host(self) -> dict:
        counts: dict = {}
        for rec in self._members.values():
            if rec["proc"].poll() is None:
                counts[rec["host"]] = counts.get(rec["host"], 0) + 1
        return counts

    def _assign(self) -> str:
        live = self._live_per_host()
        for h in sorted(set(self.alloc)):
            if self.alloc.count(h) > live.get(h, 0):
                return h
        # Capacity gating upstream should prevent this; pile onto the
        # least-loaded granted host rather than refuse to spawn.
        return min(sorted(set(self.alloc)) or ["?"],
                   key=lambda h: live.get(h, 0))

    def spawn(self, member_id: str, slot: int, env: dict):
        with self._lock:
            host = self._assign()
            env = dict(env)
            env["HVT_FLEET_HOST"] = host
            env["HVT_FAULT_HOST_PIDS"] = os.path.join(
                self.fleet_dir, "hostpids", host
            )
            proc = supervisor._spawn_member_local(
                self.argv, env, member_id, slot, tag_output=self.tag_output
            )
            self._members[member_id] = {
                "host": host, "proc": proc, "preempting": False,
            }
            return proc

    # -- fleetd-driven transitions (control server) ------------------------
    def shrink(self, target: int) -> None:
        with self._lock:
            self._target = min(self._target, int(target))

    def grow(self, hosts: list) -> None:
        with self._lock:
            for h in hosts:
                self.alloc.append(h)
                self._lost_set.discard(h)
            self._target = len(self.alloc)
            self._pending_grow += len(hosts)

    # -- the supervise_elastic controller protocol -------------------------
    def capacity(self):
        with self._lock:
            return len(self.alloc)

    def take_preempts(self) -> list:
        with self._lock:
            excess = len(self.alloc) - self._target
            if excess <= 0:
                return []
            live = self._live_per_host()
            victims: list = []
            # Unoccupied units go straight back — nothing to SIGTERM.
            for h in sorted(set(self.alloc), reverse=True):
                while excess > 0 and self.alloc.count(h) > live.get(h, 0):
                    self.alloc.remove(h)
                    self._released.append(h)
                    excess -= 1
            # Then live members, reverse host order / newest member
            # first, so releases concentrate on whole hosts (the shape
            # an admission-blocked peer can actually use).
            candidates = sorted(
                (m for m, rec in self._members.items()
                 if rec["proc"].poll() is None and not rec["preempting"]),
                key=lambda m: (self._members[m]["host"], m), reverse=True,
            )
            for m in candidates:
                if excess <= 0:
                    break
                rec = self._members[m]
                if rec["host"] not in self.alloc:
                    continue
                rec["preempting"] = True
                # The unit leaves the allocation NOW (capacity drops so
                # the supervisor won't respawn into it); the host label
                # reaches `released` only when the member's clean leave
                # lands (on_exit) — released means actually vacated.
                self.alloc.remove(rec["host"])
                victims.append(m)
                excess -= 1
            return victims

    def take_grows(self) -> int:
        with self._lock:
            n = self._pending_grow
            self._pending_grow = 0
            return n

    def classify_exit(self, member_id: str, code: int, kind: str):
        with self._lock:
            rec = self._members.get(member_id)
            if rec is None or rec["preempting"]:
                return None
            if code not in _SIGKILL_CODES:
                return None
            host = rec["host"]
            if host in self._lost_set:
                # A sibling of an already-declared loss. This check must
                # run BEFORE the cohort gate: by the time the sibling's
                # death is classified, the first victim has been reaped
                # (popped by on_exit), so the sibling is the host's LAST
                # live member and the cohort test alone would misread it
                # as a lone oom-kill — double-charging the incident.
                return ("host_lost", False)
            cohort = [m for m, r in self._members.items()
                      if r["host"] == host and not r["preempting"]]
            if len(cohort) < 2:
                # A lone SIGKILL keeps its classic classification
                # (oom-kill) — host loss means co-residents died
                # together.
                return None
            # The host's ranks die peers-first-self-last within
            # microseconds, but the reap loop can observe a sibling
            # before the killer finishes itself — give the cohort a
            # beat to die together before ruling host loss out.
            deadline = time.monotonic() + 0.5
            while True:
                codes = [self._members[m]["proc"].poll() for m in cohort]
                if all(c is not None for c in codes):
                    break
                if time.monotonic() >= deadline:
                    break
                time.sleep(0.02)
            for c in codes:
                if c is None or c not in _SIGKILL_CODES:
                    return None
            if host in self._lost_set:
                return (kind if kind == "host_lost" else "host_lost",
                        False)
            # First co-resident death: declare the host, purge its
            # units (capacity drops; the scheduler quarantines and
            # later regrows), charge the incident ONCE.
            self._lost_set.add(host)
            self._lost.append(host)
            self.alloc = [h for h in self.alloc if h != host]
            return ("host_lost", True)

    def on_exit(self, member_id: str, kind: str) -> None:
        with self._lock:
            rec = self._members.pop(member_id, None)
            if rec is None:
                return
            if rec["preempting"]:
                # Clean leave landed (or the grace escalation did):
                # either way the unit is vacated — give it back.
                self._released.append(rec["host"])

    # -- the fleetd-facing ledger ------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "job": self.name,
                "alloc": list(self.alloc),
                "capacity": len(self.alloc),
                "target": self._target,
                "released": list(self._released),
                "lost_hosts": list(self._lost),
                "members": {
                    m: rec["host"] for m, rec in self._members.items()
                    if rec["proc"].poll() is None
                },
            }


def start_ctl_server(controller: JobController, port: int):
    """The job child's control surface, loopback-only: ``GET /fleetctl``
    (the controller ledger), ``POST /shrink {"target": K}``, ``POST
    /grow {"hosts": [...]}``. Returns the started server (daemon
    thread); callers own ``shutdown()``."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

        def do_GET(self):
            if self.path == "/fleetctl":
                self._send(200, controller.snapshot())
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, TypeError):
                self._send(400, {"error": "bad JSON body"})
                return
            if self.path == "/shrink":
                controller.shrink(int(body.get("target", 0)))
                self._send(200, {"ok": True, "target": body.get("target")})
            elif self.path == "/grow":
                hosts = [str(h) for h in (body.get("hosts") or [])]
                controller.grow(hosts)
                self._send(200, {"ok": True, "hosts": hosts})
            else:
                self._send(404, {"error": f"no route {self.path}"})

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


# --------------------------------------------------------------------------
# the job child — one supervised job, adoptable after a fleetd crash
# --------------------------------------------------------------------------

def _job_main(cfg_path: str) -> int:
    """Entry point of ``python -m horovod_tpu.launch.fleetd _job CFG`` —
    one job under its own supervisor, in its OWN session (fleetd spawns
    with ``start_new_session=True``), so a dead fleetd never takes the
    job with it and a SIGTERM from fleetd tears down the whole process
    group cleanly (the handler raises SystemExit → the supervise loop's
    teardown reaps every member)."""
    with open(cfg_path) as f:
        cfg = json.load(f)

    def _term(signum, frame):
        raise SystemExit(143)

    signal.signal(signal.SIGTERM, _term)
    from horovod_tpu.launch import job as job_lib

    spec = cfg["spec"]
    j = spec.get("job") or {}
    name = cfg["name"]
    hosts = list(cfg["hosts"])
    env = {str(k): str(v) for k, v in (j.get("env") or {}).items()}
    command = j.get("command")
    argv = (
        command if isinstance(command, list) else shlex.split(command)
    ) if command else []
    log_path = cfg.get("log_path")
    metrics_path = spec.get("metrics", os.path.join(
        env.get("PS_MODEL_PATH", "./models"), "metrics.jsonl"
    ))
    if spec.get("checks") and os.path.exists(metrics_path):
        os.remove(metrics_path)
    if j.get("fresh"):
        import shutil

        norm = os.path.normpath(
            os.path.abspath(env.get("PS_MODEL_PATH", "./models"))
        )
        if norm in ("/", os.path.expanduser("~")) or norm.count(os.sep) < 2:
            print(f"fleet job {name}: refusing to wipe suspicious "
                  f"fresh dir {norm}")
            return 1
        shutil.rmtree(norm, ignore_errors=True)
    if "serve" in j:
        from horovod_tpu.serving import fleet as serve_fleet

        serve = j["serve"] or {}
        os.environ.update(env)
        job_lib._reset_journal(log_path, supervisor.default_model_dir(env))
        serve_argv = ["--replicas", str(serve.get("replicas", 2)),
                      "--journal", log_path,
                      "--port", str(serve.get("port", 0)),
                      "--host", str(serve.get("host", "127.0.0.1"))]
        if serve.get("demo"):
            serve_argv.append("--demo")
        else:
            serve_argv.insert(0, str(serve["bundle"]))
        if serve.get("requests"):
            serve_argv += ["--requests", str(serve["requests"])]
        if serve.get("swap"):
            serve_argv.append("--swap")
        if serve.get("coalesce"):
            serve_argv.append("--coalesce")
        return serve_fleet.main(serve_argv)
    pcfg = None
    if "policy" in j:
        from horovod_tpu.launch import policy as policy_lib

        pcfg = policy_lib.PolicyConfig.from_mapping(j["policy"] or {})
    restart = j.get("restart") or {}
    policy = supervisor.RestartPolicy.from_mapping(
        {k: v for k, v in restart.items() if k != "log"}
    )
    job_lib._reset_journal(log_path, supervisor.default_model_dir(env))
    if "elastic" in j:
        elastic = supervisor.ElasticPolicy.from_mapping(j["elastic"] or {})
        ctl = JobController(name, hosts, cfg["fleet_dir"], argv)
        server = start_ctl_server(ctl, int(cfg["ctl_port"]))
        try:
            return supervisor.supervise_elastic(
                len(hosts), argv, env=env, policy=policy, elastic=elastic,
                log_path=log_path, status_port=cfg.get("status_port"),
                policy_config=pcfg, spawn=ctl.spawn, controller=ctl,
                journal_tags={"job": name},
            )
        finally:
            server.shutdown()
    return supervisor.supervise_local(
        len(hosts), argv, env=env, policy=policy, log_path=log_path,
        status_port=cfg.get("status_port"), policy_config=pcfg,
    )


# --------------------------------------------------------------------------
# budget isolation — cross-charging is a bug, asserted
# --------------------------------------------------------------------------

def budget_isolation_violations(name: str, log_path: str | None) -> list:
    """Records in job ``name``'s journal attributed to a DIFFERENT job.
    Every record the job's supervisor writes is stamped ``job=<name>``
    (`RestartLog` ``extra``); any other attribution means two jobs
    shared a journal — exactly the cross-charging the per-job budget
    isolation exists to prevent."""
    bad = []
    for rec in supervisor.journal_records(log_path):
        if "job" in rec and rec.get("job") != name:
            bad.append(rec)
    return bad


# --------------------------------------------------------------------------
# fleetd metrics (the declared hvt_fleetd_* series)
# --------------------------------------------------------------------------

def fleetd_metrics(journal_path: str | None, jobs: dict | None = None,
                   pool: dict | None = None,
                   now: float | None = None) -> obs_core.Registry:
    """One scrape of the control plane, as a fresh obs registry —
    journal-derived counters (so they survive a fleetd restart) plus
    live job/host gauges."""
    reg = obs_core.Registry()
    preempts = regrows = lost = 0
    for rec in supervisor.journal_records(journal_path):
        n = rec.get("name")
        if n == "preempt":
            preempts += 1
        elif n == "regrow":
            regrows += 1
        elif n == "host_lost":
            lost += 1
    reg.counter_set("hvt_fleetd_preempts_total", preempts)
    reg.counter_set("hvt_fleetd_regrows_total", regrows)
    reg.counter_set("hvt_fleetd_host_lost_total", lost)
    if jobs is not None:
        states: dict = {}
        for name, st in sorted(jobs.items()):
            states[st["state"]] = states.get(st["state"], 0) + 1
            reg.gauge("hvt_fleetd_job_size", len(st["alloc"]), job=name)
            if st.get("budget") is not None:
                reg.gauge("hvt_fleetd_job_restart_budget_remaining",
                          st["budget"], job=name)
        for state, n in sorted(states.items()):
            reg.gauge("hvt_fleetd_jobs", n, state=state)
    if pool is not None:
        now = time.time() if now is None else now
        up = sum(1 for p in pool.values() if p.get("until", 0.0) <= now)
        reg.gauge("hvt_fleetd_hosts", up, state="up")
        reg.gauge("hvt_fleetd_hosts", len(pool) - up, state="quarantined")
    return reg


# --------------------------------------------------------------------------
# HTTP plumbing (tiny, timeout-bounded, failure == None)
# --------------------------------------------------------------------------

def _http_json(url: str, timeout: float = 2.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def _http_text(url: str, timeout: float = 2.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def _http_post(url: str, payload: dict, timeout: float = 2.0) -> bool:
    try:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout):
            return True
    except Exception:
        return False


def _pid_alive(pid) -> bool:
    if not pid:
        return False
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


# --------------------------------------------------------------------------
# the daemon
# --------------------------------------------------------------------------

class Fleetd:
    """The scheduler daemon: tick loop over (reap → scrape → schedule →
    act), journaling every decision. Construct from a parsed spec
    mapping (tests) or via `run_fleet` (the CLI)."""

    def __init__(self, spec: dict, status_port: int | None = None,
                 verbose: bool = True):
        cfg, entries = load_entries(spec)
        self.fleet_dir = os.path.abspath(cfg["dir"])
        self.journal_path = os.path.join(self.fleet_dir, JOURNAL_NAME)
        self.tick_s = float(
            cfg["tick_s"] if cfg["tick_s"] is not None
            else registry.get_float("HVT_FLEET_TICK_S")
        )
        self.quarantine_s = float(
            cfg["quarantine_s"] if cfg["quarantine_s"] is not None
            else registry.get_float("HVT_FLEET_QUARANTINE_S")
        )
        self.status_port = (
            status_port if status_port is not None else cfg["status_port"]
        )
        self.verbose = verbose
        self.pool = {
            h: {"slots": n, "until": 0.0} for h, n in cfg["pool"].items()
        }
        self.fleet_checks = spec.get("journal_checks") or {}
        # Fleet-level metrics gates run against the shared hvt-data
        # dispatcher's final /metrics scrape (per-job batches-served,
        # zero cursor refusals).
        self.fleet_metrics_checks = spec.get("metrics_checks") or {}
        self.data_service_cfg = cfg.get("data_service")
        self.data_proc = None
        self.data_port: int | None = None
        self.data_metrics_port: int | None = None
        self.jobs: dict = {}
        for e in entries:
            self.jobs[e.name] = {
                "entry": e, "state": "pending", "alloc": [],
                "requested": 0, "pid": None, "proc": None,
                "ctl_port": None, "status_port": None,
                "seen_released": 0, "seen_lost": 0, "budget": None,
                "exit_code": None, "adopted": False, "gates_ok": None,
            }
        self.start_wall: float | None = None
        self.log: supervisor.RestartLog | None = None

    # -- journal replay + survivor adoption --------------------------------
    def _maybe_recover(self) -> bool:
        records = supervisor.journal_records(self.journal_path)
        names = {r.get("name") for r in records}
        if "fleet_start" not in names or "fleet_done" in names:
            # No interrupted run to resume: a finished (or absent)
            # journal means this is a FRESH fleet — stale state must
            # not feed this run's gates.
            for p in (self.journal_path, self.journal_path + ".1"):
                if os.path.exists(p):
                    os.remove(p)
            return False
        for rec in records:
            n = rec.get("name")
            if n == "fleet_start":
                self.start_wall = rec.get("start") or rec.get("wall_time")
            elif n in ("place", "adopt", "release", "regrow", "host_lost",
                       "preempt", "job_done"):
                job = rec.get("job") or rec.get("victim")
                st = self.jobs.get(job)
                if st is None:
                    continue
                if n == "place":
                    st.update(
                        state="running", alloc=list(rec.get("hosts") or []),
                        requested=len(rec.get("hosts") or []),
                        pid=rec.get("pid"), ctl_port=rec.get("ctl_port"),
                        status_port=rec.get("status_port"),
                        seen_released=0, seen_lost=0,
                    )
                elif n == "adopt":
                    st["pid"] = rec.get("pid")
                elif n == "release":
                    for h in rec.get("hosts") or []:
                        if h in st["alloc"]:
                            st["alloc"].remove(h)
                    if rec.get("source") == "ctl":
                        st["seen_released"] += len(rec.get("hosts") or [])
                elif n == "regrow":
                    st["alloc"].extend(rec.get("hosts") or [])
                    st["requested"] = len(st["alloc"])
                elif n == "host_lost":
                    h = rec.get("host")
                    st["seen_lost"] += 1
                    st["alloc"] = [x for x in st["alloc"] if x != h]
                    if h in self.pool:
                        self.pool[h]["until"] = max(
                            self.pool[h]["until"],
                            float(rec.get("until") or 0.0),
                        )
                elif n == "preempt" and rec.get("target") is not None:
                    st["requested"] = min(
                        st["requested"], int(rec["target"])
                    )
                elif n == "job_done":
                    st.update(state="done" if rec.get("gates") else "failed",
                              alloc=[], exit_code=rec.get("exit_code"),
                              gates_ok=bool(rec.get("gates")))
        # Probe survivors: a recorded pid that still answers (and whose
        # control port still serves, for elastic jobs) is ADOPTED —
        # monitored by pid liveness from here on, judged by its gates.
        for name, st in self.jobs.items():
            if st["state"] != "running":
                continue
            alive = _pid_alive(st["pid"])
            if alive and st["ctl_port"]:
                alive = _http_json(
                    f"http://127.0.0.1:{st['ctl_port']}/fleetctl"
                ) is not None
            st["adopted"] = True
            st["proc"] = None
            if not alive:
                # Died while fleetd was down; the first tick finishes
                # it through the normal path (gates decide).
                st["pid"] = None
        return True

    # -- actions -----------------------------------------------------------
    def _say(self, msg: str) -> None:
        if self.verbose:
            print(f"fleetd: {msg}")

    # -- shared data service -----------------------------------------------
    def _start_data_service(self, recovered: bool) -> None:
        """Bring up (or adopt) the fleet's shared hvt-data dispatcher and
        point every job at it via HVT_DATA_SERVICE.

        The dispatcher address is journaled so a recovered fleetd restarts
        a dead dispatcher on the SAME port — adopted jobs hold that
        address and must be able to re-attach without reconfiguration.
        """
        cfg = self.data_service_cfg
        if cfg is None:
            return
        dsdir = os.path.abspath(
            str(cfg.get("dir") or os.path.join(self.fleet_dir,
                                               "data-service"))
        )
        port = cfg.get("port")
        metrics_port = cfg.get("metrics_port")
        adopted_pid = None
        if recovered:
            for rec in supervisor.journal_records(self.journal_path):
                if rec.get("name") != "data_service":
                    continue
                port = int(rec.get("port") or 0) or port
                metrics_port = (
                    int(rec.get("metrics_port") or 0) or metrics_port
                )
                adopted_pid = None
                if (
                    metrics_port
                    and _pid_alive(rec.get("pid"))
                    and _http_json(
                        f"http://127.0.0.1:{metrics_port}/healthz"
                    ) is not None
                ):
                    adopted_pid = rec.get("pid")
        if port is None:
            port = launcher.pick_free_port()
        if metrics_port is None:
            metrics_port = launcher.pick_free_port()
        self.data_port = int(port)
        self.data_metrics_port = int(metrics_port)
        if adopted_pid is not None:
            self._say(
                f"adopted data service (pid {adopted_pid}, "
                f":{self.data_port})"
            )
        else:
            os.makedirs(dsdir, exist_ok=True)
            self.data_proc = subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.data.service",
                 "serve", "--dir", dsdir,
                 "--port", str(self.data_port),
                 "--metrics-port", str(self.data_metrics_port)],
                start_new_session=True,
            )
            deadline = time.monotonic() + 20.0
            healthy = False
            while time.monotonic() < deadline:
                if _http_json(
                    f"http://127.0.0.1:{self.data_metrics_port}/healthz"
                ) is not None:
                    healthy = True
                    break
                if self.data_proc.poll() is not None:
                    raise RuntimeError(
                        "hvt-data dispatcher exited during startup "
                        f"(code {self.data_proc.returncode})"
                    )
                time.sleep(0.05)
            if not healthy:
                self._stop_data_service()
                raise RuntimeError(
                    "hvt-data dispatcher never became healthy on "
                    f"127.0.0.1:{self.data_metrics_port}"
                )
            self._say(
                f"data service up (pid {self.data_proc.pid}, "
                f":{self.data_port}, journal at {dsdir})"
            )
        self.log.write(
            "data_service", float(self.data_port), port=self.data_port,
            metrics_port=self.data_metrics_port, dir=dsdir,
            pid=(adopted_pid if adopted_pid is not None
                 else self.data_proc.pid),
        )
        addr = f"127.0.0.1:{self.data_port}"
        for st in self.jobs.values():
            e: JobEntry = st["entry"]
            e.env.setdefault("HVT_DATA_SERVICE", addr)
            env = e.spec.setdefault("job", {}).setdefault("env", {})
            env.setdefault("HVT_DATA_SERVICE", addr)

    def _stop_data_service(self) -> None:
        p, self.data_proc = self.data_proc, None
        if p is None or p.poll() is not None:
            return
        try:
            os.killpg(p.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.monotonic() + 5.0
        while p.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        if p.poll() is None:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            p.wait()

    def _data_gates(self) -> bool:
        """Fleet-level metrics_checks, evaluated against the dispatcher's
        final /metrics scrape (dumped for post-mortem). No scrape (no
        dispatcher, or it is down) leaves the dump absent — and an absent
        dump FAILS run_prom_checks, so a configured gate cannot silently
        pass."""
        if not self.fleet_metrics_checks:
            return True
        dump = os.path.join(self.fleet_dir, "data-metrics.prom")
        text = None
        if self.data_metrics_port is not None:
            text = _http_text(
                f"http://127.0.0.1:{self.data_metrics_port}/metrics"
            )
        if text:
            with open(dump, "w") as f:  # hvt: noqa[HVT005] — gate input;
                # a torn dump fails the gate, never corrupts state.
                f.write(text)
        return ci_gate.run_prom_checks(dump, self.fleet_metrics_checks)

    def _place(self, name: str, hosts: list) -> None:
        st = self.jobs[name]
        e: JobEntry = st["entry"]
        ctl_port = launcher.pick_free_port() if e.elastic else None
        status_port = (
            launcher.pick_free_port()
            if (e.elastic or "restart" in (e.spec.get("job") or {}))
            else None
        )
        cfg = {
            "name": name, "spec": e.spec, "hosts": hosts,
            "fleet_dir": self.fleet_dir, "ctl_port": ctl_port,
            "status_port": status_port, "log_path": e.log_path,
        }
        cfg_path = os.path.join(self.fleet_dir, f"job-{name}.json")
        with open(cfg_path, "w") as f:  # hvt: noqa[HVT005] — a relaunch
            # rewrites this config whole; a torn file only fails a
            # placement, never corrupts training state.
            json.dump(cfg, f)
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.launch.fleetd", "_job",
             cfg_path],
            start_new_session=True,  # survives fleetd; killpg tears down
        )
        st.update(
            state="running", alloc=list(hosts), requested=len(hosts),
            proc=proc, pid=proc.pid, ctl_port=ctl_port,
            status_port=status_port, seen_released=0, seen_lost=0,
            adopted=False,
        )
        self.log.write(
            "place", float(len(hosts)), job=name, hosts=hosts,
            pid=proc.pid, ctl_port=ctl_port, status_port=status_port,
            priority=e.priority,
        )
        self._say(f"placed {name} on {hosts} (pid {proc.pid})")

    def _finish_job(self, name: str, code) -> None:
        st = self.jobs[name]
        if st["alloc"]:
            self.log.write(
                "release", float(len(st["alloc"])), job=name,
                hosts=list(st["alloc"]), source="exit",
            )
            st["alloc"] = []
        gates = self._run_gates(name)
        ok = (code in (0, None)) and gates
        st.update(state="done" if ok else "failed", exit_code=code,
                  gates_ok=gates, proc=None, pid=None)
        self.log.write("job_done", 1.0, job=name, exit_code=code,
                       gates=gates)
        self._say(
            f"{name} finished (exit {code}, gates "
            f"{'green' if gates else 'RED'})"
        )

    def _run_gates(self, name: str) -> bool:
        st = self.jobs[name]
        e: JobEntry = st["entry"]
        ok = True
        bad = budget_isolation_violations(name, e.log_path)
        if bad:
            print(f"fleetd: BUDGET ISOLATION VIOLATION — {len(bad)} "
                  f"record(s) in {name}'s journal attributed to another "
                  f"job (first: {bad[0]})")
            ok = False
        jc = e.spec.get("journal_checks") or {}
        if jc:
            ok = ci_gate.run_checks(e.log_path, jc) and ok
        mc = e.spec.get("metrics_checks") or {}
        if mc:
            prom_path = supervisor.default_metrics_dump_path(
                supervisor.default_model_dir(e.env), e.log_path
            )
            ok = ci_gate.run_prom_checks(prom_path, mc) and ok
        checks = e.spec.get("checks") or {}
        if checks:
            metrics_path = e.spec.get("metrics", os.path.join(
                e.env.get("PS_MODEL_PATH", "./models"), "metrics.jsonl"
            ))
            ok = ci_gate.run_checks(metrics_path, checks) and ok
        return ok

    # -- the tick ----------------------------------------------------------
    def _scrape(self, name: str, now: float) -> None:
        st = self.jobs[name]
        if not st["ctl_port"]:
            return
        snap = _http_json(f"http://127.0.0.1:{st['ctl_port']}/fleetctl")
        if snap is not None:
            rel = (snap.get("released") or [])[st["seen_released"]:]
            if rel:
                st["seen_released"] += len(rel)
                for h in rel:
                    if h in st["alloc"]:
                        st["alloc"].remove(h)
                self.log.write("release", float(len(rel)), job=name,
                               hosts=rel, source="ctl")
                self._say(f"{name} released {rel}")
            for h in (snap.get("lost_hosts") or [])[st["seen_lost"]:]:
                st["seen_lost"] += 1
                until = now + self.quarantine_s
                if h in self.pool:
                    self.pool[h]["until"] = max(
                        self.pool[h]["until"], until
                    )
                st["alloc"] = [x for x in st["alloc"] if x != h]
                self.log.write("host_lost", 1.0, job=name, host=h,
                               until=until)
                self._say(
                    f"host {h} LOST under {name} — quarantined "
                    f"{self.quarantine_s:.0f}s"
                )
        if st["status_port"]:
            text = _http_text(
                f"http://127.0.0.1:{st['status_port']}/metrics"
            )
            if text:
                try:
                    values = obs_prom.parse_text(text)
                except ValueError:
                    return
                remaining = values.get("hvt_restart_budget_remaining")
                if remaining is not None and remaining != st["budget"]:
                    st["budget"] = remaining
                    self.log.write("job_budget", remaining, job=name,
                                   remaining=remaining)

    def _sched_view(self, now: float) -> list:
        view = []
        for name, st in sorted(self.jobs.items()):
            e: JobEntry = st["entry"]
            view.append({
                "name": name, "priority": e.priority,
                "state": st["state"],
                "arrival": (self.start_wall or now) + e.delay_s,
                "alloc": list(st["alloc"]), "min": e.min_units,
                "target": e.target_units, "requested": st["requested"],
                "preemptible": e.elastic,
            })
        return view

    def _tick(self) -> None:
        now = time.time()
        # 1. reap owned children / probe adopted survivors
        for name, st in self.jobs.items():
            if st["state"] != "running":
                continue
            if st["proc"] is not None:
                code = st["proc"].poll()
                if code is not None:
                    self._finish_job(name, code)
            elif not _pid_alive(st["pid"]):
                self._finish_job(name, None)
        # 2. scrape controller ledgers + budget gauges
        for name, st in self.jobs.items():
            if st["state"] == "running":
                self._scrape(name, now)
        # 3. schedule + act
        for act in schedule(self._sched_view(now), self.pool, now):
            name = act["job"]
            st = self.jobs[name]
            if act["op"] == "place":
                self._place(name, act["hosts"])
            elif act["op"] == "grow":
                if st["ctl_port"] and _http_post(
                    f"http://127.0.0.1:{st['ctl_port']}/grow",
                    {"hosts": act["hosts"]},
                ):
                    st["alloc"].extend(act["hosts"])
                    st["requested"] = len(st["alloc"])
                    self.log.write(
                        "regrow", float(len(act["hosts"])), job=name,
                        hosts=act["hosts"],
                    )
                    self._say(f"regrew {name} with {act['hosts']}")
            elif act["op"] == "shrink":
                if act["target"] < st["requested"] and st["ctl_port"]:
                    if _http_post(
                        f"http://127.0.0.1:{st['ctl_port']}/shrink",
                        {"target": act["target"]},
                    ):
                        st["requested"] = act["target"]
                        self.log.write(
                            "preempt", 1.0, victim=name, job=name,
                            target=act["target"], **{"for": act["for"]},
                        )
                        self._say(
                            f"preempting {name} -> {act['target']} "
                            f"unit(s) for {act['for']}"
                        )

    def snapshot(self) -> dict:
        now = time.time()
        return {
            "start": self.start_wall,
            "jobs": {
                name: {
                    "state": st["state"], "priority":
                        st["entry"].priority,
                    "alloc": list(st["alloc"]),
                    "target": st["entry"].target_units,
                    "min": st["entry"].min_units,
                    "pid": st["pid"], "adopted": st["adopted"],
                    "budget_remaining": st["budget"],
                    "exit_code": st["exit_code"],
                    "gates_ok": st["gates_ok"],
                }
                for name, st in sorted(self.jobs.items())
            },
            "hosts": {
                h: {
                    "slots": p["slots"],
                    "state": "quarantined" if p["until"] > now else "up",
                    "until": p["until"] or None,
                }
                for h, p in sorted(self.pool.items())
            },
            "journal": self.journal_path,
        }

    def _start_status_server(self, port: int):
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        fleetd = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

            def do_GET(self):
                try:
                    if self.path == "/fleetd":
                        self._send(200, fleetd.snapshot())
                    elif self.path == "/metrics":
                        obs_prom.write_http(self, fleetd_metrics(
                            fleetd.journal_path, fleetd.jobs, fleetd.pool,
                        ))
                    elif self.path == "/healthz":
                        self._send(200, {"status": "ok"})
                    else:
                        self._send(404, {"error": f"no route {self.path}"})
                except Exception as e:
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

        server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server

    def _teardown_children(self) -> None:
        """Abnormal-exit cleanup of OWNED children only: adopted jobs
        were deliberately left running across one fleetd death already —
        a second fleetd death leaves them for the next recovery too."""
        for st in self.jobs.values():
            if st["proc"] is None or st["proc"].poll() is not None:
                continue
            try:
                os.killpg(st["proc"].pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                continue
        deadline = time.monotonic() + 10.0
        for st in self.jobs.values():
            p = st["proc"]
            if p is None:
                continue
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                p.wait()

    def run(self) -> int:
        os.makedirs(self.fleet_dir, exist_ok=True)
        recovered = self._maybe_recover()
        self.log = supervisor.RestartLog(self.journal_path,
                                         max_lines=1_000_000)
        self.log.touch()
        if recovered:
            self._say(f"recovered from {self.journal_path}")
            for name, st in self.jobs.items():
                if st["state"] == "running" and st["pid"]:
                    self.log.write("adopt", 1.0, job=name, pid=st["pid"])
                    self._say(f"adopted {name} (pid {st['pid']})")
        else:
            self.start_wall = time.time()
            self.log.write(
                "fleet_start", 1.0, start=self.start_wall,
                pool={h: p["slots"] for h, p in self.pool.items()},
                jobs=sorted(self.jobs),
            )
        self._start_data_service(recovered)
        server = (
            self._start_status_server(int(self.status_port))
            if self.status_port is not None else None
        )
        try:
            while any(st["state"] in ("pending", "running")
                      for st in self.jobs.values()):
                self._tick()
                time.sleep(self.tick_s)
            ok = all(st["state"] == "done" for st in self.jobs.values())
            if self.fleet_checks:
                ok = ci_gate.run_checks(
                    self.journal_path, self.fleet_checks
                ) and ok
            # Scrape + gate the shared dispatcher while it is still up,
            # THEN retire it.
            ok = self._data_gates() and ok
            self._stop_data_service()
            self.log.write("fleet_done", 1.0, ok=ok)
            self._say(f"fleet done ({'all green' if ok else 'FAILED'})")
            return 0 if ok else 1
        except BaseException:
            self._teardown_children()
            raise
        finally:
            self._stop_data_service()
            if server is not None:
                server.shutdown()


def run_fleet(spec_path: str, status_port: int | None = None) -> int:
    import yaml

    with open(spec_path) as f:
        spec = yaml.safe_load(f)
    try:
        fleetd = Fleetd(spec, status_port=status_port)
    except ValueError as e:
        print(f"{spec_path}: {e}")
        return 1
    return fleetd.run()


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "_job":
        return _job_main(argv[1])
    import argparse

    ap = argparse.ArgumentParser(
        prog="hvt-launch fleet",
        description="Run N job specs over a shared host pool: priority "
        "placement, preemption-as-elastic-shrink, per-job restart-budget "
        "isolation, host quarantine, journal-recoverable.",
    )
    ap.add_argument("spec", help="fleet spec YAML (fleet: pool + jobs:)")
    ap.add_argument("--status-port", type=int, default=None,
                    help="serve GET /fleetd + /metrics on this port")
    args = ap.parse_args(argv)
    return run_fleet(args.spec, status_port=args.status_port)


if __name__ == "__main__":
    raise SystemExit(main())
