"""The canonical compiled-trainer-step probe `hvt-audit step` and the
HLO tests share.

Auditing a compiled step needs three things the test files used to
duplicate: a tiny deterministic model, the [K, G, ...] microbatch-stack
feeding contract, and the ``.lower().as_text()`` plumbing around
``Trainer._train_step``. This module owns all three, so the auditor can
run standalone against any jitted step and the tests stop carrying
private copies. Structure is what's audited — the model is deliberately
small (the invariants under test are per-BUCKET and per-STEP, not
per-FLOP).

This is the only analysis module that imports jax (lazily, inside the
functions): `hlo_audit` stays importable without an accelerator stack.
"""

from __future__ import annotations

__all__ = [
    "build_trainer",
    "canonical_step_text",
    "lowered_moe_dispatch_text",
    "lowered_step_text",
    "probe_data",
    "probe_model",
]


def probe_model():
    """The canonical audit model: a 2-layer MLP over flattened input —
    small enough that the default 64 MB bucket holds every gradient
    (one bucket -> the one-reduction invariant reads exactly 1)."""
    import flax.linen as nn
    import jax.numpy as jnp

    class Probe(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
            return nn.Dense(10)(nn.relu(nn.Dense(32)(x)))

    return Probe()


def probe_data(n: int = 64, seed: int = 0):
    import numpy as np

    rng = np.random.RandomState(seed)
    x = rng.rand(n, 8, 8, 1).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.int32)
    return x, y


def build_trainer(k: int = 1, compression: str = "none", *,
                  compression_ici: str = "none",
                  overlap=None, bucket_bytes=None, bucket_order=None,
                  error_feedback: bool = True, model=None, seed: int = 3,
                  zero1: bool = False):
    """A `Trainer` wired exactly like the perf-path tests wire theirs:
    accumulation factor ``k``, wire ``compression`` (plus the ICI-hop
    ``compression_ici``, audit-relevant only under a dcn > 1 factoring
    — set HVT_DCN_FACTOR to fake one), optional overlap/bucket knob
    overrides (None = the env-driven defaults). ``zero1`` turns on the
    sharded weight update (``Trainer(shard_update=True)``) — the
    composed ZeRO-1 x accumulation x compression step
    `hvt-audit step --zero1` gates."""
    import optax

    import horovod_tpu as hvt

    tx = hvt.DistributedOptimizer(
        optax.adam(1e-3), backward_passes_per_step=k,
        average_aggregated_gradients=True, compression=compression,
        compression_ici=compression_ici, error_feedback=error_feedback,
    )
    return hvt.Trainer(
        model if model is not None else probe_model(), tx, seed=seed,
        bucket_bytes=bucket_bytes, overlap_reduction=overlap,
        bucket_order=bucket_order, shard_update=zero1,
    )


def lowered_step_text(tr, x, y, k: int, *, micro: int = 8,
                      n: int = 32) -> str:
    """The lowered (StableHLO) text of one compiled optimizer step, fed
    a [K, G, ...] microbatch stack when ``k > 1`` — the single
    implementation of the plumbing the HLO assertions run against."""
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.parallel import sharding as sharding_lib

    state = tr.build(x[: tr.dp_size])
    if k == 1:
        batch = tr._shard((x[:n], y[:n]))
    else:
        batch = tr._shard_chunk(
            (
                np.stack([x[i * micro: (i + 1) * micro] for i in range(k)]),
                np.stack([y[i * micro: (i + 1) * micro] for i in range(k)]),
            ),
            1,
        )
    acc = sharding_lib.replicate(tr.zero_metrics(), tr.mesh)
    return tr._train_step.lower(
        state, batch, jnp.asarray(1.0, jnp.float32), acc
    ).as_text()


def lowered_moe_dispatch_text(d_model: int = 8, capacity: int = 4) -> str:
    """Lowered StableHLO of the canonical EP dispatch/combine probe —
    the MoE wire shape `hvt-audit moe --expect alltoalls=2` gates.

    A shard_map over an ``expert`` axis spanning every local device
    moves each group's routed activations to the expert shards that own
    them (`collectives.all_to_all`, the HVT011 entry point), runs the
    expert FFN stand-in, and combines them back with the mirror
    all-to-all — exactly TWO payload (rank >= 2) all-to-alls, no
    full-payload all-reduce anywhere. The probe is structural like
    `probe_model`: what's audited is the wire shape, not the routing
    math (`models/moe.py` owns that). Requires `horovod_tpu.init()`."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import compat
    from horovod_tpu.parallel import collectives

    devices = jax.devices()
    e = len(devices)
    mesh = jax.sharding.Mesh(np.asarray(devices), ("expert",))

    def stage(x):
        # x: this shard's [E, C, D] dispatch block — row i holds the
        # tokens this shard routed to expert i.
        dispatched = collectives.all_to_all(
            x, "expert", split_axis=0, concat_axis=0, tiled=True
        )
        h = jnp.tanh(dispatched)  # the expert FFN stand-in
        return collectives.all_to_all(
            h, "expert", split_axis=0, concat_axis=0, tiled=True
        )

    fn = compat.shard_map(
        stage, mesh=mesh, in_specs=(P("expert"),), out_specs=P("expert")
    )
    x = jnp.zeros((e * e, capacity, d_model), jnp.float32)
    return jax.jit(fn).lower(x).as_text()


def canonical_step_text(k: int = 4, compression: str = "none", *,
                        overlap=None, bucket_bytes=None) -> str:
    """One call from config to auditable text — `hvt-audit step`'s
    workhorse. Requires `horovod_tpu.init()` to have run."""
    x, y = probe_data()
    tr = build_trainer(
        k, compression, overlap=overlap, bucket_bytes=bucket_bytes,
    )
    return lowered_step_text(tr, x, y, k)
