"""`hvt-trace` — the fleet timeline CLI (``HOROVOD_TIMELINE`` parity,
arXiv:1802.05799): merge every rank's ``HVT_TRACE_DIR`` span stream onto
one aligned clock and either export it for Perfetto/``chrome://tracing``
or interrogate it for stragglers.

Usage::

    # One Chrome trace-event JSON for the whole fleet (pid = rank,
    # tid = span depth; flight-recorded collective submissions as
    # instant events when flight-*.jsonl files sit in the same dir):
    hvt-trace timeline /path/to/trace-dir -o trace.json

    # Per-phase per-rank duration tables at the terminal:
    hvt-trace report /path/to/trace-dir

    # Cross-rank skew: straggler score, barrier-wait attribution, and a
    # named straggler with evidence. --expect-straggler N gates CI runs
    # with an injected `slow:MS` fault (testing/faults.py):
    hvt-trace skew /path/to/trace-dir
    hvt-trace skew /path/to/trace-dir --threshold-pct 5 \\
        --expect-straggler 1

Exit codes (the `hvt-lint`/`hvt-audit`/`hvt-sched` contract):

* ``0`` — merged/reported (skew: and any ``--expect-straggler`` gate
  passed);
* ``1`` — the ``--expect-straggler`` gate missed (no straggler named,
  or a different rank);
* ``2`` — usage error / refusal: no span files, or a host whose clock
  shares no step anchors with the reference (`timeline.TimelineError`
  — an unalignable dir must not silently export a fabricated order).
"""

from __future__ import annotations

import argparse
import json
import sys

from horovod_tpu.obs import timeline


def _load(trace_dir: str):
    by_rank = timeline.load_spans(trace_dir)
    alignment = timeline.align(by_rank)
    return by_rank, alignment


def _run_timeline(args) -> int:
    by_rank, alignment = _load(args.dir)
    flight = timeline.load_flight(args.dir)
    doc = timeline.chrome_trace(by_rank, alignment, flight)
    with open(args.output, "w") as f:  # hvt: noqa[HVT005] — derived,
        # regenerable analysis output, not a durability artifact
        json.dump(doc, f)
    n_flight = sum(len(v) for v in flight.values())
    print(
        f"hvt-trace: merged {len(by_rank)} rank(s), "
        f"{sum(len(v) for v in by_rank.values())} span(s)"
        + (f", {n_flight} collective submission(s)" if n_flight else "")
        + f" -> {args.output}"
    )
    for host in sorted(alignment.residual_ms):
        print(
            f"  clock {host!r}: offset applied, residual "
            f"{alignment.residual_ms[host]:.3f} ms over "
            f"{alignment.anchor_counts.get(host, 0)} anchor(s)"
        )
    print("  load in https://ui.perfetto.dev or chrome://tracing")
    return 0


def _run_report(args) -> int:
    # Per-rank duration aggregates need no merged ordering — no align()
    # here, so a dir whose hosts share no anchors (refused by
    # timeline/skew) still gets its tables.
    by_rank = timeline.load_spans(args.dir)
    print(render_banner(by_rank))
    print(timeline.render_report(by_rank))
    return 0


def _run_skew(args) -> int:
    by_rank, alignment = _load(args.dir)
    report = timeline.skew(
        by_rank, alignment, threshold_pct=args.threshold_pct
    )
    print(render_banner(by_rank))
    print(timeline.render_skew(report))
    if args.expect_straggler is not None:
        if report["straggler"] != args.expect_straggler:
            print(
                f"hvt-trace: FAIL — expected straggler rank "
                f"{args.expect_straggler}, detected "
                f"{report['straggler']}",
                file=sys.stderr,
            )
            return 1
        print(
            f"hvt-trace: straggler gate passed (rank "
            f"{args.expect_straggler})"
        )
    return 0


def render_banner(by_rank: dict) -> str:
    return (
        f"trace: {len(by_rank)} rank(s) "
        f"({', '.join(f'rank{r}' for r in sorted(by_rank))})"
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvt-trace",
        description=(
            "Cross-rank span timeline: merge HVT_TRACE_DIR span files "
            "onto one aligned clock; export Chrome trace JSON, print "
            "per-phase tables, or detect stragglers."
        ),
    )
    sub = p.add_subparsers(dest="command", required=True)
    t = sub.add_parser(
        "timeline", help="export a merged Chrome trace-event JSON"
    )
    t.add_argument("dir", help="the HVT_TRACE_DIR of the run")
    t.add_argument(
        "-o", "--output", default="trace.json",
        help="output path (default: trace.json)",
    )
    t.set_defaults(fn=_run_timeline)
    r = sub.add_parser(
        "report", help="per-phase per-rank duration tables"
    )
    r.add_argument("dir", help="the HVT_TRACE_DIR of the run")
    r.set_defaults(fn=_run_report)
    s = sub.add_parser(
        "skew", help="cross-rank skew + straggler attribution"
    )
    s.add_argument("dir", help="the HVT_TRACE_DIR of the run")
    s.add_argument(
        "--threshold-pct", type=float, default=5.0,
        help="straggler margin as %% of the fleet step period (default 5)",
    )
    s.add_argument(
        "--expect-straggler", type=int, default=None, metavar="RANK",
        help="exit 1 unless exactly this rank is named the straggler",
    )
    s.set_defaults(fn=_run_skew)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except timeline.TimelineError as e:
        print(f"hvt-trace: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"hvt-trace: {e}", file=sys.stderr)
        return 2


def cli() -> None:
    """Console entry point (`hvt-trace`, pyproject.toml)."""
    raise SystemExit(main())


if __name__ == "__main__":
    raise SystemExit(main())
