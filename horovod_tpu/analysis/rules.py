"""The distributed-correctness rules `hvt-lint` ships.

Each rule encodes an invariant this repo has actually been bitten by (or
designed around, loudly, in CHANGES.md/docstrings) — not generic style:

* HVT001 — collective symmetry: a collective/barrier reached only under
  rank-conditional control flow is the classic Horovod hang class
  (arXiv:1802.05799): the gated ranks never enter, the rest block
  forever (or the coordination service SIGABRTs them).
* HVT002 — teardown discipline: `jax.distributed.shutdown` is a BARRIER
  on this stack; one-sided teardown kills survivors (PR 2). Only the
  sanctioned runtime/elastic boundary modules may touch it directly.
* HVT003 — tracing hazards: host side effects inside jit/scan/shard_map
  functions execute once at trace time (or diverge per-rank) — the
  silent-divergence class.
* HVT004 — env-knob registry: every ``HVT_*`` knob must be declared in
  `analysis/registry.py`, and inline ``os.environ`` reads must go
  through the typed accessors.
* HVT005 — checkpoint-write atomicity: artifact writes go through
  `checkpoint._atomic_write` (atomic rename + ``.sha256`` sidecar); a
  bare truncating ``open`` can tear under crash/preemption (PR 3).
* HVT006 — data-layer determinism: unseeded host RNG inside
  ``horovod_tpu/data/`` breaks the durable-stream-cursor contract
  (every feeding path's order must be a pure function of
  ``(seed, epoch, pass)`` — `data.stream`); a global-RNG draw or a
  seedless generator makes resumed byte streams irreproducible.

Heuristics are lexical by design (no dataflow): a collective gated by an
early ``return`` under a rank check, or a rank value laundered through a
local variable, is NOT caught. The rules catch the shapes that actually
appear; the suppressions (``# hvt: noqa[RULE]``, baseline) keep the
false-positive cost at zero.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from horovod_tpu.analysis import registry
from horovod_tpu.analysis.core import (
    Finding,
    ModuleSource,
    Rule,
    dotted_name,
    register_rule,
    resolved_dotted,
    terminal_name,
)

# --- shared: rank-condition detection ---------------------------------------

# Topology queries whose result gates single-writer code paths. Both the
# call forms (`runtime.rank()`, `jax.process_index()`, `hvt.is_primary()`)
# and the attribute forms (`world.process_rank`) count.
_RANK_CALLS = {"rank", "process_rank", "process_index", "local_rank",
               "is_primary"}
_RANK_ATTRS = {"process_rank", "process_index", "local_rank", "is_primary"}


def _is_rank_gated(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            name = terminal_name(node.func)
            if name in _RANK_CALLS:
                return True
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            if node.attr in _RANK_ATTRS:
                return True
    return False


# --- HVT001 -----------------------------------------------------------------

# Collective/barrier operations that every rank of the world must issue
# together, matched by terminal callee name regardless of qualification.
_COLLECTIVES_ANY = {
    "psum", "psum_scatter", "pmean", "hierarchical_psum",
    "allreduce", "allgather", "all_gather", "broadcast",
    "broadcast_object", "allgather_object", "broadcast_pytree",
    "pmean_pytree", "reduce_gradients", "barrier", "wait_at_barrier",
    "sync_global_devices",
}
# Operations matched only when qualified, to dodge same-name methods on
# unrelated objects (`httpd.shutdown()`, `os.sync()`):
#   runtime.shutdown / runtime.reinit (also bare, via the import map) are
#   world-teardown barriers; `<...>.state.sync` / `ElasticState.sync` is
#   the elastic state collective.
_QUALIFIED = {
    "shutdown": {"runtime", "hvt", "horovod_tpu"},
    "reinit": {"runtime", "hvt", "horovod_tpu"},
    "sync": {"state", "elastic_state", "ElasticState"},
}


def _collective_name(module: ModuleSource, call: ast.Call) -> str | None:
    name = terminal_name(call.func)
    if name is None:
        return None
    if name in _COLLECTIVES_ANY:
        return dotted_name(call.func) or name
    if name in _QUALIFIED:
        resolved = resolved_dotted(module, call.func) or name
        segments = resolved.split(".")
        if len(segments) == 1 or segments[-2] in _QUALIFIED[name]:
            return dotted_name(call.func) or name
    return None


@register_rule
class CollectiveSymmetry(Rule):
    rule_id = "HVT001"
    title = "collective reachable only under rank-conditional control flow"

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        findings: list[Finding] = []

        def visit(node: ast.AST, gate: tuple[int, str] | None):
            if isinstance(node, ast.Call):
                name = _collective_name(module, node)
                if name is not None and gate is not None:
                    line, cond = gate
                    findings.append(module.finding(
                        self.rule_id, node,
                        f"collective/barrier `{name}` is reached only "
                        f"under rank-conditional control flow (gated at "
                        f"line {line}: `{cond}`) — ranks outside the "
                        "branch never issue it, and the others hang in "
                        "it (the Horovod one-sided-collective class); "
                        "hoist the collective out of the rank gate",
                    ))
                for child in ast.iter_child_nodes(node):
                    visit(child, gate)
                return
            if isinstance(node, (ast.If, ast.While)):
                branch_gate = gate
                if _is_rank_gated(node.test):
                    branch_gate = (node.lineno, module.line_at(node.lineno))
                visit(node.test, gate)
                for child in node.body:
                    visit(child, branch_gate)
                for child in node.orelse:
                    visit(child, branch_gate)
                return
            if isinstance(node, ast.IfExp):
                branch_gate = gate
                if _is_rank_gated(node.test):
                    branch_gate = (node.lineno, module.line_at(node.lineno))
                visit(node.test, gate)
                visit(node.body, branch_gate)
                visit(node.orelse, branch_gate)
                return
            if isinstance(node, ast.BoolOp):
                # `rank() == 0 and collective()`: operands after a
                # rank-gated one are short-circuit-conditional on it.
                seen_gate = gate
                for value in node.values:
                    visit(value, seen_gate)
                    if seen_gate is None and _is_rank_gated(value):
                        seen_gate = (
                            node.lineno, module.line_at(node.lineno)
                        )
                return
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef),
            ):
                # New execution scope: a def/lambda under a rank gate is
                # conditionally DEFINED, not conditionally executed —
                # tracking call sites needs dataflow this linter
                # deliberately doesn't do.
                for child in ast.iter_child_nodes(node):
                    visit(child, None)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, gate)

        visit(module.tree, None)
        return iter(findings)


# --- HVT002 -----------------------------------------------------------------

# The only modules allowed to touch the raw teardown primitives: the
# runtime owns the shutdown barrier, compat implements it, and the two
# elastic modules run the sanctioned `_teardown_and_interrupt` /
# `ensure_world` boundaries where lockstep is guaranteed by the
# membership agreement.
_SANCTIONED_TEARDOWN_MODULES = (
    "horovod_tpu/runtime.py",
    "horovod_tpu/compat.py",
    "horovod_tpu/elastic/rescale.py",
    "horovod_tpu/elastic/state.py",
)


@register_rule
class TeardownDiscipline(Rule):
    rule_id = "HVT002"
    title = "raw distributed teardown outside the sanctioned boundary"

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        if module.relpath in _SANCTIONED_TEARDOWN_MODULES:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = resolved_dotted(module, node.func)
            if resolved is None:
                continue
            if resolved.endswith("jax.distributed.shutdown"):
                target = "jax.distributed.shutdown"
            elif resolved.split(".")[-1] == "clear_backends":
                target = resolved
            else:
                continue
            yield module.finding(
                self.rule_id, node,
                f"direct `{target}` call — the distributed teardown is a "
                "BARRIER (one-sided teardown SIGABRTs the survivors); "
                "call `runtime.shutdown()`/`runtime.reinit()` or go "
                "through the elastic membership boundary "
                "(`_teardown_and_interrupt`), which guarantee lockstep",
            )


# --- HVT003 -----------------------------------------------------------------

_TRACE_WRAPPERS = {"jit", "pjit", "shard_map"}


def _decorator_traces(dec: ast.AST) -> bool:
    for node in ast.walk(dec):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if terminal_name(node) in _TRACE_WRAPPERS:
                return True
    return False


def _collect_traced_roots(module: ModuleSource) -> list[ast.AST]:
    """Function bodies that run under a jax trace: defs decorated with
    jit/pjit/shard_map (incl. through `partial`), and functions/lambdas
    handed to `jax.jit(f)` / `shard_map(f, ...)` / `lax.scan(f, ...)`."""
    defs_by_name: dict[str, ast.AST] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name[node.name] = node

    roots: list[ast.AST] = []
    seen: set[int] = set()

    def add(node: ast.AST):
        if id(node) not in seen:
            seen.add(id(node))
            roots.append(node)

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_traces(d) for d in node.decorator_list):
                add(node)
        elif isinstance(node, ast.Call):
            name = terminal_name(node.func)
            is_wrapper = name in _TRACE_WRAPPERS
            if not is_wrapper and name == "scan":
                resolved = resolved_dotted(module, node.func) or ""
                is_wrapper = resolved.endswith("lax.scan")
            if not is_wrapper or not node.args:
                continue
            fn = node.args[0]
            if isinstance(fn, ast.Lambda):
                add(fn)
            elif isinstance(fn, ast.Name) and fn.id in defs_by_name:
                add(defs_by_name[fn.id])
    return roots


@register_rule
class TracingHazards(Rule):
    rule_id = "HVT003"
    title = "host side effect inside a traced (jit/scan/shard_map) function"

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        reported: set[tuple[int, int]] = set()
        for root in _collect_traced_roots(module):
            body = root.body if isinstance(root.body, list) else [root.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    finding = self._hazard(module, node)
                    if finding and (finding.line, finding.col) not in reported:
                        reported.add((finding.line, finding.col))
                        yield finding

    def _hazard(self, module: ModuleSource, node: ast.AST) -> Finding | None:
        if isinstance(node, ast.Call):
            resolved = resolved_dotted(module, node.func)
            if resolved is not None:
                if resolved.startswith("time."):
                    return module.finding(
                        self.rule_id, node,
                        f"`{resolved}` inside a traced function reads the "
                        "host clock ONCE at trace time (a constant "
                        "thereafter) — and any rank-varying value "
                        "silently diverges the compiled program; compute "
                        "timestamps outside the traced region",
                    )
                if resolved.startswith(("random.", "numpy.random.")):
                    return module.finding(
                        self.rule_id, node,
                        f"seed-free `{resolved}` inside a traced function "
                        "draws per-rank host randomness at trace time — "
                        "the silent-divergence class; thread a "
                        "`jax.random` key through the function instead",
                    )
                if resolved == "os.getenv":
                    return module.finding(
                        self.rule_id, node,
                        "`os.getenv` inside a traced function is read "
                        "once at trace time and may differ across ranks; "
                        "resolve knobs outside the traced region",
                    )
            if isinstance(node.func, ast.Name) and node.func.id in (
                "print", "open", "input"
            ):
                return module.finding(
                    self.rule_id, node,
                    f"host side effect `{node.func.id}(...)` inside a "
                    "traced function runs at TRACE time, not per step — "
                    "use `jax.debug.print`/`io_callback`, or hoist it "
                    "out of the traced region",
                )
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            if (
                node.attr == "environ"
                and resolved_dotted(module, node) == "os.environ"
            ):
                return module.finding(
                    self.rule_id, node,
                    "`os.environ` read inside a traced function is "
                    "evaluated once at trace time and may differ across "
                    "ranks; resolve knobs outside the traced region",
                )
        return None


# --- HVT004 -----------------------------------------------------------------

_KNOB_RE = re.compile(r"^HVT_[A-Z0-9_]+$")


@register_rule
class EnvKnobRegistry(Rule):
    rule_id = "HVT004"
    title = "HVT_* env knob not declared in analysis/registry.py"

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                if _KNOB_RE.match(node.value) and not registry.is_registered(
                    node.value
                ):
                    yield module.finding(
                        self.rule_id, node,
                        f"`{node.value}` is not declared in "
                        "horovod_tpu/analysis/registry.py — add a Knob "
                        "row (type, default, subsystem, description) and "
                        "regenerate docs/ENVVARS.md, so the knob surface "
                        "can't drift",
                    )
            elif isinstance(node, ast.Call):
                key = self._env_read_key(module, node)
                if key is not None:
                    yield module.finding(
                        self.rule_id, node,
                        f"inline `os.environ` read of `{key}` — go "
                        "through the typed registry accessors "
                        "(`horovod_tpu.analysis.registry.get_*`), which "
                        "carry the declared default and the "
                        "empty-string-is-unset contract",
                    )
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                if (
                    resolved_dotted(module, node.value) == "os.environ"
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and _KNOB_RE.match(node.slice.value)
                ):
                    yield module.finding(
                        self.rule_id, node,
                        f"inline `os.environ[{node.slice.value!r}]` read "
                        "— go through the typed registry accessors "
                        "(`horovod_tpu.analysis.registry.get_*`)",
                    )

    @staticmethod
    def _env_read_key(module: ModuleSource, call: ast.Call) -> str | None:
        resolved = resolved_dotted(module, call.func)
        if resolved not in ("os.environ.get", "os.getenv"):
            return None
        if not call.args:
            return None
        key = call.args[0]
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            if _KNOB_RE.match(key.value):
                return key.value
        return None


# --- HVT005 -----------------------------------------------------------------

# The one function allowed to open artifact files for writing: it owns the
# tmp-name + os.replace + .sha256-sidecar discipline every checkpoint
# consumer (discovery, restore, elastic reassembly) verifies against.
_SANCTIONED_WRITERS = {"_atomic_write"}

_WRITE_MODES = ("w", "x", "+")


@register_rule
class CheckpointWriteAtomicity(Rule):
    rule_id = "HVT005"
    title = "truncating file write outside the atomic-write helper"

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        for writer, node in self._truncating_opens(module.tree):
            if writer in _SANCTIONED_WRITERS:
                continue
            yield module.finding(
                self.rule_id, node,
                "truncating `open(..., 'w')` outside "
                "`checkpoint._atomic_write` — a crash/preemption "
                "mid-write tears the file, and checkpoint artifacts "
                "additionally need the `.sha256` sidecar that discovery "
                "and restore verify; route artifact writes through "
                "`checkpoint._atomic_write`/`save*` (non-artifact "
                "writes: suppress with `# hvt: noqa[HVT005]` and say "
                "why)",
            )

    @staticmethod
    def _truncating_opens(tree: ast.AST):
        """(enclosing function name, call node) for each truncating open."""

        def walk(node: ast.AST, fn_name: str | None):
            for child in ast.iter_child_nodes(node):
                child_fn = fn_name
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    child_fn = child.name
                if isinstance(child, ast.Call) and isinstance(
                    child.func, ast.Name
                ) and child.func.id == "open":
                    mode = None
                    if len(child.args) >= 2:
                        mode = child.args[1]
                    for kw in child.keywords:
                        if kw.arg == "mode":
                            mode = kw.value
                    if (
                        isinstance(mode, ast.Constant)
                        and isinstance(mode.value, str)
                        and any(c in mode.value for c in _WRITE_MODES)
                    ):
                        yield (fn_name, child)
                yield from walk(child, child_fn)

        yield from walk(tree, None)


# --- HVT006 -----------------------------------------------------------------

# The data layer the durable-stream-cursor contract covers: every feeding
# path here must derive its order purely from (seed, epoch, pass).
_DATA_LAYER_PREFIX = "horovod_tpu/data/"

# Draw/mutate functions on the GLOBAL numpy/stdlib RNGs — process-state-
# dependent, hence irreproducible across a resume.
_GLOBAL_RNG_FNS = {
    "rand", "randn", "randint", "random", "random_sample", "ranf",
    "sample", "choice", "shuffle", "permutation", "uniform", "normal",
    "standard_normal", "randrange", "getrandbits", "bytes", "seed",
}

# Generator constructors that MUST carry an explicit seed argument.
_SEEDED_CTORS = {"RandomState", "default_rng", "Generator", "Random",
                 "SeedSequence", "PCG64", "Philox"}


@register_rule
class DataLayerSeededRng(Rule):
    rule_id = "HVT006"
    title = "unseeded RNG in the data layer (durable-cursor determinism)"

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        if not module.relpath.startswith(_DATA_LAYER_PREFIX):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = resolved_dotted(module, node.func)
            if resolved is None:
                continue
            tail = resolved.split(".")[-1]
            on_np_random = resolved.startswith(
                ("numpy.random.", "np.random.")
            )
            on_stdlib_random = (
                resolved.startswith("random.")
                and resolved.count(".") == 1
            )
            if tail in _GLOBAL_RNG_FNS and (
                on_np_random or on_stdlib_random
            ):
                yield module.finding(
                    self.rule_id, node,
                    f"`{resolved}` draws from the GLOBAL RNG: the order "
                    "it produces depends on process history, so a "
                    "resumed stream cannot reproduce it — the durable-"
                    "cursor byte-identity contract (data/stream.py) "
                    "requires every data-layer draw to come from a "
                    "generator seeded purely by (seed, epoch, pass); "
                    "use np.random.RandomState(stream.epoch_seed(...))",
                )
            elif tail in _SEEDED_CTORS and (
                on_np_random or resolved == "random.Random"
            ):
                has_seed = bool(node.args) or any(
                    kw.arg in ("seed", "entropy") for kw in node.keywords
                )
                if not has_seed:
                    yield module.finding(
                        self.rule_id, node,
                        f"`{resolved}()` without an explicit seed draws "
                        "OS entropy — the stream it feeds is "
                        "irreproducible on resume; pass a seed derived "
                        "from (seed, epoch, pass) (`stream.epoch_seed`)",
                    )
